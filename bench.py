"""Headline benchmark: train-step throughput / MFU on one TPU chip.

Measures the end-to-end jitted training step (fwd + bwd + adamw update,
remat on, bf16 compute, donated buffers) of the Llama-1B config at
batch 2 x seq 2048 and reports tokens/sec/chip and model FLOPs
utilization against the v5e peak; also runs a Mixtral-style sparse-MoE
config (top-2 of 8 experts) and reports its MFU over *active* FLOPs.

Batch is 2 because the 1B model's bf16 params + adamw moments + grads
leave room for exactly two 2048-token activations sets on a 16 GiB
chip even with buffer donation and full remat (b4 fits but is slower;
b1 under-utilizes the MXU).

BASELINE.md north star: Llama finetune >=40% MFU. vs_baseline is
MFU / 0.40 (>1.0 beats the target).

Prints exactly one JSON line; the MoE numbers ride in "extra".
"""
from __future__ import annotations

import json
import os
import sys
import time
from functools import partial


def flops_per_token(n_params: float, cfg, seq_len: int) -> float:
    """6N matmul flops/token + attention score flops
    (12 * L * T * hidden per token, fwd+bwd)."""
    return 6.0 * n_params + 12.0 * cfg.num_layers * seq_len * cfg.hidden_size


def bench_model(model, cfg, n_params, batch, seq, steps, peak_flops,
                chunked_loss: bool = False):
    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import causal_lm_loss, chunked_causal_lm_loss

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    targets = jnp.roll(ids, -1, axis=1)

    params = jax.jit(model.init)(jax.random.PRNGKey(0), ids[:1, :8])
    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, mu_dtype=jnp.bfloat16)
    opt_state = tx.init(params)

    # Donate params + opt_state: the step consumes the old buffers in
    # place, halving peak HBM (old+new copies never coexist).
    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, ids, targets):
        def loss_fn(p):
            if chunked_loss:
                # Long context: the [B, T, V] logits tensor would be
                # the biggest activation (4.2 GB f32 at 32k/32k);
                # chunk the head + softmax-xent over the sequence.
                return chunked_causal_lm_loss(model, p, ids, targets)
            return causal_lm_loss(model.apply(p, ids), targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Warm up / compile. Timing closes with a scalar device->host fetch:
    # on relayed/remote TPU backends block_until_ready can return before
    # remote execution finishes, but a value fetch cannot.
    params, opt_state, loss = train_step(params, opt_state, ids, targets)
    _ = float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, ids, targets)
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_s = tokens / dt
    mfu = tok_per_s * flops_per_token(n_params, cfg, seq) / peak_flops
    return tok_per_s, mfu, final_loss


def main() -> int:
    batch = int(os.environ.get("BENCH_BATCH", "2"))
    seq = int(os.environ.get("BENCH_SEQ", "2048"))
    model_name = os.environ.get("BENCH_MODEL", "llama-1b")
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    peak_flops = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))  # v5e bf16
    run_moe = os.environ.get("BENCH_MOE", "1") != "0"

    import jax.numpy as jnp

    from dataclasses import replace

    from ray_tpu.models import CONFIGS
    from ray_tpu.models.llama import LlamaForCausalLM

    cfg = replace(CONFIGS[model_name], param_dtype=jnp.bfloat16)
    tok_per_s, mfu, final_loss = bench_model(
        LlamaForCausalLM(cfg), cfg, cfg.num_params(), batch, seq, steps,
        peak_flops,
    )

    extra = {}
    if os.environ.get("BENCH_LONGCTX", "1") != "0":
        # Long-context sweep: same model at batch 1, 4x/8x/16x the
        # sequence — the regime the pallas flash fwd+bwd kernels exist
        # for (the score matrix at s8192 would be 256 MiB/head/layer in
        # f32 if materialized; blockwise fwd+bwd never leaves VMEM).
        # Points that exceed chip HBM record "oom" instead of failing
        # the whole bench.
        lc_seqs = [
            int(s)
            for s in os.environ.get(
                "BENCH_LONGCTX_SEQS", "8192,16384,32768"
            ).split(",")
        ]
        points = []
        for lc_seq in lc_seqs:
            try:
                lc_tok, lc_mfu, lc_loss = bench_model(
                    LlamaForCausalLM(cfg), cfg, cfg.num_params(), 1, lc_seq,
                    max(5, steps // 2), peak_flops, chunked_loss=True,
                )
            except Exception as exc:  # RESOURCE_EXHAUSTED at the top end
                if not points:
                    raise  # first point failing is a bug, not an OOM
                points.append({"seq": lc_seq, "oom": type(exc).__name__})
                break
            points.append(
                {
                    "seq": lc_seq,
                    "tokens_per_s": round(lc_tok, 1),
                    "mfu": round(lc_mfu, 3),
                    "loss": round(lc_loss, 3),
                }
            )
        extra["longctx"] = points
        # Headline long-context fields stay on the first (8k) point for
        # round-over-round comparability.
        if points and "mfu" in points[0]:
            extra.update(
                longctx_seq=points[0]["seq"],
                longctx_tokens_per_s=points[0]["tokens_per_s"],
                longctx_mfu=points[0]["mfu"],
                longctx_loss=points[0]["loss"],
            )
    if run_moe:
        from ray_tpu.models.mixtral import CONFIGS as MOE_CONFIGS
        from ray_tpu.models.mixtral import MixtralForCausalLM

        moe_cfg = replace(MOE_CONFIGS["mixtral-small"], param_dtype=jnp.bfloat16)
        # Measured backend selection (capacity vs pallas gmm) on the
        # live chip, cached per machine; the probe IS the heuristic.
        from ray_tpu.models.mixtral import resolve_moe_dispatch

        moe_dispatch = resolve_moe_dispatch(moe_cfg, tokens=batch * seq)
        moe_cfg = replace(moe_cfg, moe_dispatch=moe_dispatch)
        # MFU over *active* FLOPs: a top-k sparse model only computes k of
        # E experts per token.
        moe_tok, moe_mfu, moe_loss = bench_model(
            MixtralForCausalLM(moe_cfg),
            moe_cfg,
            moe_cfg.active_params_per_token(),
            batch,
            seq,
            steps,
            peak_flops,
        )
        extra.update(
            moe_model="mixtral-small (8 experts, top-2)",
            moe_dispatch=moe_dispatch,
            moe_tokens_per_s=round(moe_tok, 1),
            moe_mfu_active=round(moe_mfu, 3),
            moe_loss=round(moe_loss, 3),
        )

    print(
        json.dumps(
            {
                "metric": f"{model_name} train step tokens/s/chip (b{batch} s{seq}, "
                f"loss {final_loss:.3f}, MFU {mfu:.3f})",
                "value": round(tok_per_s, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.40, 4),
                "extra": extra,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
