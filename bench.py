"""Headline benchmark: train-step throughput / MFU on one TPU chip.

Measures the end-to-end jitted training step (fwd + bwd + adamw update,
remat on, bf16 compute, donated buffers) of the Llama-1B config at
batch 2 x seq 2048 and reports tokens/sec/chip and model FLOPs
utilization against the v5e peak; also runs a Mixtral-style sparse-MoE
config (top-2 of 8 experts) and reports its MFU over *active* FLOPs.

Batch is 2 because the 1B model's bf16 params + adamw moments + grads
leave room for exactly two 2048-token activations sets on a 16 GiB
chip even with buffer donation and full remat (b4 fits but is slower;
b1 under-utilizes the MXU).

BASELINE.md north star: Llama finetune >=40% MFU. vs_baseline is
MFU / 0.40 (>1.0 beats the target).

Prints exactly one JSON line; the MoE numbers ride in "extra".
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from functools import partial

# Progressively-filled result the watchdog can flush: if the relay dies
# MID-bench (it did mid-round-4), the parent would otherwise block forever
# inside backend init / a device fetch where no except-handler runs.
_RESULT = {
    "metric": "bench unavailable",
    "value": 0.0,
    "unit": "tokens/s/chip",
    "vs_baseline": 0.0,
}
_PRINTED = threading.Event()
# Serializes watchdog vs. main around _RESULT mutation and the single
# print — without it the deadline boundary can double-print or dump
# _RESULT mid-update.
_EMIT_LOCK = threading.Lock()


def _emit(extra_error: str | None = None) -> int:
    """Print the one JSON output line exactly once (main or watchdog)."""
    with _EMIT_LOCK:
        if not _PRINTED.is_set():
            _PRINTED.set()
            if extra_error is not None:
                _RESULT["error"] = extra_error
            print(json.dumps(_RESULT), flush=True)
            # Tell the external watchdog the line is out (it must not
            # print a second one if we're merely slow to exit).
            try:
                open(_DONE_PATH, "w").close()
            except OSError:
                pass
    return 0


def _update_result(**kw) -> None:
    with _EMIT_LOCK:
        _RESULT.update(**kw)
    _dump_partial()


def _update_extra(extra: dict, **kw) -> None:
    """`extra` lives inside _RESULT once the headline lands, so the
    watchdog's json.dumps may walk it concurrently — same lock."""
    with _EMIT_LOCK:
        extra.update(**kw)
    _dump_partial()


_PARTIAL_PATH = f"/tmp/bench_partial_{os.getpid()}.json"
_DONE_PATH = _PARTIAL_PATH + ".done"

_WATCHDOG_SRC = r"""
import json, os, signal, sys, time

pid, partial, done, deadline = (
    int(sys.argv[1]), sys.argv[2], sys.argv[3], float(sys.argv[4]),
)
end = time.time() + deadline
while time.time() < end:
    time.sleep(2)
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        sys.exit(0)  # parent exited (it printed or crashed visibly)
if os.path.exists(done):
    sys.exit(0)  # parent already emitted; it's just slow to die
try:
    with open(partial) as f:
        res = json.load(f)
except Exception:
    res = {
        "metric": "bench unavailable", "value": 0.0,
        "unit": "tokens/s/chip", "vs_baseline": 0.0,
    }
res["error"] = f"bench_killed_by_external_watchdog_{int(deadline)}s"
print(json.dumps(res), flush=True)
try:
    os.kill(pid, signal.SIGKILL)
except ProcessLookupError:
    pass
"""


def _start_watchdog(deadline_s: float) -> None:
    # Layer 1: in-process timer — catches hangs where Python threads
    # still run (device fetches that release the GIL).
    def fire():
        _emit(f"bench_deadline_exceeded_{int(deadline_s)}s")
        os._exit(0)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()
    # Layer 2: an EXTERNAL watchdog process — a wedged relay can block
    # inside a C call HOLDING the GIL (observed: a second bench run sat
    # 40 min past the timer with the timer thread starved), and no
    # in-process mechanism runs then. The child inherits stdout, so the
    # one JSON line still reaches the driver, read from the partial
    # file the main thread keeps current.
    _dump_partial()
    try:
        subprocess.Popen(
            [
                sys.executable, "-c", _WATCHDOG_SRC,
                str(os.getpid()), _PARTIAL_PATH, _DONE_PATH,
                # Fire AFTER layer 1 had its chance.
                str(deadline_s + 30.0),
            ],
        )
    except OSError:
        pass


def _dump_partial() -> None:
    """Keep the external watchdog's view of _RESULT current."""
    try:
        blob = json.dumps(_RESULT)
        with open(_PARTIAL_PATH + ".tmp", "w") as f:
            f.write(blob)
        os.replace(_PARTIAL_PATH + ".tmp", _PARTIAL_PATH)
    except OSError:
        pass


def _probe_backend(timeout_s: float) -> str | None:
    """Initialize the jax backend in a KILLABLE child with a bounded wait.

    The host sitecustomize forces a relayed TPU backend whose init can hang
    forever when the relay is wedged (round-4 BENCH was rc=1, MULTICHIP
    rc=124 for exactly this).  In-process init can't be interrupted, so the
    probe runs `jax.devices()` in a subprocess first; only if that succeeds
    within the budget does the parent initialize the same backend.

    Returns None when the backend is healthy, else a short diagnostic tag.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(jax.default_backend(), len(d))"],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return "backend_init_timeout"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        return "backend_init_failed: " + (tail[-1][:200] if tail else "?")
    return None


def _probe_backend_with_retry(per_try_s: float, budget_s: float) -> str | None:
    """Spend the FULL driver probe budget retrying backend init with
    exponential backoff instead of one fixed-length probe: the r04/r05
    wedge was environmental (relay not up yet), and a single 180 s
    probe turned a transient into two empty scoreboard rounds
    (ROADMAP standing item). Every attempt is tagged with a
    flight-recorder event AND mirrored into the watchdog's partial
    result, so a future wedge is attributable to its phase even when
    this process is ultimately SIGKILLed."""
    from ray_tpu._private.chaos import Backoff
    from ray_tpu._private import events as _events

    backoff = Backoff(base_s=5.0, cap_s=60.0, budget_s=budget_s)
    deadline = time.monotonic() + budget_s
    attempts = []
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        t0 = time.monotonic()
        err = _probe_backend(min(per_try_s, max(10.0, remaining)))
        took = time.monotonic() - t0
        _events.record(
            "bench", "backend_probe",
            "OK" if err is None else "RETRY",
            {"attempt": attempt, "seconds": round(took, 1),
             "error": err or ""},
        )
        attempts.append(
            {"attempt": attempt, "seconds": round(took, 1),
             "error": err or "ok"}
        )
        _update_result(probe={"attempts": attempts})
        if err is None:
            return None
        if time.monotonic() >= deadline or not backoff.sleep():
            return f"{err} (after {attempt} attempts over "\
                   f"{budget_s - max(0.0, deadline - time.monotonic()):.0f}s)"
    return f"backend_init_budget_exhausted ({attempt} attempts)"




def flops_per_token(n_params: float, cfg, seq_len: int) -> float:
    """6N matmul flops/token + attention score flops
    (12 * L * T * hidden per token, fwd+bwd)."""
    return 6.0 * n_params + 12.0 * cfg.num_layers * seq_len * cfg.hidden_size


def bench_model(model, cfg, n_params, batch, seq, steps, peak_flops,
                chunked_loss: bool = False):
    import jax
    import numpy as np
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import causal_lm_loss, chunked_causal_lm_loss

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    targets = jnp.roll(ids, -1, axis=1)

    params = jax.jit(model.init)(jax.random.PRNGKey(0), ids[:1, :8])
    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, mu_dtype=jnp.bfloat16)
    opt_state = tx.init(params)

    # Donate params + opt_state: the step consumes the old buffers in
    # place, halving peak HBM (old+new copies never coexist).
    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, ids, targets):
        def loss_fn(p):
            if chunked_loss:
                # Long context: the [B, T, V] logits tensor would be
                # the biggest activation (4.2 GB f32 at 32k/32k);
                # chunk the head + softmax-xent over the sequence.
                return chunked_causal_lm_loss(model, p, ids, targets)
            return causal_lm_loss(model.apply(p, ids), targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Warm up / compile. Timing closes with a scalar device->host fetch:
    # on relayed/remote TPU backends block_until_ready can return before
    # remote execution finishes, but a value fetch cannot.
    params, opt_state, loss = train_step(params, opt_state, ids, targets)
    _ = float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, ids, targets)
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_s = tokens / dt
    mfu = tok_per_s * flops_per_token(n_params, cfg, seq) / peak_flops
    return tok_per_s, mfu, final_loss


def main() -> int:
    batch = int(os.environ.get("BENCH_BATCH", "2"))
    seq = int(os.environ.get("BENCH_SEQ", "2048"))
    model_name = os.environ.get("BENCH_MODEL", "llama-1b")
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    peak_flops = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))  # v5e bf16
    run_moe = os.environ.get("BENCH_MOE", "1") != "0"

    # Below any plausible driver timeout: a flushed partial result beats
    # an rc=124 with no output line. Armed BEFORE the probe retries so
    # the whole run (probe loop included) stays under one deadline.
    deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "1500"))
    if deadline_s > 0:
        _start_watchdog(deadline_s)
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "180"))
    if probe_timeout > 0:
        # Spend the full driver budget minus what the measured bench
        # itself needs (~300s for all phases on a healthy chip) on
        # backend-init retries — not one fixed-length probe.
        default_budget = max(probe_timeout, deadline_s - 300.0)
        probe_budget = float(
            os.environ.get("BENCH_PROBE_BUDGET_S", str(default_budget))
        )
        err = _probe_backend_with_retry(probe_timeout, probe_budget)
        if err is not None:
            return _emit(err)

    import jax.numpy as jnp

    from dataclasses import replace

    from ray_tpu.models import CONFIGS
    from ray_tpu.models.llama import LlamaForCausalLM

    cfg = replace(CONFIGS[model_name], param_dtype=jnp.bfloat16)
    tok_per_s, mfu, final_loss = bench_model(
        LlamaForCausalLM(cfg), cfg, cfg.num_params(), batch, seq, steps,
        peak_flops,
    )

    extra = {}
    # Headline lands in _RESULT immediately: if a later phase wedges the
    # backend, the watchdog still flushes a valid tokens/s/MFU point.
    _update_result(
        metric=f"{model_name} train step tokens/s/chip (b{batch} s{seq}, "
        f"loss {final_loss:.3f}, MFU {mfu:.3f})",
        value=round(tok_per_s, 1),
        vs_baseline=round(mfu / 0.40, 4),
        extra=extra,
    )
    if os.environ.get("BENCH_LONGCTX", "1") != "0":
        # Long-context sweep: same model at batch 1, 4x/8x/16x the
        # sequence — the regime the pallas flash fwd+bwd kernels exist
        # for (the score matrix at s8192 would be 256 MiB/head/layer in
        # f32 if materialized; blockwise fwd+bwd never leaves VMEM).
        # Points that exceed chip HBM record "oom" instead of failing
        # the whole bench.
        lc_seqs = [
            int(s)
            for s in os.environ.get(
                "BENCH_LONGCTX_SEQS", "8192,16384,32768"
            ).split(",")
        ]
        points = []
        for lc_seq in lc_seqs:
            try:
                lc_tok, lc_mfu, lc_loss = bench_model(
                    LlamaForCausalLM(cfg), cfg, cfg.num_params(), 1, lc_seq,
                    max(5, steps // 2), peak_flops, chunked_loss=True,
                )
            except Exception as exc:  # RESOURCE_EXHAUSTED at the top end
                if not points:
                    raise  # first point failing is a bug, not an OOM
                points.append({"seq": lc_seq, "oom": type(exc).__name__})
                break
            points.append(
                {
                    "seq": lc_seq,
                    "tokens_per_s": round(lc_tok, 1),
                    "mfu": round(lc_mfu, 3),
                    "loss": round(lc_loss, 3),
                }
            )
        _update_extra(extra, longctx=points)
        # Headline long-context fields stay on the first (8k) point for
        # round-over-round comparability.
        if points and "mfu" in points[0]:
            _update_extra(
                extra,
                longctx_seq=points[0]["seq"],
                longctx_tokens_per_s=points[0]["tokens_per_s"],
                longctx_mfu=points[0]["mfu"],
                longctx_loss=points[0]["loss"],
            )
    if run_moe:
        try:
            _bench_moe(batch, seq, steps, peak_flops, extra)
        except Exception as exc:  # MoE phase must not void the headline
            _update_extra(
                extra, moe_error=f"{type(exc).__name__}: {exc}"[:200]
            )

    return _emit()


def _bench_moe(batch, seq, steps, peak_flops, extra) -> None:
    import jax.numpy as jnp

    from dataclasses import replace

    from ray_tpu.models.mixtral import CONFIGS as MOE_CONFIGS
    from ray_tpu.models.mixtral import MixtralForCausalLM, resolve_moe_dispatch

    moe_cfg = replace(MOE_CONFIGS["mixtral-small"], param_dtype=jnp.bfloat16)
    # Measured backend selection (capacity vs pallas gmm) on the
    # live chip, cached per machine; the probe IS the heuristic.
    moe_dispatch = resolve_moe_dispatch(moe_cfg, tokens=batch * seq)
    moe_cfg = replace(moe_cfg, moe_dispatch=moe_dispatch)
    # MFU over *active* FLOPs: a top-k sparse model only computes k of
    # E experts per token.
    moe_tok, moe_mfu, moe_loss = bench_model(
        MixtralForCausalLM(moe_cfg),
        moe_cfg,
        moe_cfg.active_params_per_token(),
        batch,
        seq,
        steps,
        peak_flops,
    )
    _update_extra(
        extra,
        moe_model="mixtral-small (8 experts, top-2)",
        moe_dispatch=moe_dispatch,
        moe_tokens_per_s=round(moe_tok, 1),
        moe_mfu_active=round(moe_mfu, 3),
        moe_loss=round(moe_loss, 3),
    )


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as exc:  # traceback to stderr, parseable line to stdout
        import traceback

        traceback.print_exc(file=sys.stderr)
        sys.exit(_emit(f"{type(exc).__name__}: {exc}"[:300]))
