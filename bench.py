"""Headline benchmark: Llama train-step throughput / MFU on one TPU chip.

Measures the end-to-end jitted training step (fwd + bwd + adamw update,
remat on, bf16 compute) of the Llama-1B config at seq 2048 and reports
tokens/sec/chip and model FLOPs utilization against the v5e peak.

BASELINE.md north star: Llama finetune >=40% MFU. vs_baseline is
MFU / 0.40 (>1.0 beats the target).

Prints exactly one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time


def model_flops_per_token(cfg, seq_len: int) -> float:
    """6N matmul flops/token + attention score flops
    (12 * L * T * hidden per token, fwd+bwd)."""
    n = cfg.num_params()
    return 6.0 * n + 12.0 * cfg.num_layers * seq_len * cfg.hidden_size


def main() -> int:
    # Defaults sized to one v5e-lite chip (batch 4 OOMs with adamw state).
    batch = int(os.environ.get("BENCH_BATCH", "1"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    model_name = os.environ.get("BENCH_MODEL", "llama-1b")
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    peak_flops = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))  # v5e bf16

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import CONFIGS
    from ray_tpu.models.llama import LlamaForCausalLM, causal_lm_loss

    from dataclasses import replace

    cfg = replace(CONFIGS[model_name], param_dtype=jnp.bfloat16)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    targets = jnp.roll(ids, -1, axis=1)

    params = jax.jit(model.init)(jax.random.PRNGKey(0), ids[:1, :8])
    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, mu_dtype=jnp.bfloat16)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, ids, targets):
        def loss_fn(p):
            return causal_lm_loss(model.apply(p, ids), targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Warm up / compile. Timing closes with a scalar device->host fetch:
    # on relayed/remote TPU backends block_until_ready can return before
    # remote execution finishes, but a value fetch cannot.
    params, opt_state, loss = train_step(params, opt_state, ids, targets)
    _ = float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, ids, targets)
    final_loss = float(loss)
    dt = time.perf_counter() - t0

    tokens = batch * seq * steps
    tok_per_s = tokens / dt
    flops_per_tok = model_flops_per_token(cfg, seq)
    mfu = tok_per_s * flops_per_tok / peak_flops

    print(
        json.dumps(
            {
                "metric": f"{model_name} train step tokens/s/chip (b{batch} s{seq}, "
                f"loss {final_loss:.3f}, MFU {mfu:.3f})",
                "value": round(tok_per_s, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.40, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
