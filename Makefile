# CI entry points (reference: the Bazel/Buildkite pipelines in
# .buildkite/ + ci/ — here one deterministic make surface: native
# build, bytecode lint, stress binaries, full suite).

.PHONY: ci native lint test stress clean

ci: native lint test

native:
	$(MAKE) -C native

# No flake8/pyflakes in this image: compileall catches syntax errors in
# every module (including ones the suite never imports) and -W error
# on import smoke-checks the public surface.
lint:
	python -m compileall -q ray_tpu tests
	JAX_PLATFORMS=cpu python -c "import ray_tpu, ray_tpu.data, \
	ray_tpu.train, ray_tpu.tune, ray_tpu.serve, ray_tpu.rllib, \
	ray_tpu.workflow, ray_tpu.dag, ray_tpu.autoscaler.gce, \
	ray_tpu.util.multiprocessing, ray_tpu.experimental.tqdm_ray"

test:
	python -m pytest tests/ -q

stress:
	$(MAKE) -C native stress-asan
	./ray_tpu/_private/_native/store_stress_asan 30

clean:
	$(MAKE) -C native clean
