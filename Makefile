# CI entry points (reference: the Bazel/Buildkite pipelines in
# .buildkite/ + ci/ — here one deterministic make surface: native
# build, bytecode lint, stress binaries, full suite).

.PHONY: ci native lint raylint raylint-baseline race-smoke test \
	obs-smoke envelope-smoke chaos-smoke failover-smoke \
	pressure-smoke shm-smoke partition-smoke straggler-smoke \
	stress clean

ci: native lint test obs-smoke envelope-smoke chaos-smoke failover-smoke \
	pressure-smoke race-smoke shm-smoke partition-smoke straggler-smoke

native:
	$(MAKE) -C native

# Three lint layers: compileall catches syntax errors in every module
# (including ones the suite never imports), the import line smoke-
# checks the public surface, and raylint enforces the runtime's
# concurrency/reliability invariants (thread domains, one retry
# policy, at-least-once GCS traffic, counted-never-silent faults, the
# event-name registry) against tools/raylint/baseline.json —
# pre-existing debt is tracked, NEW violations fail CI. See README
# "Static analysis & concurrency invariants".
lint:
	python -m compileall -q ray_tpu tests tools
	python -m tools.raylint
	JAX_PLATFORMS=cpu python -c "import ray_tpu, ray_tpu.data, \
	ray_tpu.train, ray_tpu.tune, ray_tpu.serve, ray_tpu.rllib, \
	ray_tpu.workflow, ray_tpu.dag, ray_tpu.autoscaler.gce, \
	ray_tpu.util.multiprocessing, ray_tpu.experimental.tqdm_ray"

# raylint alone (fast; no jax import needed).
raylint:
	python -m tools.raylint

# Re-snapshot the accepted debt after deliberately fixing or accepting
# violations. Review the diff of tools/raylint/baseline.json!
raylint-baseline:
	python -m tools.raylint --write-baseline

# Lock-order witness soak (Python TSan-lite): the full witness unit
# suite (inverted pair caught, clean ordering clean, reentrant RLock
# no-false-positive) plus the object-plane, chaos, lifetime, and
# actors suites with every threading.Lock/RLock wrapped and the
# held-before graph checked for cycles. A witnessed inversion FAILS the run (pytest exit 3 from the
# sessionfinish hook) even when every test passed — the inversion is a
# deadlock waiting for production traffic to align. Subprocesses
# (heads/raylets/workers) inherit RAY_TPU_lock_witness and
# self-install; their findings append to the shared sidecar file the
# sessionfinish gate scans (plus stderr and CHAOS LOCK_ORDER
# flight-recorder events), so a daemon-side inversion fails the run
# too. Skips are counted by pytest, never silent.
race-smoke:
	RAY_TPU_lock_witness=1 JAX_PLATFORMS=cpu python -m pytest \
		tests/test_lock_witness.py tests/test_object_plane.py \
		tests/test_chaos.py tests/test_object_lifetime.py \
		tests/test_actors.py -q -p no:cacheprovider

test:
	python -m pytest tests/ -q

# Observability surface: flight-recorder event pipeline + tracing +
# dashboard tests, including the recorder overhead-budget perf check
# (test_flight_recorder_overhead_budget asserts ≤5% on the
# single_client_tasks_async shape vs recording disabled).
obs-smoke:
	python -m pytest tests/test_observability.py \
		tests/test_dashboard_tracing.py tests/test_logging.py -q

# Object-plane envelope, scaled down (64 MiB broadcast to 4 real
# daemon nodes, 1k args, 300 returns, 1k gets, spill-backed get) held
# concurrently with a 20k-task/100-node scheduling stress. The full
# reference-scale rows (1 GiB / 32 nodes / 10k / 3k / 200k-task
# stress) run via:
#   python -m ray_tpu._private.ray_perf --only object_envelope
# A host that can't fit even the smoke payload records an explicit
# object_envelope_skipped row — counted, never silent.
envelope-smoke:
	JAX_PLATFORMS=cpu python -m ray_tpu._private.ray_perf \
		--only object_envelope --envelope-smoke \
		--out /tmp/ray_tpu_envelope_smoke.json

# Chaos soak, short + seeded (2 real daemon nodes, ~25s of task/actor/
# object traffic under message drop/delay/dup/reorder on ref_flush /
# borrow / pull paths, worker kill points, and node SIGKILLs). The run
# prints its seed up front; any red run reproduces with
#   python -m ray_tpu._private.ray_perf --only chaos_soak --chaos-smoke \
#       --chaos-seed <printed seed>
# A host without the TCP control plane records chaos_soak_skipped —
# counted, never silent. The full multi-minute soak:
#   python -m ray_tpu._private.ray_perf --only chaos_soak
chaos-smoke:
	JAX_PLATFORMS=cpu python -m ray_tpu._private.ray_perf \
		--only chaos_soak --chaos-smoke \
		--out /tmp/ray_tpu_chaos_smoke.json

# Head-failover smoke, short + seeded (1 supervised-head SIGKILL under
# task/actor/object traffic on a 2-daemon cluster, bounded wall time).
# Asserts zero wedged gets, actor + kv continuity across the restart,
# no leaked directory entries, and visible HEAD/RECONCILE events. A
# red run reproduces with
#   python -m ray_tpu._private.ray_perf --only head_failover \
#       --failover-smoke --chaos-seed <printed seed>
# A host that cannot launch the external head records an explicit
# head_failover_skipped row — counted, never silent. The full
# multi-kill soak:
#   python -m ray_tpu._private.ray_perf --only head_failover
failover-smoke:
	JAX_PLATFORMS=cpu python -m ray_tpu._private.ray_perf \
		--only head_failover --failover-smoke \
		--out /tmp/ray_tpu_failover_smoke.json

# Partition soak, short + seeded (1 victim daemon fully partitioned
# from the head past the death threshold while holding a restartable
# actor, leased tasks and owned objects; scheduled heal; then one
# supervised-head SIGKILL to prove fencing composes with failover).
# Asserts zero wedged gets, at-most-once actor side effects across the
# false death (per-incarnation boot tokens never interleave, counters
# stay monotonic), no resurrected freed objects, NODE_FENCED +
# ZOMBIE_SELF_FENCE visible, and the victim back as a NEW node id with
# a HIGHER incarnation. A red run reproduces with
#   python -m ray_tpu._private.ray_perf --only partition_soak \
#       --partition-smoke --chaos-seed <printed seed>
# A host that cannot launch the external head records an explicit
# partition_soak_skipped row — counted, never silent. The full
# two-node soak:
#   python -m ray_tpu._private.ray_perf --only partition_soak
partition-smoke:
	JAX_PLATFORMS=cpu python -m ray_tpu._private.ray_perf \
		--only partition_soak --partition-smoke \
		--out /tmp/ray_tpu_partition_smoke.json

# Straggler soak, short + seeded (2 healthy daemons + 1 gray victim:
# alive and heartbeating but with task execution stretched 50x and its
# transfer plane later throttled to 1 MiB/s). Asserts the health
# scorer suspects then quarantines the victim (drain, not fence),
# hedged twins keep task p99 within 3x the all-healthy baseline,
# every hedged pair resolves to exactly one accepted done (the
# resource ledger never over-credits), throttled multi-chunk pulls
# re-lead (PULL_RELEAD) instead of wedging and deliver correct bytes,
# hedging stays <= 1% launch rate while healthy, the victim is
# readmitted after heal, and the sequence composes with one
# supervised-head SIGKILL. A red run reproduces with
#   python -m ray_tpu._private.ray_perf --only straggler_soak \
#       --straggler-smoke --chaos-seed <printed seed>
# A host that cannot launch the external head records an explicit
# straggler_soak_skipped row — counted, never silent. The full
# >=100-pair soak:
#   python -m ray_tpu._private.ray_perf --only straggler_soak
straggler-smoke:
	JAX_PLATFORMS=cpu python -m ray_tpu._private.ray_perf \
		--only straggler_soak --straggler-smoke \
		--out /tmp/ray_tpu_straggler_smoke.json

# Memory-pressure soak, scaled down (a 32 MiB broadcast chunk train to
# 8 real daemon nodes concurrent with hundreds of small gets, under a
# 48 MiB pool and a 12 MiB in-flight pull budget, then seeded storage
# chaos: spill IO errors, disk-full, truncated spill files). Asserts
# bounded small-get p99 (no starvation), in-flight pull bytes <= budget
# (from PULL_ACTIVATE flight-recorder events), zero wedged gets, no
# leaked pool bytes, and that every injected storage fault ends in
# backpressure / OutOfMemoryError / lineage reconstruction. A host
# without the TCP control plane records pressure_soak_skipped —
# counted, never silent. The full 1 GiB / 8-node soak:
#   python -m ray_tpu._private.ray_perf --only pressure_soak
pressure-smoke:
	JAX_PLATFORMS=cpu python -m ray_tpu._private.ray_perf \
		--only pressure_soak --pressure-smoke \
		--out /tmp/ray_tpu_pressure_smoke.json

# Shared-memory object plane smoke: the node-pool crash-safety suite
# (multi-process bit-exactness, SIGKILL ledger sweep, mid-put partial
# reclamation, cross-process eviction pinning, pool-full -> segment
# ladder) plus the allocator/refcount unit tests. On a host without
# /dev/shm or the C++ toolchain the suite SKIPS each test with a
# counted reason (pytest's skip column) — never silently green.
shm-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_shm_plane.py \
		tests/test_native_store.py -q -p no:cacheprovider -rs

stress:
	$(MAKE) -C native stress-asan
	./ray_tpu/_private/_native/store_stress_asan 30

clean:
	$(MAKE) -C native clean
