/* Native control-plane hot path.
 *
 * Reference: the compiled Cython/C++ submit/receive path
 * (python/ray/_raylet.pyx:3996 submit_task, src/ray/core_worker/
 * core_worker.cc:2149) and the hand-rolled protobuf encoding of the hot
 * RPCs (src/ray/protobuf/). ray_tpu's control plane frames are Python
 * tuples; this module gives the two hot shapes (task/actor CALL and its
 * REPLY), their batch envelope, readiness pushes, and the worker's
 * task_done report a typed binary wire format encoded/decoded in C —
 * no pickle on the steady-state path — plus C implementations of the
 * per-message loops that dominate submit/wait in profiles:
 *
 *   encode(obj)        -> bytes | None (unsupported shape: use pickle)
 *   decode(buf)        -> the tuple/list structure pickle would return
 *   return_oids(tid,n) -> list of n return-object ids (12B prefix+u32)
 *   wait_partition(refs, ready_set, num_returns) -> (ready, rest)|None
 *
 * Wire format (little-endian), payload position 0 is the magic byte
 * 0xF1 — pickle protocol 2+ payloads start with 0x80, so a receiver
 * can route on the first byte with no framing change:
 *
 *   frame   := 0xF1 kind body
 *   kind    := 1 CALL | 2 REPLY | 3 BATCH | 4 RDY
 *   CALL    := u32 req_id  bstr tid  obytes fid  ostr method
 *              bstr args  u32 nret  obytes aid  ostr cgroup
 *   REPLY   := u32 req_id  obytes error  u16 nresults result*
 *   result  := obytes inline  ostr segment  u64 size  u16 nchild bstr*
 *   BATCH   := u32 count elem*          ("B", [...]) envelope
 *   elem    := 0x01 frame-body-with-kind | 0x00 u32 len pickle-bytes
 *   RDY     := u16 count bstr*          ("RDY", (oid,...)) push
 *   bstr    := u32 len bytes            obytes := 0x00 | 0x01 bstr
 *   ostr    := 0x00 | 0x01 u32 len utf8
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define MAGIC 0xF1
#define K_CALL 1
#define K_REPLY 2
#define K_BATCH 3
#define K_RDY 4

/* ------------------------------------------------------------------ buf */

typedef struct {
    char *p;
    Py_ssize_t len, cap;
} Buf;

static int buf_init(Buf *b, Py_ssize_t cap) {
    b->p = PyMem_Malloc(cap);
    if (!b->p) return -1;
    b->len = 0;
    b->cap = cap;
    return 0;
}

static int buf_reserve(Buf *b, Py_ssize_t extra) {
    if (b->len + extra <= b->cap) return 0;
    Py_ssize_t cap = b->cap * 2;
    while (cap < b->len + extra) cap *= 2;
    char *np = PyMem_Realloc(b->p, cap);
    if (!np) return -1;
    b->p = np;
    b->cap = cap;
    return 0;
}

static int buf_u8(Buf *b, uint8_t v) {
    if (buf_reserve(b, 1) < 0) return -1;
    b->p[b->len++] = (char)v;
    return 0;
}

static int buf_u16(Buf *b, uint16_t v) {
    if (buf_reserve(b, 2) < 0) return -1;
    memcpy(b->p + b->len, &v, 2);
    b->len += 2;
    return 0;
}

static int buf_u32(Buf *b, uint32_t v) {
    if (buf_reserve(b, 4) < 0) return -1;
    memcpy(b->p + b->len, &v, 4);
    b->len += 4;
    return 0;
}

static int buf_u64(Buf *b, uint64_t v) {
    if (buf_reserve(b, 8) < 0) return -1;
    memcpy(b->p + b->len, &v, 8);
    b->len += 8;
    return 0;
}

static int buf_raw(Buf *b, const char *src, Py_ssize_t n) {
    if (buf_reserve(b, n) < 0) return -1;
    memcpy(b->p + b->len, src, n);
    b->len += n;
    return 0;
}

/* bytes with u32 length prefix; -1 on overflow/alloc, -2 wrong type */
static int buf_bstr(Buf *b, PyObject *o) {
    char *s;
    Py_ssize_t n;
    if (!PyBytes_Check(o)) return -2;
    s = PyBytes_AS_STRING(o);
    n = PyBytes_GET_SIZE(o);
    if (n > UINT32_MAX) return -2;
    if (buf_u32(b, (uint32_t)n) < 0) return -1;
    return buf_raw(b, s, n);
}

static int buf_obytes(Buf *b, PyObject *o) {
    if (o == Py_None) return buf_u8(b, 0);
    if (buf_u8(b, 1) < 0) return -1;
    return buf_bstr(b, o);
}

static int buf_ostr(Buf *b, PyObject *o) {
    Py_ssize_t n;
    const char *s;
    if (o == Py_None) return buf_u8(b, 0);
    if (!PyUnicode_Check(o)) return -2;
    s = PyUnicode_AsUTF8AndSize(o, &n);
    if (!s || n > UINT32_MAX) return -2;
    if (buf_u8(b, 1) < 0 || buf_u32(b, (uint32_t)n) < 0) return -1;
    return buf_raw(b, s, n);
}

/* ---------------------------------------------------------------- encode */

/* Returns 0 ok, -2 shape-unsupported (no exception), -1 error (exc set) */
static int enc_call(Buf *b, PyObject *t) {
    long req_id, nret;
    PyObject *o;
    if (PyTuple_GET_SIZE(t) != 9) return -2;
    o = PyTuple_GET_ITEM(t, 1);
    if (!PyLong_Check(o)) return -2;
    req_id = PyLong_AsLong(o);
    if (req_id < 0 || req_id > UINT32_MAX) return -2;
    o = PyTuple_GET_ITEM(t, 6);
    if (!PyLong_Check(o)) return -2;
    nret = PyLong_AsLong(o);
    if (nret < 0 || nret > UINT32_MAX) return -2;
    if (buf_u8(b, K_CALL) < 0 || buf_u32(b, (uint32_t)req_id) < 0) return -1;
    int r;
    if ((r = buf_bstr(b, PyTuple_GET_ITEM(t, 2))) != 0) return r;   /* tid */
    if ((r = buf_obytes(b, PyTuple_GET_ITEM(t, 3))) != 0) return r; /* fid */
    if ((r = buf_ostr(b, PyTuple_GET_ITEM(t, 4))) != 0) return r;   /* method */
    if ((r = buf_bstr(b, PyTuple_GET_ITEM(t, 5))) != 0) return r;   /* args */
    if (buf_u32(b, (uint32_t)nret) < 0) return -1;
    if ((r = buf_obytes(b, PyTuple_GET_ITEM(t, 7))) != 0) return r; /* aid */
    if ((r = buf_ostr(b, PyTuple_GET_ITEM(t, 8))) != 0) return r;   /* cg */
    return 0;
}

static int enc_reply(Buf *b, PyObject *t) {
    long req_id;
    PyObject *o, *results;
    Py_ssize_t n, i;
    if (PyTuple_GET_SIZE(t) != 4) return -2;
    o = PyTuple_GET_ITEM(t, 1);
    if (!PyLong_Check(o)) return -2;
    req_id = PyLong_AsLong(o);
    if (req_id < 0 || req_id > UINT32_MAX) return -2;
    results = PyTuple_GET_ITEM(t, 3);
    /* error replies carry results=None — encode as zero results (the
     * receiver checks the error field first); rejecting None silently
     * pushed every error reply onto the pickle fallback */
    if (results == Py_None) {
        if (buf_u8(b, K_REPLY) < 0 || buf_u32(b, (uint32_t)req_id) < 0)
            return -1;
        int r0;
        if ((r0 = buf_obytes(b, PyTuple_GET_ITEM(t, 2))) != 0) return r0;
        return buf_u16(b, 0) < 0 ? -1 : 0;
    }
    if (!PyList_Check(results)) return -2;
    n = PyList_GET_SIZE(results);
    if (n > UINT16_MAX) return -2;
    if (buf_u8(b, K_REPLY) < 0 || buf_u32(b, (uint32_t)req_id) < 0) return -1;
    int r;
    if ((r = buf_obytes(b, PyTuple_GET_ITEM(t, 2))) != 0) return r; /* err */
    if (buf_u16(b, (uint16_t)n) < 0) return -1;
    for (i = 0; i < n; i++) {
        PyObject *res = PyList_GET_ITEM(results, i);
        PyObject *size, *children;
        Py_ssize_t nc, j;
        if (!PyTuple_Check(res) || PyTuple_GET_SIZE(res) != 4) return -2;
        if ((r = buf_obytes(b, PyTuple_GET_ITEM(res, 0))) != 0) return r;
        if ((r = buf_ostr(b, PyTuple_GET_ITEM(res, 1))) != 0) return r;
        size = PyTuple_GET_ITEM(res, 2);
        if (!PyLong_Check(size)) return -2;
        {
            unsigned long long sz = PyLong_AsUnsignedLongLong(size);
            if (sz == (unsigned long long)-1 && PyErr_Occurred()) {
                PyErr_Clear();
                return -2;
            }
            if (buf_u64(b, (uint64_t)sz) < 0) return -1;
        }
        children = PyTuple_GET_ITEM(res, 3);
        if (children == Py_None) {
            /* the common case: no child refs captured in the result —
             * must NOT fall back to pickle (it did until round 5: every
             * childless direct reply silently paid the pickle path) */
            if (buf_u16(b, 0) < 0) return -1;
        } else if (PyTuple_Check(children)) {
            nc = PyTuple_GET_SIZE(children);
            if (nc > UINT16_MAX) return -2;
            if (buf_u16(b, (uint16_t)nc) < 0) return -1;
            for (j = 0; j < nc; j++)
                if ((r = buf_bstr(b, PyTuple_GET_ITEM(children, j))) != 0)
                    return r;
        } else if (PyList_Check(children)) {
            nc = PyList_GET_SIZE(children);
            if (nc > UINT16_MAX) return -2;
            if (buf_u16(b, (uint16_t)nc) < 0) return -1;
            for (j = 0; j < nc; j++)
                if ((r = buf_bstr(b, PyList_GET_ITEM(children, j))) != 0)
                    return r;
        } else {
            return -2;
        }
    }
    return 0;
}

static int enc_rdy(Buf *b, PyObject *t) {
    PyObject *ids;
    Py_ssize_t n, i;
    int r;
    if (PyTuple_GET_SIZE(t) != 2) return -2;
    ids = PyTuple_GET_ITEM(t, 1);
    if (PyTuple_Check(ids)) {
        n = PyTuple_GET_SIZE(ids);
        if (n > UINT16_MAX) return -2;
        if (buf_u8(b, K_RDY) < 0 || buf_u16(b, (uint16_t)n) < 0) return -1;
        for (i = 0; i < n; i++)
            if ((r = buf_bstr(b, PyTuple_GET_ITEM(ids, i))) != 0) return r;
        return 0;
    }
    if (PyList_Check(ids)) {
        n = PyList_GET_SIZE(ids);
        if (n > UINT16_MAX) return -2;
        if (buf_u8(b, K_RDY) < 0 || buf_u16(b, (uint16_t)n) < 0) return -1;
        for (i = 0; i < n; i++)
            if ((r = buf_bstr(b, PyList_GET_ITEM(ids, i))) != 0) return r;
        return 0;
    }
    return -2;
}

static PyObject *g_pickle_dumps;  /* pickle.dumps */
static PyObject *g_pickle_loads;  /* pickle.loads */
static PyObject *g_proto5;        /* int 5 */

/* one frame body (with kind byte), no magic. */
static int enc_frame(Buf *b, PyObject *obj) {
    PyObject *op;
    if (!PyTuple_Check(obj) || PyTuple_GET_SIZE(obj) < 2) return -2;
    op = PyTuple_GET_ITEM(obj, 0);
    if (PyLong_Check(op)) {
        long k = PyLong_AsLong(op);
        if (k == K_CALL) return enc_call(b, obj);
        if (k == K_REPLY) return enc_reply(b, obj);
        return -2;
    }
    if (PyUnicode_Check(op)) {
        if (PyUnicode_CompareWithASCIIString(op, "RDY") == 0)
            return enc_rdy(b, obj);
    }
    return -2;
}

static int enc_batch(Buf *b, PyObject *t) {
    PyObject *list;
    Py_ssize_t n, i;
    if (PyTuple_GET_SIZE(t) != 2) return -2;
    list = PyTuple_GET_ITEM(t, 1);
    if (!PyList_Check(list)) return -2;
    n = PyList_GET_SIZE(list);
    if (n > UINT32_MAX) return -2;
    if (buf_u8(b, K_BATCH) < 0 || buf_u32(b, (uint32_t)n) < 0) return -1;
    for (i = 0; i < n; i++) {
        PyObject *el = PyList_GET_ITEM(list, i);
        Py_ssize_t mark = b->len;
        if (buf_u8(b, 1) < 0) return -1;
        int r = enc_frame(b, el);
        if (r == 0) continue;
        if (r == -1) return -1;
        /* unsupported element: rewind, embed pickled bytes */
        b->len = mark;
        {
            PyObject *pk = PyObject_CallFunctionObjArgs(
                g_pickle_dumps, el, g_proto5, NULL);
            if (!pk) return -1;
            if (buf_u8(b, 0) < 0 || buf_bstr(b, pk) != 0) {
                Py_DECREF(pk);
                return -1;
            }
            Py_DECREF(pk);
        }
    }
    return 0;
}

static PyObject *py_encode(PyObject *self, PyObject *obj) {
    Buf b;
    int r;
    (void)self;
    if (!PyTuple_Check(obj) || PyTuple_GET_SIZE(obj) < 2) Py_RETURN_NONE;
    if (buf_init(&b, 256) < 0) return PyErr_NoMemory();
    b.p[b.len++] = (char)(unsigned char)MAGIC;
    {
        PyObject *op = PyTuple_GET_ITEM(obj, 0);
        if (PyUnicode_Check(op) &&
            PyUnicode_CompareWithASCIIString(op, "B") == 0) {
            r = enc_batch(&b, obj);
        } else {
            r = enc_frame(&b, obj);
        }
    }
    if (r == -2) {
        PyMem_Free(b.p);
        Py_RETURN_NONE;
    }
    if (r == -1) {
        PyMem_Free(b.p);
        if (!PyErr_Occurred()) PyErr_NoMemory();
        return NULL;
    }
    {
        PyObject *out = PyBytes_FromStringAndSize(b.p, b.len);
        PyMem_Free(b.p);
        return out;
    }
}

/* ---------------------------------------------------------------- decode */

typedef struct {
    const char *p;
    Py_ssize_t len, off;
} Rd;

static int rd_u8(Rd *r, uint8_t *v) {
    if (r->off + 1 > r->len) return -1;
    *v = (uint8_t)r->p[r->off++];
    return 0;
}

static int rd_u16(Rd *r, uint16_t *v) {
    if (r->off + 2 > r->len) return -1;
    memcpy(v, r->p + r->off, 2);
    r->off += 2;
    return 0;
}

static int rd_u32(Rd *r, uint32_t *v) {
    if (r->off + 4 > r->len) return -1;
    memcpy(v, r->p + r->off, 4);
    r->off += 4;
    return 0;
}

static int rd_u64(Rd *r, uint64_t *v) {
    if (r->off + 8 > r->len) return -1;
    memcpy(v, r->p + r->off, 8);
    r->off += 8;
    return 0;
}

static PyObject *rd_bstr(Rd *r) {
    uint32_t n;
    if (rd_u32(r, &n) < 0 || r->off + (Py_ssize_t)n > r->len) {
        PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
        return NULL;
    }
    {
        PyObject *o = PyBytes_FromStringAndSize(r->p + r->off, n);
        r->off += n;
        return o;
    }
}

static PyObject *rd_obytes(Rd *r) {
    uint8_t f;
    if (rd_u8(r, &f) < 0) {
        PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
        return NULL;
    }
    if (!f) Py_RETURN_NONE;
    return rd_bstr(r);
}

static PyObject *rd_ostr(Rd *r) {
    uint8_t f;
    uint32_t n;
    if (rd_u8(r, &f) < 0) goto trunc;
    if (!f) Py_RETURN_NONE;
    if (rd_u32(r, &n) < 0 || r->off + (Py_ssize_t)n > r->len) goto trunc;
    {
        PyObject *o = PyUnicode_DecodeUTF8(r->p + r->off, n, NULL);
        r->off += n;
        return o;
    }
trunc:
    PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
    return NULL;
}

static PyObject *dec_frame(Rd *r);

static PyObject *dec_call(Rd *r) {
    uint32_t req_id, nret;
    PyObject *tid = NULL, *fid = NULL, *meth = NULL, *args = NULL;
    PyObject *aid = NULL, *cg = NULL, *out = NULL;
    if (rd_u32(r, &req_id) < 0) goto trunc;
    if (!(tid = rd_bstr(r))) goto fail;
    if (!(fid = rd_obytes(r))) goto fail;
    if (!(meth = rd_ostr(r))) goto fail;
    if (!(args = rd_bstr(r))) goto fail;
    if (rd_u32(r, &nret) < 0) goto trunc;
    if (!(aid = rd_obytes(r))) goto fail;
    if (!(cg = rd_ostr(r))) goto fail;
    out = Py_BuildValue("(lNNNNlNN)", (long)K_CALL, tid,
                        fid, meth, args, (long)nret, aid, cg);
    /* Py_BuildValue 'N' steals; wrap req_id back in by rebuilding: */
    if (out) {
        PyObject *rid = PyLong_FromUnsignedLong(req_id);
        if (!rid) {
            Py_DECREF(out);
            return NULL;
        }
        /* tuple layout: (1, req_id, tid, fid, method, args, nret, aid, cg) */
        PyObject *full = PyTuple_New(9);
        if (!full) {
            Py_DECREF(out);
            Py_DECREF(rid);
            return NULL;
        }
        PyTuple_SET_ITEM(full, 0, PyLong_FromLong(K_CALL));
        PyTuple_SET_ITEM(full, 1, rid);
        {
            int i;
            for (i = 1; i < 8; i++) {
                PyObject *it = PyTuple_GET_ITEM(out, i);
                Py_INCREF(it);
                PyTuple_SET_ITEM(full, i + 1, it);
            }
        }
        Py_DECREF(out);
        return full;
    }
    return NULL;
trunc:
    PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
fail:
    Py_XDECREF(tid);
    Py_XDECREF(fid);
    Py_XDECREF(meth);
    Py_XDECREF(args);
    Py_XDECREF(aid);
    Py_XDECREF(cg);
    return NULL;
}

static PyObject *dec_reply(Rd *r) {
    uint32_t req_id;
    uint16_t n, i;
    PyObject *err = NULL, *results = NULL, *out;
    if (rd_u32(r, &req_id) < 0) goto trunc;
    if (!(err = rd_obytes(r))) goto fail;
    if (rd_u16(r, &n) < 0) goto trunc;
    results = PyList_New(n);
    if (!results) goto fail;
    for (i = 0; i < n; i++) {
        PyObject *inl = NULL, *seg = NULL, *children = NULL, *res;
        uint64_t size;
        uint16_t nc, j;
        if (!(inl = rd_obytes(r))) goto fail;
        if (!(seg = rd_ostr(r))) {
            Py_DECREF(inl);
            goto fail;
        }
        if (rd_u64(r, &size) < 0 || rd_u16(r, &nc) < 0) {
            Py_DECREF(inl);
            Py_DECREF(seg);
            goto trunc;
        }
        children = PyTuple_New(nc);
        if (!children) {
            Py_DECREF(inl);
            Py_DECREF(seg);
            goto fail;
        }
        for (j = 0; j < nc; j++) {
            PyObject *c = rd_bstr(r);
            if (!c) {
                Py_DECREF(inl);
                Py_DECREF(seg);
                Py_DECREF(children);
                goto fail;
            }
            PyTuple_SET_ITEM(children, j, c);
        }
        res = PyTuple_New(4);
        if (!res) {
            Py_DECREF(inl);
            Py_DECREF(seg);
            Py_DECREF(children);
            goto fail;
        }
        PyTuple_SET_ITEM(res, 0, inl);
        PyTuple_SET_ITEM(res, 1, seg);
        PyTuple_SET_ITEM(res, 2, PyLong_FromUnsignedLongLong(size));
        PyTuple_SET_ITEM(res, 3, children);
        PyList_SET_ITEM(results, i, res);
    }
    out = PyTuple_New(4);
    if (!out) goto fail;
    PyTuple_SET_ITEM(out, 0, PyLong_FromLong(K_REPLY));
    PyTuple_SET_ITEM(out, 1, PyLong_FromUnsignedLong(req_id));
    PyTuple_SET_ITEM(out, 2, err);
    PyTuple_SET_ITEM(out, 3, results);
    return out;
trunc:
    PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
fail:
    Py_XDECREF(err);
    Py_XDECREF(results);
    return NULL;
}

static PyObject *dec_rdy(Rd *r) {
    uint16_t n, i;
    PyObject *ids, *out;
    if (rd_u16(r, &n) < 0) {
        PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
        return NULL;
    }
    ids = PyTuple_New(n);
    if (!ids) return NULL;
    for (i = 0; i < n; i++) {
        PyObject *o = rd_bstr(r);
        if (!o) {
            Py_DECREF(ids);
            return NULL;
        }
        PyTuple_SET_ITEM(ids, i, o);
    }
    out = Py_BuildValue("(sN)", "RDY", ids);
    return out;
}

static PyObject *dec_batch(Rd *r) {
    uint32_t n, i;
    PyObject *list, *out;
    if (rd_u32(r, &n) < 0) {
        PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
        return NULL;
    }
    list = PyList_New(n);
    if (!list) return NULL;
    for (i = 0; i < n; i++) {
        uint8_t fast;
        PyObject *el;
        if (rd_u8(r, &fast) < 0) {
            Py_DECREF(list);
            PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
            return NULL;
        }
        if (fast) {
            el = dec_frame(r);
        } else {
            PyObject *pk = rd_bstr(r);
            if (!pk) {
                Py_DECREF(list);
                return NULL;
            }
            el = PyObject_CallFunctionObjArgs(g_pickle_loads, pk, NULL);
            Py_DECREF(pk);
        }
        if (!el) {
            Py_DECREF(list);
            return NULL;
        }
        PyList_SET_ITEM(list, i, el);
    }
    out = Py_BuildValue("(sN)", "B", list);
    return out;
}

static PyObject *dec_frame(Rd *r) {
    uint8_t kind;
    if (rd_u8(r, &kind) < 0) {
        PyErr_SetString(PyExc_ValueError, "fastpath: truncated frame");
        return NULL;
    }
    switch (kind) {
    case K_CALL:
        return dec_call(r);
    case K_REPLY:
        return dec_reply(r);
    case K_BATCH:
        return dec_batch(r);
    case K_RDY:
        return dec_rdy(r);
    default:
        PyErr_Format(PyExc_ValueError, "fastpath: bad frame kind %d", kind);
        return NULL;
    }
}

static PyObject *py_decode(PyObject *self, PyObject *arg) {
    Py_buffer view;
    Rd r;
    uint8_t magic;
    PyObject *out;
    (void)self;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    r.p = (const char *)view.buf;
    r.len = view.len;
    r.off = 0;
    if (rd_u8(&r, &magic) < 0 || magic != MAGIC) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "fastpath: bad magic");
        return NULL;
    }
    out = dec_frame(&r);
    PyBuffer_Release(&view);
    return out;
}

/* -------------------------------------------------------- return_oids
 * ObjectID.bytes_for_return: 12-byte task-id prefix + u32 LE index.
 * (ids.py bytes_for_return; reference: id.h ObjectID::ForTaskReturn.)
 */
static PyObject *py_return_oids(PyObject *self, PyObject *args) {
    const char *tid;
    Py_ssize_t tid_len;
    long n, i;
    PyObject *list;
    char tmp[16];
    (void)self;
    if (!PyArg_ParseTuple(args, "y#l", &tid, &tid_len, &n)) return NULL;
    if (tid_len < 12) {
        PyErr_SetString(PyExc_ValueError, "task id too short");
        return NULL;
    }
    list = PyList_New(n);
    if (!list) return NULL;
    memcpy(tmp, tid, 12);
    for (i = 0; i < n; i++) {
        uint32_t idx = (uint32_t)i;
        PyObject *o;
        memcpy(tmp + 12, &idx, 4);
        o = PyBytes_FromStringAndSize(tmp, 16);
        if (!o) {
            Py_DECREF(list);
            return NULL;
        }
        PyList_SET_ITEM(list, i, o);
    }
    return list;
}

/* ------------------------------------------------------ wait_partition
 * The drain-by-wait hot loop: split refs into (ready, rest) against the
 * client's ready-set, reading ref._id._bytes without interpreter
 * dispatch. Returns None when fewer than num_returns are ready (caller
 * parks on the condvar).
 */
static PyObject *s_id;     /* "_id" */
static PyObject *s_bytes;  /* "_bytes" */

static PyObject *py_wait_partition(PyObject *self, PyObject *args) {
    PyObject *refs, *ready_set;
    long num_returns;
    PyObject *seq = NULL, *ready = NULL, *rest = NULL, *out = NULL;
    Py_ssize_t n, i;
    long nready = 0;
    (void)self;
    if (!PyArg_ParseTuple(args, "OOl", &refs, &ready_set, &num_returns))
        return NULL;
    seq = PySequence_Fast(refs, "refs must be a sequence");
    if (!seq) return NULL;
    n = PySequence_Fast_GET_SIZE(seq);
    ready = PyList_New(0);
    rest = PyList_New(0);
    if (!ready || !rest) goto fail;
    for (i = 0; i < n; i++) {
        PyObject *ref = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *idobj, *idbytes;
        int hit = 0;
        idobj = PyObject_GetAttr(ref, s_id);
        if (!idobj) goto fail;
        idbytes = PyObject_GetAttr(idobj, s_bytes);
        Py_DECREF(idobj);
        if (!idbytes) goto fail;
        if (nready < num_returns) {
            hit = PySet_Contains(ready_set, idbytes);
            if (hit < 0) {
                Py_DECREF(idbytes);
                goto fail;
            }
        }
        Py_DECREF(idbytes);
        if (hit) {
            if (PyList_Append(ready, ref) < 0) goto fail;
            nready++;
        } else {
            if (PyList_Append(rest, ref) < 0) goto fail;
        }
    }
    if (nready < num_returns) {
        Py_DECREF(ready);
        Py_DECREF(rest);
        Py_DECREF(seq);
        Py_RETURN_NONE;
    }
    out = PyTuple_New(2);
    if (!out) goto fail;
    PyTuple_SET_ITEM(out, 0, ready);
    PyTuple_SET_ITEM(out, 1, rest);
    Py_DECREF(seq);
    return out;
fail:
    Py_XDECREF(ready);
    Py_XDECREF(rest);
    Py_XDECREF(seq);
    Py_XDECREF(out);
    return NULL;
}

/* ------------------------------------------------------------ module */

static PyMethodDef methods[] = {
    {"encode", py_encode, METH_O,
     "encode(frame) -> bytes | None (None: shape unsupported, pickle it)"},
    {"decode", py_decode, METH_O,
     "decode(buf) -> frame structure (first byte must be the 0xF1 magic)"},
    {"return_oids", py_return_oids, METH_VARARGS,
     "return_oids(task_id, n) -> [oid bytes] (12B prefix + u32 LE index)"},
    {"wait_partition", py_wait_partition, METH_VARARGS,
     "wait_partition(refs, ready_set, num_returns) -> (ready, rest)|None"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastpath",
    "Native control-plane hot path (frame codec, oid gen, wait partition)",
    -1, methods, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit_fastpath(void) {
    PyObject *m, *pickle;
    m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    pickle = PyImport_ImportModule("pickle");
    if (!pickle) return NULL;
    g_pickle_dumps = PyObject_GetAttrString(pickle, "dumps");
    g_pickle_loads = PyObject_GetAttrString(pickle, "loads");
    Py_DECREF(pickle);
    if (!g_pickle_dumps || !g_pickle_loads) return NULL;
    g_proto5 = PyLong_FromLong(5);
    s_id = PyUnicode_InternFromString("_id");
    s_bytes = PyUnicode_InternFromString("_bytes");
    if (!g_proto5 || !s_id || !s_bytes) return NULL;
    return m;
}
