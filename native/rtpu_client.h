/* rtpu_client: minimal C ABI client for ray_tpu's direct call plane.
 *
 * Reference parity note: the reference ships a full C++ worker API
 * (cpp/, 9.1k LoC) that can host actors and submit arbitrary tasks.
 * ray_tpu's compute path is jax/Python by design, so the C surface
 * targets the embed case instead: a C/C++ service calling methods on
 * an already-deployed actor over the worker's direct socket — the
 * same typed binary frames the Python fast path uses (native/
 * fastpath.c CALL/REPLY layout), with no Python dependency.
 *
 * Capabilities:
 *   - connect to a worker direct socket (unix path + session authkey;
 *     the 1-RTT HMAC-SHA256 token handshake from transport.py)
 *   - call an actor method with positional args of simple types
 *     (none/bool/int/double/str/bytes)
 *   - receive inline results of the same simple types; larger or
 *     richer results are surfaced as the raw serialized blob
 *     (RTPU_VAL_OPAQUE) for the caller to hand to a Python helper.
 *
 * Thread-safety: one rtpu_conn per thread (calls are synchronous
 * request/reply on one socket).
 */
#ifndef RTPU_CLIENT_H
#define RTPU_CLIENT_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct rtpu_conn rtpu_conn;

/* Value kinds for args and results. */
typedef enum {
    RTPU_VAL_NONE = 0,
    RTPU_VAL_BOOL = 1,
    RTPU_VAL_INT = 2,     /* int64 */
    RTPU_VAL_FLOAT = 3,   /* double */
    RTPU_VAL_STR = 4,     /* utf-8, data/len */
    RTPU_VAL_BYTES = 5,   /* data/len */
    RTPU_VAL_OPAQUE = 6,  /* raw serialized value (results only) */
} rtpu_val_kind;

typedef struct {
    rtpu_val_kind kind;
    int64_t i;           /* BOOL/INT */
    double f;            /* FLOAT */
    const uint8_t *data; /* STR/BYTES/OPAQUE (result: owned by reply) */
    size_t len;
} rtpu_value;

/* Returns NULL on failure and fills err (NUL-terminated). authkey is
 * the session key (ray_tpu exports it hex; pass raw bytes here). */
rtpu_conn *rtpu_connect(const char *unix_path, const uint8_t *authkey,
                        size_t authkey_len, char *err, size_t errlen);

void rtpu_close(rtpu_conn *c);

/* Synchronous actor method call. aid = 16-byte actor id. args is an
 * array of nargs rtpu_value (STR/BYTES point into caller memory).
 * On success returns 0 and fills *result; STR/BYTES/OPAQUE result data
 * stays valid until the next call on this conn. On application error
 * returns RTPU_ERR_REMOTE and fills err with the remote error text if
 * extractable. */
#define RTPU_OK 0
#define RTPU_ERR_IO (-1)
#define RTPU_ERR_PROTO (-2)
#define RTPU_ERR_REMOTE (-3)

int rtpu_actor_call(rtpu_conn *c, const uint8_t aid[16],
                    const char *method, const rtpu_value *args,
                    size_t nargs, rtpu_value *result, char *err,
                    size_t errlen);

#ifdef __cplusplus
}
#endif
#endif
