/* C ABI client for the ray_tpu direct call plane. See rtpu_client.h.
 *
 * Wire stack, bottom to top (all reimplemented here, no deps):
 *   unix stream socket
 *   multiprocessing.connection framing: u32 big-endian length prefix
 *   1-RTT HMAC-SHA256 token handshake (transport.py unix scheme)
 *   fastpath.c typed frames (0xF1 magic, K_CALL/K_REPLY)
 *   serialization.py value layout ("RTPUOBJ1" header + pickle)
 *   a pickle protocol-3 writer / protocol-5 reader for simple values
 */
#include "rtpu_client.h"

#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <sys/random.h>
#include <unistd.h>

/* ============================== SHA-256 ============================== */

typedef struct {
    uint32_t h[8];
    uint64_t nbytes;
    uint8_t block[64];
    size_t fill;
} sha256_ctx;

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_init(sha256_ctx *c) {
    static const uint32_t iv[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
    };
    memcpy(c->h, iv, sizeof iv);
    c->nbytes = 0;
    c->fill = 0;
}

static void sha256_block(sha256_ctx *c, const uint8_t *p) {
    uint32_t w[64], a, b, d, e, f, g, hh, t1, t2, cc;
    int i;
    for (i = 0; i < 16; i++)
        w[i] = (uint32_t)p[4 * i] << 24 | (uint32_t)p[4 * i + 1] << 16 |
               (uint32_t)p[4 * i + 2] << 8 | p[4 * i + 3];
    for (; i < 64; i++) {
        uint32_t s0 = ROR(w[i - 15], 7) ^ ROR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = ROR(w[i - 2], 17) ^ ROR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    a = c->h[0]; b = c->h[1]; cc = c->h[2]; d = c->h[3];
    e = c->h[4]; f = c->h[5]; g = c->h[6]; hh = c->h[7];
    for (i = 0; i < 64; i++) {
        uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        t1 = hh + S1 + ch + K256[i] + w[i];
        uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
        uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
        t2 = S0 + maj;
        hh = g; g = f; f = e; e = d + t1;
        d = cc; cc = b; b = a; a = t1 + t2;
    }
    c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
    c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += hh;
}

static void sha256_update(sha256_ctx *c, const void *data, size_t n) {
    const uint8_t *p = data;
    c->nbytes += n;
    if (c->fill) {
        while (n && c->fill < 64) { c->block[c->fill++] = *p++; n--; }
        if (c->fill == 64) { sha256_block(c, c->block); c->fill = 0; }
    }
    while (n >= 64) { sha256_block(c, p); p += 64; n -= 64; }
    while (n) { c->block[c->fill++] = *p++; n--; }
}

static void sha256_final(sha256_ctx *c, uint8_t out[32]) {
    uint64_t bits = c->nbytes * 8;
    uint8_t pad = 0x80, zero = 0, lenb[8];
    int i;
    sha256_update(c, &pad, 1);
    while (c->fill != 56) sha256_update(c, &zero, 1);
    for (i = 0; i < 8; i++) lenb[i] = (uint8_t)(bits >> (56 - 8 * i));
    sha256_update(c, lenb, 8);
    for (i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)(c->h[i] >> 24);
        out[4 * i + 1] = (uint8_t)(c->h[i] >> 16);
        out[4 * i + 2] = (uint8_t)(c->h[i] >> 8);
        out[4 * i + 3] = (uint8_t)c->h[i];
    }
}

static void hmac_sha256(const uint8_t *key, size_t keylen,
                        const uint8_t *msg, size_t msglen, uint8_t out[32]) {
    uint8_t kblock[64], pad[64], khash[32];
    sha256_ctx c;
    size_t i;
    if (keylen > 64) {
        sha256_init(&c);
        sha256_update(&c, key, keylen);
        sha256_final(&c, khash);
        key = khash;
        keylen = 32;
    }
    memset(kblock, 0, 64);
    memcpy(kblock, key, keylen);
    for (i = 0; i < 64; i++) pad[i] = kblock[i] ^ 0x36;
    sha256_init(&c);
    sha256_update(&c, pad, 64);
    sha256_update(&c, msg, msglen);
    sha256_final(&c, out);
    for (i = 0; i < 64; i++) pad[i] = kblock[i] ^ 0x5c;
    sha256_init(&c);
    sha256_update(&c, pad, 64);
    sha256_update(&c, out, 32);
    sha256_final(&c, out);
}

/* ======================= socket + mp framing ======================== */

struct rtpu_conn {
    int fd;
    uint32_t req_id;
    uint8_t *reply;      /* last raw reply frame (owns result memory) */
    size_t reply_len;
    char strerr[256];
};

static int set_err(char *err, size_t errlen, const char *msg) {
    if (err && errlen) {
        strncpy(err, msg, errlen - 1);
        err[errlen - 1] = 0;
    }
    return -1;
}

static int read_full(int fd, void *buf, size_t n) {
    uint8_t *p = buf;
    while (n) {
        ssize_t r = read(fd, p, n);
        if (r <= 0) {
            if (r < 0 && errno == EINTR) continue;
            return -1;
        }
        p += r;
        n -= (size_t)r;
    }
    return 0;
}

static int write_full(int fd, const void *buf, size_t n) {
    const uint8_t *p = buf;
    while (n) {
        ssize_t r = write(fd, p, n);
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        p += r;
        n -= (size_t)r;
    }
    return 0;
}

/* multiprocessing.connection: u32 BIG-endian length, then payload. */
static int mp_send(int fd, const uint8_t *payload, size_t n) {
    uint8_t hdr[4] = {
        (uint8_t)(n >> 24), (uint8_t)(n >> 16), (uint8_t)(n >> 8), (uint8_t)n,
    };
    if (write_full(fd, hdr, 4)) return -1;
    return write_full(fd, payload, n);
}

static int mp_recv(int fd, uint8_t **out, size_t *outlen) {
    uint8_t hdr[4];
    if (read_full(fd, hdr, 4)) return -1;
    uint32_t n = (uint32_t)hdr[0] << 24 | (uint32_t)hdr[1] << 16 |
                 (uint32_t)hdr[2] << 8 | hdr[3];
    if (n == 0xFFFFFFFF) return -1; /* >2GB extension: not for replies */
    uint8_t *buf = malloc(n ? n : 1);
    if (!buf) return -1;
    if (read_full(fd, buf, n)) { free(buf); return -1; }
    *out = buf;
    *outlen = n;
    return 0;
}

/* ========================= pickle writer ============================ */
/* Protocol 3: BINUNICODE/SHORT_BINBYTES are available and the server's
 * pickle.loads accepts any protocol <= its own. */

typedef struct {
    uint8_t *p;
    size_t len, cap;
} wbuf;

static int wb_put(wbuf *b, const void *data, size_t n) {
    if (b->len + n > b->cap) {
        size_t cap = b->cap * 2 + n + 64;
        uint8_t *q = realloc(b->p, cap);
        if (!q) return -1;
        b->p = q;
        b->cap = cap;
    }
    memcpy(b->p + b->len, data, n);
    b->len += n;
    return 0;
}

static int wb_u8(wbuf *b, uint8_t v) { return wb_put(b, &v, 1); }

static int wb_u32le(wbuf *b, uint32_t v) {
    uint8_t x[4] = {(uint8_t)v, (uint8_t)(v >> 8), (uint8_t)(v >> 16),
                    (uint8_t)(v >> 24)};
    return wb_put(b, x, 4);
}

static int pkl_value(wbuf *b, const rtpu_value *v) {
    switch (v->kind) {
    case RTPU_VAL_NONE:
        return wb_u8(b, 'N');
    case RTPU_VAL_BOOL:
        return wb_u8(b, v->i ? 0x88 : 0x89); /* NEWTRUE/NEWFALSE */
    case RTPU_VAL_INT:
        if (v->i >= -2147483648LL && v->i <= 2147483647LL) {
            if (wb_u8(b, 'J')) return -1; /* BININT i32 LE */
            return wb_u32le(b, (uint32_t)(int32_t)v->i);
        } else {
            /* LONG1: u8 nbytes + LE two's-complement */
            uint8_t tmp[9];
            int n = 0;
            int64_t x = v->i;
            do {
                tmp[n++] = (uint8_t)x;
                x >>= 8;
            } while (n < 8 && x != 0 && x != -1);
            /* sign byte if top bit disagrees with sign */
            if ((v->i >= 0 && (tmp[n - 1] & 0x80)) ||
                (v->i < 0 && !(tmp[n - 1] & 0x80)))
                tmp[n++] = v->i < 0 ? 0xFF : 0x00;
            if (wb_u8(b, 0x8a) || wb_u8(b, (uint8_t)n)) return -1;
            return wb_put(b, tmp, (size_t)n);
        }
    case RTPU_VAL_FLOAT: {
        uint64_t bits;
        uint8_t be[8];
        int i;
        memcpy(&bits, &v->f, 8);
        for (i = 0; i < 8; i++) be[i] = (uint8_t)(bits >> (56 - 8 * i));
        if (wb_u8(b, 'G')) return -1; /* BINFLOAT, big-endian */
        return wb_put(b, be, 8);
    }
    case RTPU_VAL_STR:
        if (wb_u8(b, 'X') || wb_u32le(b, (uint32_t)v->len)) return -1;
        return wb_put(b, v->data, v->len);
    case RTPU_VAL_BYTES:
        if (v->len < 256) {
            if (wb_u8(b, 'C') || wb_u8(b, (uint8_t)v->len)) return -1;
        } else {
            if (wb_u8(b, 'B') || wb_u32le(b, (uint32_t)v->len)) return -1;
        }
        return wb_put(b, v->data, v->len);
    default:
        return -1; /* OPAQUE not valid as an argument */
    }
}

/* pickle of ((args...), {}) — what serialization.unpack returns as
 * (args, kwargs). */
static int pkl_args(wbuf *b, const rtpu_value *args, size_t nargs) {
    size_t i;
    if (wb_u8(b, 0x80) || wb_u8(b, 3)) return -1; /* PROTO 3 */
    if (nargs == 0) {
        if (wb_u8(b, ')')) return -1; /* EMPTY_TUPLE */
    } else if (nargs <= 3) {
        for (i = 0; i < nargs; i++)
            if (pkl_value(b, &args[i])) return -1;
        if (wb_u8(b, (uint8_t)(0x85 + nargs - 1))) return -1; /* TUPLE1-3 */
    } else {
        if (wb_u8(b, '(')) return -1; /* MARK */
        for (i = 0; i < nargs; i++)
            if (pkl_value(b, &args[i])) return -1;
        if (wb_u8(b, 't')) return -1; /* TUPLE */
    }
    if (wb_u8(b, '}')) return -1;    /* EMPTY_DICT (kwargs) */
    if (wb_u8(b, 0x86)) return -1;   /* TUPLE2 */
    return wb_u8(b, '.');            /* STOP */
}

/* ========================= pickle reader ============================ */
/* Protocol-5 subset for simple scalar results; anything richer falls
 * back to RTPU_VAL_OPAQUE with the raw serialized blob. */

static int pkl_read_value(const uint8_t *p, size_t n, rtpu_value *out) {
    size_t off = 0;
    int have = 0;
    memset(out, 0, sizeof *out);
    while (off < n) {
        uint8_t op = p[off++];
        switch (op) {
        case 0x80: /* PROTO */
            if (off + 1 > n) return -1;
            off += 1;
            break;
        case 0x95: /* FRAME (proto 4+) */
            if (off + 8 > n) return -1;
            off += 8;
            break;
        case 0x94: /* MEMOIZE */
            break;
        case 'N':
            out->kind = RTPU_VAL_NONE;
            have = 1;
            break;
        case 0x88:
        case 0x89:
            out->kind = RTPU_VAL_BOOL;
            out->i = (op == 0x88);
            have = 1;
            break;
        case 'K': /* BININT1 */
            if (off + 1 > n) return -1;
            out->kind = RTPU_VAL_INT;
            out->i = p[off];
            off += 1;
            have = 1;
            break;
        case 'M': /* BININT2 */
            if (off + 2 > n) return -1;
            out->kind = RTPU_VAL_INT;
            out->i = (int64_t)p[off] | ((int64_t)p[off + 1] << 8);
            off += 2;
            have = 1;
            break;
        case 'J': /* BININT i32 LE */
            if (off + 4 > n) return -1;
            out->kind = RTPU_VAL_INT;
            out->i = (int32_t)((uint32_t)p[off] | ((uint32_t)p[off + 1] << 8) |
                               ((uint32_t)p[off + 2] << 16) |
                               ((uint32_t)p[off + 3] << 24));
            off += 4;
            have = 1;
            break;
        case 0x8a: { /* LONG1 */
            if (off + 1 > n) return -1;
            uint8_t ln = p[off++];
            if (ln > 8 || off + ln > n) return -1;
            int64_t v = 0;
            int i;
            for (i = 0; i < ln; i++) v |= (int64_t)p[off + i] << (8 * i);
            if (ln && ln < 8 && (p[off + ln - 1] & 0x80))
                v -= (int64_t)1 << (8 * ln); /* sign-extend */
            out->kind = RTPU_VAL_INT;
            out->i = v;
            off += ln;
            have = 1;
            break;
        }
        case 'G': { /* BINFLOAT BE */
            if (off + 8 > n) return -1;
            uint64_t bits = 0;
            int i;
            for (i = 0; i < 8; i++) bits = (bits << 8) | p[off + i];
            memcpy(&out->f, &bits, 8);
            out->kind = RTPU_VAL_FLOAT;
            off += 8;
            have = 1;
            break;
        }
        case 0x8c: { /* SHORT_BINUNICODE */
            if (off + 1 > n) return -1;
            uint8_t ln = p[off++];
            if (off + ln > n) return -1;
            out->kind = RTPU_VAL_STR;
            out->data = p + off;
            out->len = ln;
            off += ln;
            have = 1;
            break;
        }
        case 'X': { /* BINUNICODE u32 LE */
            if (off + 4 > n) return -1;
            uint32_t ln = (uint32_t)p[off] | ((uint32_t)p[off + 1] << 8) |
                          ((uint32_t)p[off + 2] << 16) |
                          ((uint32_t)p[off + 3] << 24);
            off += 4;
            if (off + ln > n) return -1;
            out->kind = RTPU_VAL_STR;
            out->data = p + off;
            out->len = ln;
            off += ln;
            have = 1;
            break;
        }
        case 'C': { /* SHORT_BINBYTES */
            if (off + 1 > n) return -1;
            uint8_t ln = p[off++];
            if (off + ln > n) return -1;
            out->kind = RTPU_VAL_BYTES;
            out->data = p + off;
            out->len = ln;
            off += ln;
            have = 1;
            break;
        }
        case 'B': { /* BINBYTES u32 LE */
            if (off + 4 > n) return -1;
            uint32_t ln = (uint32_t)p[off] | ((uint32_t)p[off + 1] << 8) |
                          ((uint32_t)p[off + 2] << 16) |
                          ((uint32_t)p[off + 3] << 24);
            off += 4;
            if (off + ln > n) return -1;
            out->kind = RTPU_VAL_BYTES;
            out->data = p + off;
            out->len = ln;
            off += ln;
            have = 1;
            break;
        }
        case '.': /* STOP */
            return have ? 0 : -1;
        default:
            return -1; /* containers, reduce, memo refs: opaque */
        }
    }
    return -1;
}

/* ======================= fastpath frame codec ======================= */

#define MAGIC_BYTE 0xF1
#define K_CALL 1
#define K_REPLY 2

static int frame_bstr(wbuf *b, const uint8_t *data, size_t n) {
    if (wb_u32le(b, (uint32_t)n)) return -1;
    return wb_put(b, data, n);
}

/* ============================== API ================================ */

rtpu_conn *rtpu_connect(const char *unix_path, const uint8_t *authkey,
                        size_t authkey_len, char *err, size_t errlen) {
    static const char CLIENT_TAG[] = "rtpu-conn-auth-v1:client";
    static const char SERVER_TAG[] = "rtpu-conn-auth-v1:server";
    uint8_t tok[32], want[32], *srv = NULL;
    size_t srvlen = 0;
    struct sockaddr_un sa;
    rtpu_conn *c;
    int fd;

    if (strlen(unix_path) >= sizeof sa.sun_path) {
        set_err(err, errlen, "socket path too long");
        return NULL;
    }
    fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) {
        set_err(err, errlen, "socket() failed");
        return NULL;
    }
    memset(&sa, 0, sizeof sa);
    sa.sun_family = AF_UNIX;
    strcpy(sa.sun_path, unix_path);
    if (connect(fd, (struct sockaddr *)&sa, sizeof sa)) {
        close(fd);
        set_err(err, errlen, "connect() failed");
        return NULL;
    }
    /* 1-RTT token handshake (transport.py unix scheme). */
    hmac_sha256(authkey, authkey_len, (const uint8_t *)CLIENT_TAG,
                sizeof CLIENT_TAG - 1, tok);
    if (mp_send(fd, tok, 32) || mp_recv(fd, &srv, &srvlen)) {
        close(fd);
        set_err(err, errlen, "handshake I/O failed");
        return NULL;
    }
    hmac_sha256(authkey, authkey_len, (const uint8_t *)SERVER_TAG,
                sizeof SERVER_TAG - 1, want);
    if (srvlen != 32 || memcmp(srv, want, 32) != 0) {
        free(srv);
        close(fd);
        set_err(err, errlen, "server failed auth");
        return NULL;
    }
    free(srv);
    c = calloc(1, sizeof *c);
    if (!c) {
        close(fd);
        set_err(err, errlen, "oom");
        return NULL;
    }
    c->fd = fd;
    c->req_id = 1;
    return c;
}

void rtpu_close(rtpu_conn *c) {
    if (!c) return;
    close(c->fd);
    free(c->reply);
    free(c);
}

/* Parse one obytes/ostr: returns 0, fills data+len (NULL if absent). */
static int rd_opt(const uint8_t **pp, const uint8_t *end,
                  const uint8_t **data, size_t *len) {
    const uint8_t *p = *pp;
    if (p >= end) return -1;
    if (*p == 0) {
        *data = NULL;
        *len = 0;
        *pp = p + 1;
        return 0;
    }
    p += 1;
    if (p + 4 > end) return -1;
    uint32_t n = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                 ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
    p += 4;
    if (p + n > end) return -1;
    *data = p;
    *len = n;
    *pp = p + n;
    return 0;
}

int rtpu_actor_call(rtpu_conn *c, const uint8_t aid[16], const char *method,
                    const rtpu_value *args, size_t nargs, rtpu_value *result,
                    char *err, size_t errlen) {
    wbuf pkl = {0}, frame = {0};
    uint8_t tid[16];
    uint32_t req = c->req_id++;
    size_t mlen = strlen(method);

    if (getentropy(tid, 16)) {
        /* extremely unlikely; derive from req counter */
        memset(tid, 0, 16);
        memcpy(tid, &req, 4);
    }
    /* args blob: serialization layout with zero out-of-band buffers. */
    if (pkl_args(&pkl, args, nargs)) {
        free(pkl.p);
        return set_err(err, errlen, "unsupported argument kind");
    }
    uint8_t hdr[16];
    memcpy(hdr, "RTPUOBJ1", 8);
    uint32_t plen = (uint32_t)pkl.len;
    hdr[8] = (uint8_t)plen; hdr[9] = (uint8_t)(plen >> 8);
    hdr[10] = (uint8_t)(plen >> 16); hdr[11] = (uint8_t)(plen >> 24);
    memset(hdr + 12, 0, 4); /* nbuffers = 0 */

    /* CALL frame (fastpath.c layout). */
    int bad = 0;
    bad |= wb_u8(&frame, MAGIC_BYTE);
    bad |= wb_u8(&frame, K_CALL);
    bad |= wb_u32le(&frame, req);
    bad |= frame_bstr(&frame, tid, 16);           /* bstr tid */
    bad |= wb_u8(&frame, 0);                      /* obytes fid: None */
    bad |= wb_u8(&frame, 1);                      /* ostr method present */
    bad |= wb_u32le(&frame, (uint32_t)mlen);
    bad |= wb_put(&frame, method, mlen);
    bad |= wb_u32le(&frame, (uint32_t)(16 + pkl.len)); /* bstr args */
    bad |= wb_put(&frame, hdr, 16);
    bad |= wb_put(&frame, pkl.p, pkl.len);
    bad |= wb_u32le(&frame, 1);                   /* nret */
    bad |= wb_u8(&frame, 1);                      /* obytes aid present */
    bad |= wb_u32le(&frame, 16);
    bad |= wb_put(&frame, aid, 16);
    bad |= wb_u8(&frame, 0);                      /* ostr cgroup: None */
    free(pkl.p);
    if (bad) {
        free(frame.p);
        return set_err(err, errlen, "oom");
    }
    int rc = mp_send(c->fd, frame.p, frame.len);
    free(frame.p);
    if (rc) return set_err(err, errlen, "send failed"), RTPU_ERR_IO;

    /* Reply: skip frames until our req_id (RDY pushes may interleave). */
    for (;;) {
        uint8_t *buf;
        size_t n;
        if (mp_recv(c->fd, &buf, &n))
            return set_err(err, errlen, "recv failed"), RTPU_ERR_IO;
        if (n < 6 || buf[0] != MAGIC_BYTE || buf[1] != K_REPLY) {
            free(buf); /* not a reply (readiness push etc.): skip */
            continue;
        }
        uint32_t rid = (uint32_t)buf[2] | ((uint32_t)buf[3] << 8) |
                       ((uint32_t)buf[4] << 16) | ((uint32_t)buf[5] << 24);
        if (rid != req) {
            free(buf);
            continue;
        }
        free(c->reply);
        c->reply = buf;
        c->reply_len = n;
        const uint8_t *p = buf + 6, *end = buf + n;
        const uint8_t *eblob, *inline_b, *segment;
        size_t eblen, inlen, seglen;
        if (rd_opt(&p, end, &eblob, &eblen))
            return set_err(err, errlen, "bad reply"), RTPU_ERR_PROTO;
        if (eblob != NULL) {
            /* Remote exception: serialized RayTaskError/RayActorError.
             * Surface the raw blob so a Python helper can rehydrate. */
            if (result) {
                memset(result, 0, sizeof *result);
                result->kind = RTPU_VAL_OPAQUE;
                result->data = eblob;
                result->len = eblen;
            }
            set_err(err, errlen, "remote task error (serialized blob "
                                 "in result)");
            return RTPU_ERR_REMOTE;
        }
        if (p + 2 > end)
            return set_err(err, errlen, "bad reply"), RTPU_ERR_PROTO;
        uint16_t nres = (uint16_t)(p[0] | (p[1] << 8));
        p += 2;
        if (nres < 1)
            return set_err(err, errlen, "empty reply"), RTPU_ERR_PROTO;
        if (rd_opt(&p, end, &inline_b, &inlen))
            return set_err(err, errlen, "bad reply"), RTPU_ERR_PROTO;
        if (rd_opt(&p, end, &segment, &seglen))
            return set_err(err, errlen, "bad reply"), RTPU_ERR_PROTO;
        if (inline_b == NULL) {
            /* Sealed into the shared store: out of scope for the C
             * embed client (results must fit inline). */
            return set_err(err, errlen,
                           "result in shared segment; use the Python "
                           "client for large results"),
                   RTPU_ERR_PROTO;
        }
        /* inline blob = serialization layout; parse header. */
        if (inlen < 16 || memcmp(inline_b, "RTPUOBJ1", 8) != 0)
            return set_err(err, errlen, "bad value header"), RTPU_ERR_PROTO;
        uint32_t vplen = (uint32_t)inline_b[8] | ((uint32_t)inline_b[9] << 8) |
                         ((uint32_t)inline_b[10] << 16) |
                         ((uint32_t)inline_b[11] << 24);
        uint32_t nbuf = (uint32_t)inline_b[12] | ((uint32_t)inline_b[13] << 8) |
                        ((uint32_t)inline_b[14] << 16) |
                        ((uint32_t)inline_b[15] << 24);
        const uint8_t *pp = inline_b + 16 + 8 * (size_t)nbuf;
        if (pp + vplen > inline_b + inlen)
            return set_err(err, errlen, "bad value header"), RTPU_ERR_PROTO;
        if (result) {
            if (nbuf != 0 || pkl_read_value(pp, vplen, result)) {
                /* Rich value (container, ndarray, custom class): give
                 * the caller the raw serialized blob. */
                memset(result, 0, sizeof *result);
                result->kind = RTPU_VAL_OPAQUE;
                result->data = inline_b;
                result->len = inlen;
            }
        }
        return RTPU_OK;
    }
}
