// Multi-process crash/stress harness for the pool store.
//
// Reference behavior being defended: the plasma store survives client
// crashes (the reference runs its object-store tests under ASAN/TSAN —
// .bazelrc:104-126). Here: N writers + N readers hammer one pool while
// a victim writer is SIGKILLed mid-operation (often while holding the
// process-shared robust mutex); every round the parent then proves the
// pool is still consistent and usable — the EOWNERDEAD recovery path,
// the boundary-tag allocator, and the shared refcounts all hold.
//
// Build: make stress | stress-asan | stress-tsan  (native/Makefile)
// Run:   store_stress [rounds=5] [writers=4]
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <sys/wait.h>
#include <unistd.h>
#include <signal.h>

#include "store.cpp"

static void make_id(uint8_t* id, int writer, int counter) {
  memset(id, 0, 16);
  id[0] = (uint8_t)(writer + 1);
  memcpy(id + 1, &counter, sizeof(counter));
}

static int writer_proc(const char* pool, int idx, int ops, bool victim) {
  uint64_t h = store_attach(pool);
  if (!h) return 2;
  uint8_t* base = (uint8_t*)((Store*)h)->base;  // payload writes
  srand(getpid());
  for (int i = 0; i < ops; i++) {
    uint8_t id[16];
    make_id(id, idx, i);
    uint64_t size = 256 + (rand() % 4096);
    int32_t err = 0;
    uint64_t off = store_create_object(h, id, size, &err);
    if (off) {
      memset(base + off, (uint8_t)(idx + 1), size);
      store_seal(h, id);
    }
    if (i >= 8 && (rand() % 4) == 0) {
      uint8_t old_id[16];
      make_id(old_id, idx, i - 8);
      store_delete(h, old_id);
    }
    if (victim && i == ops / 2) {
      // Die without warning, plausibly inside the critical section of
      // a concurrent create on another iteration's timing.
      kill(getpid(), SIGKILL);
    }
  }
  store_detach(h);
  return 0;
}

static int reader_proc(const char* pool, int writers, int ops) {
  uint64_t h = store_attach(pool);
  if (!h) return 2;
  uint8_t* base = (uint8_t*)((Store*)h)->base;
  srand(getpid() * 7);
  for (int i = 0; i < ops; i++) {
    uint8_t id[16];
    int w = rand() % writers;
    make_id(id, w, rand() % 64);
    uint64_t off = 0, size = 0;
    if (store_get(h, id, &off, &size) == 0) {
      // Sealed data must carry the writer's fill byte throughout.
      uint8_t want = (uint8_t)(w + 1);
      for (uint64_t j = 0; j < size; j += 97) {
        if (base[off + j] != want) {
          fprintf(stderr, "CORRUPTION: id w%d obj, byte %lu = %u != %u\n",
                  w, (unsigned long)j, base[off + j], want);
          return 3;
        }
      }
      store_release(h, id);
    }
  }
  store_detach(h);
  return 0;
}

int main(int argc, char** argv) {
  int rounds = argc > 1 ? atoi(argv[1]) : 5;
  int writers = argc > 2 ? atoi(argv[2]) : 4;
  if (writers < 1) writers = 1;
  if (writers > 24) writers = 24;  // pids[] holds 2*writers entries
  char pool[64];
  snprintf(pool, sizeof(pool), "/rtpu_stress_%d", (int)getpid());

  for (int round = 0; round < rounds; round++) {
    uint64_t h = store_create(pool, 16ull << 20, 4096, 0);
    if (!h) { fprintf(stderr, "create failed\n"); return 1; }

    pid_t pids[64];
    int np = 0;
    for (int w = 0; w < writers; w++) {
      pid_t p = fork();
      if (p == 0) _exit(writer_proc(pool, w, 64, w == 0 /*victim*/));
      pids[np++] = p;
    }
    for (int r = 0; r < writers; r++) {
      pid_t p = fork();
      if (p == 0) _exit(reader_proc(pool, writers, 256));
      pids[np++] = p;
    }
    int failures = 0, killed = 0;
    for (int i = 0; i < np; i++) {
      int st = 0;
      waitpid(pids[i], &st, 0);
      if (WIFSIGNALED(st) && WTERMSIG(st) == SIGKILL) killed++;
      else if (!WIFEXITED(st) || WEXITSTATUS(st) != 0) failures++;
    }
    if (killed != 1 || failures != 0) {
      fprintf(stderr, "round %d: failures=%d killed=%d\n", round, failures,
              killed);
      store_destroy(pool);
      return 1;
    }

    // Invariants after the crash: the pool still serves create/seal/
    // get/delete (robust-mutex recovery), and alloc/free round-trips.
    uint64_t st[8];
    store_stats(h, st);
    uint8_t id[16];
    make_id(id, 99, round);
    int32_t err = 0;
    uint64_t off = store_create_object(h, id, 1 << 16, &err);
    if (!off) { fprintf(stderr, "post-crash create failed\n"); return 1; }
    if (store_seal(h, id) != 0) { fprintf(stderr, "seal failed\n"); return 1; }
    uint64_t goff = 0, gsz = 0;
    if (store_get(h, id, &goff, &gsz) != 0 || gsz != (1 << 16)) {
      fprintf(stderr, "post-crash get failed\n");
      return 1;
    }
    store_release(h, id);
    store_delete(h, id);
    uint64_t st2[8];
    store_stats(h, st2);
    if (st2[1] < st[1]) { /* freed at least our block: fine */ }
    if (st2[2] > st[2]) {
      fprintf(stderr, "object count grew across a full round trip\n");
      return 1;
    }
    store_detach(h);
    store_destroy(pool);
  }
  printf("stress OK: %d rounds, %d writers (+%d readers), 1 SIGKILL/round\n",
         rounds, writers, writers);
  return 0;
}
