/* Test driver for the C ABI client: called by tests/test_capi.py with
 * a live actor's direct socket, the session authkey, and the actor id;
 * performs a scripted sequence of calls and prints parseable results.
 *
 * usage: rtpu_client_test <unix_path> <authkey_hex> <aid_hex>
 */
#include "rtpu_client.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static int unhex(const char *s, uint8_t *out, size_t outlen) {
    size_t n = strlen(s);
    size_t i;
    if (n != outlen * 2) return -1;
    for (i = 0; i < outlen; i++) {
        unsigned v;
        if (sscanf(s + 2 * i, "%2x", &v) != 1) return -1;
        out[i] = (uint8_t)v;
    }
    return 0;
}

static void print_value(const char *tag, const rtpu_value *v) {
    switch (v->kind) {
    case RTPU_VAL_NONE:
        printf("%s none\n", tag);
        break;
    case RTPU_VAL_BOOL:
        printf("%s bool %lld\n", tag, (long long)v->i);
        break;
    case RTPU_VAL_INT:
        printf("%s int %lld\n", tag, (long long)v->i);
        break;
    case RTPU_VAL_FLOAT:
        printf("%s float %.9g\n", tag, v->f);
        break;
    case RTPU_VAL_STR:
        printf("%s str %.*s\n", tag, (int)v->len, (const char *)v->data);
        break;
    case RTPU_VAL_BYTES:
        printf("%s bytes %zu\n", tag, v->len);
        break;
    default:
        printf("%s opaque %zu\n", tag, v->len);
    }
}

int main(int argc, char **argv) {
    char err[256];
    uint8_t authkey[32], aid[16];
    rtpu_value result;

    setvbuf(stdout, NULL, _IOLBF, 0); /* progress visible under a pipe */
    if (argc != 4) {
        fprintf(stderr, "usage: %s <path> <authkey_hex> <aid_hex>\n", argv[0]);
        return 2;
    }
    size_t keylen = strlen(argv[2]) / 2;
    if (keylen > sizeof authkey || unhex(argv[2], authkey, keylen) ||
        unhex(argv[3], aid, 16)) {
        fprintf(stderr, "bad hex args\n");
        return 2;
    }
    rtpu_conn *c = rtpu_connect(argv[1], authkey, keylen, err, sizeof err);
    if (!c) {
        fprintf(stderr, "connect: %s\n", err);
        return 1;
    }
    fprintf(stderr, "connected\n");

    /* str result, no args */
    if (rtpu_actor_call(c, aid, "ping", NULL, 0, &result, err, sizeof err)) {
        fprintf(stderr, "ping: %s\n", err);
        return 1;
    }
    print_value("ping", &result);

    /* int result, int args */
    rtpu_value add_args[2] = {
        {.kind = RTPU_VAL_INT, .i = 40},
        {.kind = RTPU_VAL_INT, .i = 2},
    };
    if (rtpu_actor_call(c, aid, "add", add_args, 2, &result, err, sizeof err)) {
        fprintf(stderr, "add: %s\n", err);
        return 1;
    }
    print_value("add", &result);

    /* big int through LONG1 both ways */
    rtpu_value big_args[1] = {
        {.kind = RTPU_VAL_INT, .i = 1234567890123456789LL},
    };
    if (rtpu_actor_call(c, aid, "add1", big_args, 1, &result, err, sizeof err)) {
        fprintf(stderr, "add1: %s\n", err);
        return 1;
    }
    print_value("add1", &result);

    /* float round trip */
    rtpu_value f_args[1] = {{.kind = RTPU_VAL_FLOAT, .f = 1.5}};
    if (rtpu_actor_call(c, aid, "fmul", f_args, 1, &result, err, sizeof err)) {
        fprintf(stderr, "fmul: %s\n", err);
        return 1;
    }
    print_value("fmul", &result);

    /* bytes echo */
    static const uint8_t blob[300] = {7};
    rtpu_value b_args[1] = {
        {.kind = RTPU_VAL_BYTES, .data = blob, .len = sizeof blob},
    };
    if (rtpu_actor_call(c, aid, "echo_len", b_args, 1, &result, err,
                        sizeof err)) {
        fprintf(stderr, "echo_len: %s\n", err);
        return 1;
    }
    print_value("echo_len", &result);

    /* str args */
    rtpu_value s_args[1] = {
        {.kind = RTPU_VAL_STR, .data = (const uint8_t *)"wörld", .len = 6},
    };
    if (rtpu_actor_call(c, aid, "greet", s_args, 1, &result, err, sizeof err)) {
        fprintf(stderr, "greet: %s\n", err);
        return 1;
    }
    print_value("greet", &result);

    /* remote exception surfaces as RTPU_ERR_REMOTE */
    int rc = rtpu_actor_call(c, aid, "boom", NULL, 0, &result, err, sizeof err);
    printf("boom rc %d\n", rc);

    /* connection survives the error */
    if (rtpu_actor_call(c, aid, "ping", NULL, 0, &result, err, sizeof err)) {
        fprintf(stderr, "ping2: %s\n", err);
        return 1;
    }
    print_value("ping2", &result);

    rtpu_close(c);
    printf("ok\n");
    return 0;
}
