// Shared-memory object-store core (plasma equivalent, C++).
//
// The reference's plasma store (src/ray/object_manager/plasma/store.h,
// plasma_allocator.h + vendored dlmalloc) manages mmap arenas with a
// malloc-style allocator, an object table with per-object refcounts and
// states (created → sealed), and LRU eviction of sealed, unreferenced
// objects. This is the same design collapsed into one shm pool shared
// by every process on the node:
//
//   [Header | client slots | client ledgers | object table | arena]
//
// All cross-process state lives in the pool; a robust process-shared
// pthread mutex guards the table + allocator, so a crashed worker can
// never wedge the store. Data payloads are written/read directly by
// Python through a zero-copy memoryview of the same mapping — this
// library owns METADATA AND ALLOCATION only, which is where the Python
// implementation (one shm segment + 3 syscalls per object) loses.
//
// Allocator: segregated-free-list-free classic boundary-tag malloc
// (header+footer per block, explicit doubly-linked free list,
// first-fit with splitting and bidirectional coalescing), 64-byte
// alignment so payloads are cache-line- and dlpack-friendly.
//
// Client registry (v2): every attaching process registers a client slot
// {pid, generation} and its refcount mutations are double-entried into a
// per-client ledger (open-addressed, keyed by object-table slot). The
// reference's plasma store gets disconnect sweeps for free because each
// client holds a unix socket to the store and EOF triggers
// ReleaseClientResources; with direct shm attach there is no socket, so
// the sweep walks the registry, probes liveness with kill(pid, 0), and
// subtracts a dead client's ledger from the global refcounts — including
// reclaiming its mid-write (created-not-sealed) objects, which must
// never seal. A full ledger counts overflow events (counted, never
// silent) and those refs stay pinned until pool destroy.

#include <algorithm>
#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <utility>
#include <vector>

namespace {

// v2 layout (client registry + ledgers). Bumped from the v1 value so an
// old .so can never attach a new pool (or vice versa) and misread the
// table: attach checks magic and fails cleanly.
constexpr uint64_t kMagic = 0x52545055504F4F32ULL;  // "RTPUPOO2"
constexpr uint64_t kNull = ~0ULL;
constexpr uint64_t kAlign = 64;
constexpr uint64_t kBlockHeader = 16;  // [size u64][flags u64]
constexpr uint64_t kBlockFooter = 8;   // [size u64]
constexpr uint64_t kMinBlock = 128;
constexpr uint32_t kMaxClients = 256;
constexpr uint32_t kStateEmpty = 0;
constexpr uint32_t kStateCreated = 1;
constexpr uint32_t kStateSealed = 2;
constexpr uint32_t kStateTombstone = 3;
// Deleted while referenced: invisible to get/contains, freed by the
// last store_release (independent of eviction, which may be disabled).
constexpr uint32_t kStateDeleting = 4;

struct Entry {
  uint8_t id[16];
  uint64_t offset;  // arena-relative payload offset
  uint64_t size;
  uint32_t state;
  int32_t refcount;
  uint64_t lru;
  uint32_t creator;  // client slot + 1; 0 = unregistered creator
  uint32_t _pad;
};

struct ClientSlot {
  int32_t pid;
  uint32_t state;  // 0 free, 1 active
  uint64_t generation;
  uint32_t overflow;  // refs this client could not ledger (counted)
  uint32_t _pad;
  uint64_t _pad2;
};

// One per-client ledger cell: key = object-table slot + 1 (0 = empty),
// count = refs this client holds on that entry. A cell whose count
// dropped to 0 keeps its key but is reusable by any insert — losing a
// zero-count key carries no information, and probes only stop on key==0,
// so chains through reused cells stay reachable.
struct LedgerEntry {
  uint32_t key;
  uint32_t count;
};

struct Header {
  uint64_t magic;
  uint64_t pool_size;
  uint32_t evict_enabled;  // 0: full pool fails create (caller falls back)
  uint32_t _pad0;
  uint64_t table_offset;
  uint64_t arena_offset;
  uint64_t arena_size;
  uint32_t max_objects;
  uint32_t _pad;
  pthread_mutex_t mutex;
  uint64_t lru_clock;
  uint64_t free_head;  // arena-relative offset of first free block
  // client registry
  uint64_t clients_offset;
  uint64_t ledgers_offset;
  uint32_t max_clients;
  uint32_t ledger_cap;  // cells per client ledger
  uint64_t generation;  // monotonically increasing client registrations
  // stats
  uint64_t bytes_in_use;
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t bytes_evicted;
  uint64_t num_sweeps;
  uint64_t refs_swept;
  uint64_t partials_reclaimed;
  uint64_t ledger_overflows;
};

struct Store {
  uint8_t* base;
  Header* h;
  uint64_t map_size;
  int32_t client;  // this handle's registered client slot, -1 if none
  int32_t pid;     // pid recorded at registration (slot-reuse guard)
  char name[256];
};

inline Entry* table(Store* s) {
  return reinterpret_cast<Entry*>(s->base + s->h->table_offset);
}
inline uint8_t* arena(Store* s) { return s->base + s->h->arena_offset; }
inline ClientSlot* clients(Store* s) {
  return reinterpret_cast<ClientSlot*>(s->base + s->h->clients_offset);
}
inline LedgerEntry* ledger(Store* s, uint32_t client) {
  return reinterpret_cast<LedgerEntry*>(s->base + s->h->ledgers_offset) +
         (uint64_t)client * s->h->ledger_cap;
}

// ---------------------------------------------------------------- blocks
// Block layout: [size u64][flags u64][payload ...][size u64]
// flags bit0 = allocated. Free blocks keep next/prev (arena offsets) in
// the first 16 payload bytes.
inline uint64_t blk_size(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off);
}
inline uint64_t blk_flags(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off + 8);
}
inline void blk_set(Store* s, uint64_t off, uint64_t size, uint64_t flags) {
  *reinterpret_cast<uint64_t*>(arena(s) + off) = size;
  *reinterpret_cast<uint64_t*>(arena(s) + off + 8) = flags;
  *reinterpret_cast<uint64_t*>(arena(s) + off + size - kBlockFooter) = size;
}
inline uint64_t& blk_next(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off + kBlockHeader);
}
inline uint64_t& blk_prev(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off + kBlockHeader + 8);
}

void freelist_insert(Store* s, uint64_t off) {
  blk_next(s, off) = s->h->free_head;
  blk_prev(s, off) = kNull;
  if (s->h->free_head != kNull) blk_prev(s, s->h->free_head) = off;
  s->h->free_head = off;
}

void freelist_remove(Store* s, uint64_t off) {
  uint64_t n = blk_next(s, off), p = blk_prev(s, off);
  if (p != kNull) blk_next(s, p) = n; else s->h->free_head = n;
  if (n != kNull) blk_prev(s, n) = p;
}

uint64_t round_up(uint64_t v, uint64_t a) { return (v + a - 1) / a * a; }

// Returns arena-relative PAYLOAD offset or kNull.
uint64_t arena_alloc(Store* s, uint64_t payload) {
  uint64_t need = round_up(payload + kBlockHeader + kBlockFooter, kAlign);
  if (need < kMinBlock) need = kMinBlock;
  for (uint64_t off = s->h->free_head; off != kNull; off = blk_next(s, off)) {
    uint64_t sz = blk_size(s, off);
    if (sz < need) continue;
    freelist_remove(s, off);
    if (sz - need >= kMinBlock) {  // split
      blk_set(s, off + need, sz - need, 0);
      freelist_insert(s, off + need);
      blk_set(s, off, need, 1);
    } else {
      blk_set(s, off, sz, 1);
    }
    s->h->bytes_in_use += blk_size(s, off);
    return off + kBlockHeader;
  }
  return kNull;
}

void arena_free(Store* s, uint64_t payload_off) {
  uint64_t off = payload_off - kBlockHeader;
  uint64_t sz = blk_size(s, off);
  s->h->bytes_in_use -= sz;
  // Coalesce with next block.
  uint64_t next = off + sz;
  if (next < s->h->arena_size && (blk_flags(s, next) & 1) == 0) {
    freelist_remove(s, next);
    sz += blk_size(s, next);
  }
  // Coalesce with previous block (via its footer).
  if (off > 0) {
    uint64_t prev_sz = *reinterpret_cast<uint64_t*>(arena(s) + off - kBlockFooter);
    uint64_t prev = off - prev_sz;
    if ((blk_flags(s, prev) & 1) == 0) {
      freelist_remove(s, prev);
      off = prev;
      sz += prev_sz;
    }
  }
  blk_set(s, off, sz, 0);
  freelist_insert(s, off);
}

// ----------------------------------------------------------------- table
uint64_t hash_id(const uint8_t* id) {
  uint64_t h;
  std::memcpy(&h, id, 8);
  uint64_t l;
  std::memcpy(&l, id + 8, 8);
  h ^= l * 0x9E3779B97F4A7C15ULL;
  h ^= h >> 31;
  return h;
}

Entry* find_entry(Store* s, const uint8_t* id, bool for_insert) {
  uint32_t cap = s->h->max_objects;
  uint64_t idx = hash_id(id) % cap;
  Entry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < cap; ++probe) {
    Entry* e = &table(s)[(idx + probe) % cap];
    if (e->state == kStateEmpty) {
      return for_insert ? (first_tomb ? first_tomb : e) : nullptr;
    }
    if (e->state == kStateTombstone) {
      if (for_insert && !first_tomb) first_tomb = e;
      continue;
    }
    if (std::memcmp(e->id, id, 16) == 0) return e;
  }
  return for_insert ? first_tomb : nullptr;
}

void lock(Store* s) {
  int rc = pthread_mutex_lock(&s->h->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&s->h->mutex);
}
void unlock(Store* s) { pthread_mutex_unlock(&s->h->mutex); }

void free_entry(Store* s, Entry* e) {
  arena_free(s, e->offset);
  e->state = kStateTombstone;
  e->offset = kNull;
  e->creator = 0;
  s->h->num_objects--;
}

// --------------------------------------------------------------- ledgers
// Double-entry of this handle's refcount mutations, so a dead client's
// refs can be subtracted back out. Called with the pool mutex held.
void ledger_adjust(Store* s, Entry* e, int32_t delta) {
  if (s->client < 0) return;
  // Slot-reuse guard: after a sweep or a sibling handle's detach retired
  // this slot (and possibly another process re-registered it), a stale
  // handle must not touch the ledger — the global refcount alone stays
  // correct for it.
  ClientSlot* c = &clients(s)[s->client];
  if (c->state != 1 || c->pid != s->pid) {
    s->client = -1;
    return;
  }
  uint32_t ti = (uint32_t)(e - table(s));
  uint32_t key = ti + 1;
  uint32_t cap = s->h->ledger_cap;
  LedgerEntry* led = ledger(s, (uint32_t)s->client);
  uint64_t idx = ((uint64_t)ti * 0x9E3779B1ULL) % cap;
  LedgerEntry* reuse = nullptr;
  for (uint32_t p = 0; p < cap; ++p) {
    LedgerEntry* le = &led[(idx + p) % cap];
    if (le->key == key) {
      if (delta > 0) {
        le->count += (uint32_t)delta;
      } else if (le->count > 0) {
        le->count--;
      }
      return;
    }
    if (le->key == 0) {
      if (!reuse) reuse = le;
      break;
    }
    if (le->count == 0 && !reuse) reuse = le;
  }
  if (delta <= 0) return;  // release of an untracked (overflowed) ref
  if (reuse) {
    reuse->key = key;
    reuse->count = (uint32_t)delta;
    return;
  }
  // Ledger full: the global refcount is still correct while this client
  // lives, but the ref can't be swept if it dies. Counted, never silent.
  clients(s)[s->client].overflow++;
  s->h->ledger_overflows++;
}

// Subtract client `ci`'s ledger from the global refcounts and retire the
// slot. Reclaims its mid-write (created, unsealed) objects — which must
// never seal — and completes any deferred deletes its refs were pinning.
// Called with the mutex held.
uint64_t drain_client_locked(Store* s, uint32_t ci, uint64_t* partials) {
  LedgerEntry* led = ledger(s, ci);
  uint64_t dropped = 0;
  for (uint32_t j = 0; j < s->h->ledger_cap; ++j) {
    LedgerEntry* le = &led[j];
    if (le->key == 0) continue;
    if (le->count == 0) {
      le->key = 0;
      continue;
    }
    Entry* e = &table(s)[le->key - 1];
    if (e->state != kStateEmpty && e->state != kStateTombstone) {
      int32_t c = (int32_t)le->count;
      e->refcount = e->refcount > c ? e->refcount - c : 0;
      dropped += (uint64_t)c;
      if (e->state == kStateCreated && e->creator == ci + 1) {
        // Partial write by a dead creator: reclaim, never seal.
        free_entry(s, e);
        if (partials) (*partials)++;
      } else if (e->refcount == 0 && e->state == kStateDeleting) {
        free_entry(s, e);
      }
    }
    le->key = 0;
    le->count = 0;
  }
  ClientSlot* c = &clients(s)[ci];
  c->state = 0;
  c->pid = 0;
  c->overflow = 0;
  return dropped;
}

// Probe every registered client with kill(pid, 0); drain the dead ones.
// EPERM means "exists, not ours" — only ESRCH is death. A recycled pid
// pins refs until the recycled process also exits; conservative, never
// frees early. Called with the mutex held.
int32_t sweep_locked(Store* s, uint64_t* out4) {
  int32_t swept = 0;
  uint64_t refs = 0, partials = 0;
  for (uint32_t i = 0; i < s->h->max_clients; ++i) {
    ClientSlot* c = &clients(s)[i];
    if (c->state != 1) continue;
    if ((int32_t)i == s->client) continue;  // never sweep self
    if (!(kill((pid_t)c->pid, 0) != 0 && errno == ESRCH)) continue;
    refs += drain_client_locked(s, i, &partials);
    swept++;
  }
  if (swept) {
    s->h->num_sweeps++;
    s->h->refs_swept += refs;
    s->h->partials_reclaimed += partials;
  }
  if (out4) {
    out4[0] = (uint64_t)swept;
    out4[1] = refs;
    out4[2] = partials;
    out4[3] = s->h->ledger_overflows;
  }
  return swept;
}

// Evict sealed refcount-0 objects (LRU first) until at least `need`
// payload bytes can be allocated. Returns payload offset or kNull.
uint64_t alloc_with_eviction(Store* s, uint64_t need) {
  uint64_t off = arena_alloc(s, need);
  while (off == kNull && s->h->evict_enabled) {
    Entry* victim = nullptr;
    uint32_t cap = s->h->max_objects;
    for (uint32_t i = 0; i < cap; ++i) {
      Entry* e = &table(s)[i];
      if (e->state == kStateSealed && e->refcount == 0) {
        if (!victim || e->lru < victim->lru) victim = e;
      }
    }
    if (!victim) return kNull;
    s->h->num_evictions++;
    s->h->bytes_evicted += victim->size;
    free_entry(s, victim);
    off = arena_alloc(s, need);
  }
  return off;
}

}  // namespace

extern "C" {

// Create a new pool. Returns handle (opaque ptr) or 0 on failure.
// evict_enabled=0 is the safe default for a session pool: the spill
// ladder (not LRU eviction) is what frees space, so a full pool fails
// the create and the caller backpressures / falls back to per-object
// segments.
uint64_t store_create(const char* name, uint64_t pool_bytes,
                      uint32_t max_objects, int32_t evict_enabled) {
  // Ledger capacity: enough cells that a well-behaved client (refs ≤
  // objects it touches) rarely overflows, without dominating small test
  // pools. 256 clients * 4096 cells * 8 B = 8 MiB at the default cap.
  uint32_t ledger_cap = max_objects < 4096 ? max_objects : 4096;
  if (ledger_cap < 16) ledger_cap = 16;
  uint64_t clients_bytes =
      round_up((uint64_t)kMaxClients * sizeof(ClientSlot), kAlign);
  uint64_t ledgers_bytes = round_up(
      (uint64_t)kMaxClients * ledger_cap * sizeof(LedgerEntry), kAlign);
  uint64_t table_bytes = round_up((uint64_t)max_objects * sizeof(Entry), kAlign);
  uint64_t header_bytes = round_up(sizeof(Header), kAlign);
  uint64_t total = round_up(
      header_bytes + clients_bytes + ledgers_bytes + table_bytes + pool_bytes,
      4096);

  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return 0;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return 0;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return 0;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->map_size = total;
  s->client = -1;
  s->pid = 0;
  std::snprintf(s->name, sizeof(s->name), "%s", name);
  Header* h = s->h = reinterpret_cast<Header*>(base);
  h->pool_size = total;
  h->clients_offset = header_bytes;
  h->ledgers_offset = header_bytes + clients_bytes;
  h->table_offset = h->ledgers_offset + ledgers_bytes;
  h->arena_offset = h->table_offset + table_bytes;
  h->arena_size = total - h->arena_offset;
  h->max_objects = max_objects;
  h->max_clients = kMaxClients;
  h->ledger_cap = ledger_cap;
  h->generation = 0;
  h->lru_clock = 1;
  h->evict_enabled = (uint32_t)evict_enabled;
  h->free_head = kNull;
  h->bytes_in_use = 0;
  h->num_objects = 0;
  h->num_sweeps = 0;
  h->refs_swept = 0;
  h->partials_reclaimed = 0;
  h->ledger_overflows = 0;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  std::memset(s->base + h->clients_offset, 0,
              clients_bytes + ledgers_bytes + table_bytes);
  // One big free block spanning the arena.
  blk_set(s, 0, h->arena_size, 0);
  freelist_insert(s, 0);
  h->magic = kMagic;  // last: attachers spin on magic
  return reinterpret_cast<uint64_t>(s);
}

uint64_t store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return 0;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return 0;
  }
  void* base =
      mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return 0;
  Header* h = reinterpret_cast<Header*>(base);
  if (h->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    return 0;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->h = h;
  s->map_size = (size_t)st.st_size;
  s->client = -1;
  s->pid = 0;
  std::snprintf(s->name, sizeof(s->name), "%s", name);
  return reinterpret_cast<uint64_t>(s);
}

// Register this process in the client registry so its refs are sweepable
// if it dies uncleanly. Idempotent per pid (a second handle in the same
// process shares the slot and ledger). Returns the slot, or -1 when the
// registry is full even after draining dead clients.
int32_t store_register(uint64_t handle, int32_t pid) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  for (int pass = 0; pass < 2; ++pass) {
    int32_t free_slot = -1;
    for (uint32_t i = 0; i < s->h->max_clients; ++i) {
      ClientSlot* c = &clients(s)[i];
      if (c->state == 1 && c->pid == pid) {
        s->client = (int32_t)i;
        s->pid = pid;
        unlock(s);
        return (int32_t)i;
      }
      if (c->state == 0 && free_slot < 0) free_slot = (int32_t)i;
    }
    if (free_slot >= 0) {
      ClientSlot* c = &clients(s)[free_slot];
      c->pid = pid;
      c->state = 1;
      c->generation = ++s->h->generation;
      c->overflow = 0;
      std::memset(ledger(s, (uint32_t)free_slot), 0,
                  (uint64_t)s->h->ledger_cap * sizeof(LedgerEntry));
      s->client = free_slot;
      s->pid = pid;
      unlock(s);
      return free_slot;
    }
    if (pass == 0) sweep_locked(s, nullptr);  // registry full: evict the dead
  }
  unlock(s);
  return -1;
}

// Drain dead clients' refs. out4 (may be NULL): [clients_swept,
// refs_dropped, partials_reclaimed, ledger_overflows_total].
int32_t store_sweep(uint64_t handle, uint64_t* out4) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  int32_t n = sweep_locked(s, out4);
  unlock(s);
  return n;
}

// Returns ABSOLUTE payload offset within the mapping (for Python's
// memoryview slicing), or 0 on failure (0 is inside the header, never a
// valid payload). err: 1 = exists, 2 = full, 3 = table full.
uint64_t store_create_object(uint64_t handle, const uint8_t* id, uint64_t size,
                             int32_t* err) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* existing = find_entry(s, id, false);
  if (existing) {
    unlock(s);
    if (err) *err = 1;
    return 0;
  }
  Entry* e = find_entry(s, id, true);
  if (!e) {
    unlock(s);
    if (err) *err = 3;
    return 0;
  }
  uint64_t off = alloc_with_eviction(s, size ? size : 1);
  if (off == kNull) {
    unlock(s);
    if (err) *err = 2;
    return 0;
  }
  std::memcpy(e->id, id, 16);
  e->offset = off;
  e->size = size;
  e->state = kStateCreated;
  e->refcount = 1;  // creator holds a ref until seal+release
  e->lru = s->h->lru_clock++;
  e->creator = s->client >= 0 ? (uint32_t)s->client + 1 : 0;
  ledger_adjust(s, e, 1);
  s->h->num_objects++;
  unlock(s);
  if (err) *err = 0;
  return s->h->arena_offset + off;
}

int32_t store_seal(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (e && e->state == kStateDeleting) {
    // Deleted mid-write: drop the creator ref; last ref frees the block.
    if (e->refcount > 0) e->refcount--;
    ledger_adjust(s, e, -1);
    if (e->refcount == 0) free_entry(s, e);
    unlock(s);
    return -1;
  }
  if (!e || e->state != kStateCreated) {
    unlock(s);
    return -1;
  }
  e->state = kStateSealed;
  e->refcount -= 1;
  ledger_adjust(s, e, -1);
  unlock(s);
  return 0;
}

// Get a sealed object: bumps refcount. Returns 0 on success.
int32_t store_get(uint64_t handle, const uint8_t* id, uint64_t* abs_offset,
                  uint64_t* size) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e || e->state != kStateSealed) {
    unlock(s);
    return -1;
  }
  e->refcount++;
  ledger_adjust(s, e, 1);
  e->lru = s->h->lru_clock++;
  *abs_offset = s->h->arena_offset + e->offset;
  *size = e->size;
  unlock(s);
  return 0;
}

int32_t store_contains(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  int32_t ok = (e && e->state == kStateSealed) ? 1 : 0;
  unlock(s);
  return ok;
}

int32_t store_release(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e || e->state == kStateEmpty || e->state == kStateTombstone) {
    unlock(s);
    return -1;
  }
  if (e->refcount > 0) e->refcount--;
  ledger_adjust(s, e, -1);
  if (e->refcount == 0 && e->state == kStateDeleting) free_entry(s, e);
  unlock(s);
  return 0;
}

int32_t store_delete(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e) {
    unlock(s);
    return -1;
  }
  if (e->refcount > 0) {
    // Deferred: the last store_release frees the block (works even with
    // eviction disabled — the session pool's default).
    e->state = kStateDeleting;
    e->lru = 0;
    unlock(s);
    return 1;
  }
  free_entry(s, e);
  unlock(s);
  return 0;
}

// List sealed refcount-0 objects in LRU order until their sizes sum to
// at least `need` bytes (spill victim selection — reference:
// LocalObjectManager::SpillObjectsOfSize, local_object_manager.h:100).
// Writes up to max_out ids (16 bytes each) into out_ids; returns count.
int32_t store_lru_candidates(uint64_t handle, uint64_t need,
                             uint8_t* out_ids, int32_t max_out) {
  Store* s = reinterpret_cast<Store*>(handle);
  std::vector<std::pair<uint64_t, uint32_t>> eligible;  // (lru, slot)
  lock(s);
  for (uint32_t i = 0; i < s->h->max_objects; ++i) {
    Entry* e = &table(s)[i];
    if (e->state == kStateSealed && e->refcount == 0) {
      eligible.emplace_back(e->lru, i);
    }
  }
  std::sort(eligible.begin(), eligible.end());
  int32_t count = 0;
  uint64_t gathered = 0;
  for (auto& [lru, i] : eligible) {
    if (count >= max_out || gathered >= need) break;
    Entry* e = &table(s)[i];
    std::memcpy(out_ids + 16 * count, e->id, 16);
    gathered += e->size;
    ++count;
  }
  unlock(s);
  return count;
}

void store_stats(uint64_t handle, uint64_t* out8) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  out8[0] = s->h->arena_size;
  out8[1] = s->h->bytes_in_use;
  out8[2] = s->h->num_objects;
  out8[3] = s->h->num_evictions;
  out8[4] = s->h->bytes_evicted;
  out8[5] = s->h->pool_size;
  out8[6] = s->h->max_objects;
  out8[7] = s->h->ledger_overflows;
  unlock(s);
}

// Sweep stats snapshot: [num_sweeps, refs_swept, partials_reclaimed,
// active_clients].
void store_sweep_stats(uint64_t handle, uint64_t* out4) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  out4[0] = s->h->num_sweeps;
  out4[1] = s->h->refs_swept;
  out4[2] = s->h->partials_reclaimed;
  uint64_t active = 0;
  for (uint32_t i = 0; i < s->h->max_clients; ++i) {
    if (clients(s)[i].state == 1) active++;
  }
  out4[3] = active;
  unlock(s);
}

void store_detach(uint64_t handle) {
  Store* s = reinterpret_cast<Store*>(handle);
  if (s->client >= 0) {
    // Clean disconnect: drain this process's own ledger so held refs
    // don't pin objects after exit. NOTE: the slot is per-pid, so all
    // handles in one process share it — detach drains them all, which
    // is safe because detach happens at process shutdown.
    lock(s);
    ClientSlot* c = &clients(s)[s->client];
    if (c->state == 1 && c->pid == s->pid) {
      drain_client_locked(s, (uint32_t)s->client, nullptr);
    }
    s->client = -1;
    unlock(s);
  }
  munmap(s->base, s->map_size);
  delete s;
}

int32_t store_destroy(const char* name) { return shm_unlink(name); }

}  // extern "C"
