// Shared-memory object-store core (plasma equivalent, C++).
//
// The reference's plasma store (src/ray/object_manager/plasma/store.h,
// plasma_allocator.h + vendored dlmalloc) manages mmap arenas with a
// malloc-style allocator, an object table with per-object refcounts and
// states (created → sealed), and LRU eviction of sealed, unreferenced
// objects. This is the same design collapsed into one shm pool shared
// by every process on the node:
//
//   [Header | object table (open addressing) | arena]
//
// All cross-process state lives in the pool; a robust process-shared
// pthread mutex guards the table + allocator, so a crashed worker can
// never wedge the store. Data payloads are written/read directly by
// Python through a zero-copy memoryview of the same mapping — this
// library owns METADATA AND ALLOCATION only, which is where the Python
// implementation (one shm segment + 3 syscalls per object) loses.
//
// Allocator: segregated-free-list-free classic boundary-tag malloc
// (header+footer per block, explicit doubly-linked free list,
// first-fit with splitting and bidirectional coalescing), 64-byte
// alignment so payloads are cache-line- and dlpack-friendly.

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <utility>
#include <vector>

namespace {

constexpr uint64_t kMagic = 0x52545055504F4F4CULL;  // "RTPUPOOL"
constexpr uint64_t kNull = ~0ULL;
constexpr uint64_t kAlign = 64;
constexpr uint64_t kBlockHeader = 16;  // [size u64][flags u64]
constexpr uint64_t kBlockFooter = 8;   // [size u64]
constexpr uint64_t kMinBlock = 128;
constexpr uint32_t kStateEmpty = 0;
constexpr uint32_t kStateCreated = 1;
constexpr uint32_t kStateSealed = 2;
constexpr uint32_t kStateTombstone = 3;
// Deleted while referenced: invisible to get/contains, freed by the
// last store_release (independent of eviction, which may be disabled).
constexpr uint32_t kStateDeleting = 4;

struct Entry {
  uint8_t id[16];
  uint64_t offset;  // arena-relative payload offset
  uint64_t size;
  uint32_t state;
  int32_t refcount;
  uint64_t lru;
};

struct Header {
  uint64_t magic;
  uint64_t pool_size;
  uint32_t evict_enabled;  // 0: full pool fails create (caller falls back)
  uint32_t _pad0;
  uint64_t table_offset;
  uint64_t arena_offset;
  uint64_t arena_size;
  uint32_t max_objects;
  uint32_t _pad;
  pthread_mutex_t mutex;
  uint64_t lru_clock;
  uint64_t free_head;  // arena-relative offset of first free block
  // stats
  uint64_t bytes_in_use;
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t bytes_evicted;
};

struct Store {
  uint8_t* base;
  Header* h;
  uint64_t map_size;
  char name[256];
};

inline Entry* table(Store* s) {
  return reinterpret_cast<Entry*>(s->base + s->h->table_offset);
}
inline uint8_t* arena(Store* s) { return s->base + s->h->arena_offset; }

// ---------------------------------------------------------------- blocks
// Block layout: [size u64][flags u64][payload ...][size u64]
// flags bit0 = allocated. Free blocks keep next/prev (arena offsets) in
// the first 16 payload bytes.
inline uint64_t blk_size(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off);
}
inline uint64_t blk_flags(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off + 8);
}
inline void blk_set(Store* s, uint64_t off, uint64_t size, uint64_t flags) {
  *reinterpret_cast<uint64_t*>(arena(s) + off) = size;
  *reinterpret_cast<uint64_t*>(arena(s) + off + 8) = flags;
  *reinterpret_cast<uint64_t*>(arena(s) + off + size - kBlockFooter) = size;
}
inline uint64_t& blk_next(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off + kBlockHeader);
}
inline uint64_t& blk_prev(Store* s, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(arena(s) + off + kBlockHeader + 8);
}

void freelist_insert(Store* s, uint64_t off) {
  blk_next(s, off) = s->h->free_head;
  blk_prev(s, off) = kNull;
  if (s->h->free_head != kNull) blk_prev(s, s->h->free_head) = off;
  s->h->free_head = off;
}

void freelist_remove(Store* s, uint64_t off) {
  uint64_t n = blk_next(s, off), p = blk_prev(s, off);
  if (p != kNull) blk_next(s, p) = n; else s->h->free_head = n;
  if (n != kNull) blk_prev(s, n) = p;
}

uint64_t round_up(uint64_t v, uint64_t a) { return (v + a - 1) / a * a; }

// Returns arena-relative PAYLOAD offset or kNull.
uint64_t arena_alloc(Store* s, uint64_t payload) {
  uint64_t need = round_up(payload + kBlockHeader + kBlockFooter, kAlign);
  if (need < kMinBlock) need = kMinBlock;
  for (uint64_t off = s->h->free_head; off != kNull; off = blk_next(s, off)) {
    uint64_t sz = blk_size(s, off);
    if (sz < need) continue;
    freelist_remove(s, off);
    if (sz - need >= kMinBlock) {  // split
      blk_set(s, off + need, sz - need, 0);
      freelist_insert(s, off + need);
      blk_set(s, off, need, 1);
    } else {
      blk_set(s, off, sz, 1);
    }
    s->h->bytes_in_use += blk_size(s, off);
    return off + kBlockHeader;
  }
  return kNull;
}

void arena_free(Store* s, uint64_t payload_off) {
  uint64_t off = payload_off - kBlockHeader;
  uint64_t sz = blk_size(s, off);
  s->h->bytes_in_use -= sz;
  // Coalesce with next block.
  uint64_t next = off + sz;
  if (next < s->h->arena_size && (blk_flags(s, next) & 1) == 0) {
    freelist_remove(s, next);
    sz += blk_size(s, next);
  }
  // Coalesce with previous block (via its footer).
  if (off > 0) {
    uint64_t prev_sz = *reinterpret_cast<uint64_t*>(arena(s) + off - kBlockFooter);
    uint64_t prev = off - prev_sz;
    if ((blk_flags(s, prev) & 1) == 0) {
      freelist_remove(s, prev);
      off = prev;
      sz += prev_sz;
    }
  }
  blk_set(s, off, sz, 0);
  freelist_insert(s, off);
}

// ----------------------------------------------------------------- table
uint64_t hash_id(const uint8_t* id) {
  uint64_t h;
  std::memcpy(&h, id, 8);
  uint64_t l;
  std::memcpy(&l, id + 8, 8);
  h ^= l * 0x9E3779B97F4A7C15ULL;
  h ^= h >> 31;
  return h;
}

Entry* find_entry(Store* s, const uint8_t* id, bool for_insert) {
  uint32_t cap = s->h->max_objects;
  uint64_t idx = hash_id(id) % cap;
  Entry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < cap; ++probe) {
    Entry* e = &table(s)[(idx + probe) % cap];
    if (e->state == kStateEmpty) {
      return for_insert ? (first_tomb ? first_tomb : e) : nullptr;
    }
    if (e->state == kStateTombstone) {
      if (for_insert && !first_tomb) first_tomb = e;
      continue;
    }
    if (std::memcmp(e->id, id, 16) == 0) return e;
  }
  return for_insert ? first_tomb : nullptr;
}

void lock(Store* s) {
  int rc = pthread_mutex_lock(&s->h->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&s->h->mutex);
}
void unlock(Store* s) { pthread_mutex_unlock(&s->h->mutex); }

void free_entry(Store* s, Entry* e) {
  arena_free(s, e->offset);
  e->state = kStateTombstone;
  e->offset = kNull;
  s->h->num_objects--;
}

// Evict sealed refcount-0 objects (LRU first) until at least `need`
// payload bytes can be allocated. Returns payload offset or kNull.
uint64_t alloc_with_eviction(Store* s, uint64_t need) {
  uint64_t off = arena_alloc(s, need);
  while (off == kNull && s->h->evict_enabled) {
    Entry* victim = nullptr;
    uint32_t cap = s->h->max_objects;
    for (uint32_t i = 0; i < cap; ++i) {
      Entry* e = &table(s)[i];
      if (e->state == kStateSealed && e->refcount == 0) {
        if (!victim || e->lru < victim->lru) victim = e;
      }
    }
    if (!victim) return kNull;
    s->h->num_evictions++;
    s->h->bytes_evicted += victim->size;
    free_entry(s, victim);
    off = arena_alloc(s, need);
  }
  return off;
}

}  // namespace

extern "C" {

// Create a new pool. Returns handle (opaque ptr) or 0 on failure.
// evict_enabled=0 is the safe default for a session pool: nothing pins
// client-referenced objects across processes yet, so eviction could free
// data a live ObjectRef still names. With eviction off a full pool fails
// the create and the caller falls back to per-object segments.
uint64_t store_create(const char* name, uint64_t pool_bytes,
                      uint32_t max_objects, int32_t evict_enabled) {
  uint64_t table_bytes = round_up((uint64_t)max_objects * sizeof(Entry), kAlign);
  uint64_t header_bytes = round_up(sizeof(Header), kAlign);
  uint64_t total = round_up(header_bytes + table_bytes + pool_bytes, 4096);

  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return 0;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return 0;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return 0;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->map_size = total;
  std::snprintf(s->name, sizeof(s->name), "%s", name);
  Header* h = s->h = reinterpret_cast<Header*>(base);
  h->pool_size = total;
  h->table_offset = header_bytes;
  h->arena_offset = header_bytes + table_bytes;
  h->arena_size = total - h->arena_offset;
  h->max_objects = max_objects;
  h->lru_clock = 1;
  h->evict_enabled = (uint32_t)evict_enabled;
  h->free_head = kNull;
  h->bytes_in_use = 0;
  h->num_objects = 0;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  std::memset(s->base + h->table_offset, 0, table_bytes);
  // One big free block spanning the arena.
  blk_set(s, 0, h->arena_size, 0);
  freelist_insert(s, 0);
  h->magic = kMagic;  // last: attachers spin on magic
  return reinterpret_cast<uint64_t>(s);
}

uint64_t store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return 0;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return 0;
  }
  void* base =
      mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return 0;
  Header* h = reinterpret_cast<Header*>(base);
  if (h->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    return 0;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->h = h;
  s->map_size = (size_t)st.st_size;
  std::snprintf(s->name, sizeof(s->name), "%s", name);
  return reinterpret_cast<uint64_t>(s);
}

// Returns ABSOLUTE payload offset within the mapping (for Python's
// memoryview slicing), or 0 on failure (0 is inside the header, never a
// valid payload). err: 1 = exists, 2 = full, 3 = table full.
uint64_t store_create_object(uint64_t handle, const uint8_t* id, uint64_t size,
                             int32_t* err) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* existing = find_entry(s, id, false);
  if (existing) {
    unlock(s);
    if (err) *err = 1;
    return 0;
  }
  Entry* e = find_entry(s, id, true);
  if (!e) {
    unlock(s);
    if (err) *err = 3;
    return 0;
  }
  uint64_t off = alloc_with_eviction(s, size ? size : 1);
  if (off == kNull) {
    unlock(s);
    if (err) *err = 2;
    return 0;
  }
  std::memcpy(e->id, id, 16);
  e->offset = off;
  e->size = size;
  e->state = kStateCreated;
  e->refcount = 1;  // creator holds a ref until seal+release
  e->lru = s->h->lru_clock++;
  s->h->num_objects++;
  unlock(s);
  if (err) *err = 0;
  return s->h->arena_offset + off;
}

int32_t store_seal(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (e && e->state == kStateDeleting) {
    // Deleted mid-write: drop the creator ref; last ref frees the block.
    if (e->refcount > 0) e->refcount--;
    if (e->refcount == 0) free_entry(s, e);
    unlock(s);
    return -1;
  }
  if (!e || e->state != kStateCreated) {
    unlock(s);
    return -1;
  }
  e->state = kStateSealed;
  e->refcount -= 1;
  unlock(s);
  return 0;
}

// Get a sealed object: bumps refcount. Returns 0 on success.
int32_t store_get(uint64_t handle, const uint8_t* id, uint64_t* abs_offset,
                  uint64_t* size) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e || e->state != kStateSealed) {
    unlock(s);
    return -1;
  }
  e->refcount++;
  e->lru = s->h->lru_clock++;
  *abs_offset = s->h->arena_offset + e->offset;
  *size = e->size;
  unlock(s);
  return 0;
}

int32_t store_contains(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  int32_t ok = (e && e->state == kStateSealed) ? 1 : 0;
  unlock(s);
  return ok;
}

int32_t store_release(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e || e->state == kStateEmpty || e->state == kStateTombstone) {
    unlock(s);
    return -1;
  }
  if (e->refcount > 0) e->refcount--;
  if (e->refcount == 0 && e->state == kStateDeleting) free_entry(s, e);
  unlock(s);
  return 0;
}

int32_t store_delete(uint64_t handle, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  Entry* e = find_entry(s, id, false);
  if (!e) {
    unlock(s);
    return -1;
  }
  if (e->refcount > 0) {
    // Deferred: the last store_release frees the block (works even with
    // eviction disabled — the session pool's default).
    e->state = kStateDeleting;
    e->lru = 0;
    unlock(s);
    return 1;
  }
  free_entry(s, e);
  unlock(s);
  return 0;
}

// List sealed refcount-0 objects in LRU order until their sizes sum to
// at least `need` bytes (spill victim selection — reference:
// LocalObjectManager::SpillObjectsOfSize, local_object_manager.h:100).
// Writes up to max_out ids (16 bytes each) into out_ids; returns count.
int32_t store_lru_candidates(uint64_t handle, uint64_t need,
                             uint8_t* out_ids, int32_t max_out) {
  Store* s = reinterpret_cast<Store*>(handle);
  std::vector<std::pair<uint64_t, uint32_t>> eligible;  // (lru, slot)
  lock(s);
  for (uint32_t i = 0; i < s->h->max_objects; ++i) {
    Entry* e = &table(s)[i];
    if (e->state == kStateSealed && e->refcount == 0) {
      eligible.emplace_back(e->lru, i);
    }
  }
  std::sort(eligible.begin(), eligible.end());
  int32_t count = 0;
  uint64_t gathered = 0;
  for (auto& [lru, i] : eligible) {
    if (count >= max_out || gathered >= need) break;
    Entry* e = &table(s)[i];
    std::memcpy(out_ids + 16 * count, e->id, 16);
    gathered += e->size;
    ++count;
  }
  unlock(s);
  return count;
}

void store_stats(uint64_t handle, uint64_t* out8) {
  Store* s = reinterpret_cast<Store*>(handle);
  lock(s);
  out8[0] = s->h->arena_size;
  out8[1] = s->h->bytes_in_use;
  out8[2] = s->h->num_objects;
  out8[3] = s->h->num_evictions;
  out8[4] = s->h->bytes_evicted;
  out8[5] = s->h->pool_size;
  out8[6] = s->h->max_objects;
  out8[7] = 0;
  unlock(s);
}

void store_detach(uint64_t handle) {
  Store* s = reinterpret_cast<Store*>(handle);
  munmap(s->base, s->map_size);
  delete s;
}

int32_t store_destroy(const char* name) { return shm_unlink(name); }

}  // extern "C"
