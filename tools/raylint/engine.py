"""raylint engine: file contexts, rule registry, baseline workflow.

The engine parses each file once into a :class:`FileContext` (AST +
marker index + function table), runs every registered rule over it,
applies ``disable=`` suppressions, and diffs the surviving violations
against a JSON baseline: pre-existing debt is tracked, NEW violations
fail the run. ``--write-baseline`` re-snapshots the debt.

Fingerprints are line-number free — ``(rule, path, enclosing qualname,
stripped source text)`` — so unrelated edits moving a violation up or
down a file do not churn the baseline; only adding a second identical
violation to the same function trips the count.
"""
from __future__ import annotations

import ast
import fnmatch
import hashlib
import json
import os
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from . import markers as _markers

# --------------------------------------------------------------- registry

#: rule name -> (func, one-line doc). Populated by @rule.
RULES: Dict[str, Tuple[Callable, str]] = {}


def rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = (fn, doc)
        return fn
    return deco


class Violation:
    __slots__ = ("rule", "path", "line", "message", "qualname", "text")

    def __init__(self, rule_name: str, path: str, line: int,
                 message: str, qualname: str, text: str):
        self.rule = rule_name
        self.path = path
        self.line = line
        self.message = message
        self.qualname = qualname
        self.text = text

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha256(
            "|".join((self.rule, self.path, self.qualname, self.text))
            .encode()
        )
        return h.hexdigest()[:16]

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        )

    def as_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "qualname": self.qualname,
            "fingerprint": self.fingerprint,
        }


# ---------------------------------------------------------- file context


class FileContext:
    """One parsed file: AST, markers, function table, parent links."""

    def __init__(self, path: str, source: str, repo_rel: str):
        self.path = repo_rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.markers = _markers.parse_markers(source)
        self.module = _markers.module_directives(self.markers)
        self._marker_by_line: Dict[int, List[_markers.Marker]] = {}
        for mk in self.markers:
            self._marker_by_line.setdefault(mk.line, []).append(mk)
        # Parent links (AST walk helpers).
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        # Function table: qualname -> def node, plus sorted spans for
        # enclosing-function lookup.
        self.functions: Dict[str, ast.AST] = {}
        self._spans: List[Tuple[int, int, str]] = []
        self._index_functions(self.tree, prefix="")
        self._spans.sort()
        # Function-scope directives (markers on decorator/def/above-def
        # lines): qualname -> list of markers.
        self.func_markers: Dict[str, List[_markers.Marker]] = {}
        for qual, node in self.functions.items():
            first = min(
                [node.lineno]
                + [d.lineno for d in getattr(node, "decorator_list", [])]
            )
            body_start = node.body[0].lineno if node.body else node.lineno
            mks: List[_markers.Marker] = []
            for ln in range(first - 1, body_start):
                mks.extend(self._marker_by_line.get(ln, []))
            self.func_markers[qual] = mks

    def _index_functions(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                self.functions[qual] = child
                self._spans.append(
                    (child.lineno, child.end_lineno or child.lineno, qual)
                )
                self._index_functions(child, prefix=f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                self._index_functions(child, prefix=f"{prefix}{child.name}.")
            else:
                self._index_functions(child, prefix=prefix)

    # ------------------------------------------------------------ lookups

    def enclosing_function(self, line: int) -> str:
        """Innermost function qualname containing the line; "<module>"
        otherwise."""
        best = "<module>"
        best_width = None
        for lo, hi, qual in self._spans:
            if lo <= line <= hi:
                width = hi - lo
                if best_width is None or width <= best_width:
                    best, best_width = qual, width
        return best

    def function_has(self, qual: str, directive: str) -> bool:
        for mk in self.func_markers.get(qual, []):
            if mk.directive == directive:
                return True
        # Nested defs inherit their parents' domain markers.
        while "." in qual:
            qual = qual.rsplit(".", 1)[0]
            for mk in self.func_markers.get(qual, []):
                if mk.directive == directive:
                    return True
        return False

    def dispatch_roots(self) -> List[str]:
        """Functions that run on dispatch threads: explicit
        ``dispatch-only`` markers plus module-level
        ``dispatch-handlers=`` globs."""
        # Direct markers only — a def nested inside a handler is most
        # often a thread target (Thread(target=...)) that does NOT run
        # on the dispatch thread; it joins the root set only if the
        # handler actually CALLS it (call-graph reachability).
        roots = [
            q for q in self.functions
            if any(
                mk.directive == "dispatch-only"
                for mk in self.func_markers.get(q, [])
            )
        ]
        globs = self.module.get("dispatch-handlers", [])
        if globs:
            for qual in self.functions:
                name = qual.rsplit(".", 1)[-1]
                if any(fnmatch.fnmatch(name, g) for g in globs):
                    roots.append(qual)
        return sorted(set(roots))

    def suppressed(self, rule_name: str, line: int) -> bool:
        """``disable=`` at the line, on an own-line comment just above
        it, or on the enclosing def."""
        candidates = list(self._marker_by_line.get(line, []))
        candidates.extend(
            mk for mk in self._marker_by_line.get(line - 1, [])
            if mk.own_line
        )
        qual = self.enclosing_function(line)
        while True:
            candidates.extend(self.func_markers.get(qual, []))
            if "." not in qual:
                break
            qual = qual.rsplit(".", 1)[0]
        for mk in candidates:
            if mk.directive == "disable" and rule_name in mk.values:
                return True
        return False

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


# ------------------------------------------------------------------ runner


def _iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [
                d for d in dirs
                if d not in ("__pycache__", ".git", "_native")
            ]
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


def lint_source(source: str, path: str = "<string>",
                only: Optional[Iterable[str]] = None) -> List[Violation]:
    """Lint one source blob (fixture tests drive rules through this)."""
    ctx = FileContext(path, source, path)
    return _run_rules(ctx, only=only)


def lint_paths(paths: Iterable[str], repo_root: str,
               only: Optional[Iterable[str]] = None
               ) -> Tuple[List[Violation], List[str]]:
    """Lint a tree. Returns (violations, unparsable-file errors)."""
    violations: List[Violation] = []
    errors: List[str] = []
    for fp in _iter_py_files(paths):
        rel = os.path.relpath(fp, repo_root).replace(os.sep, "/")
        try:
            with open(fp, "r", encoding="utf-8") as f:
                source = f.read()
            ctx = FileContext(fp, source, rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{rel}: {e}")
            continue
        violations.extend(_run_rules(ctx, only=only))
    return violations, errors


def _run_rules(ctx: FileContext,
               only: Optional[Iterable[str]] = None) -> List[Violation]:
    from . import rules as _rules  # noqa: F401 - registers RULES

    out: List[Violation] = []
    selected = set(only) if only else None
    for name, (fn, _doc) in sorted(RULES.items()):
        if selected is not None and name not in selected:
            continue
        for line, message in fn(ctx):
            if ctx.suppressed(name, line):
                continue
            out.append(
                Violation(
                    name, ctx.path, line, message,
                    ctx.enclosing_function(line), ctx.line_text(line),
                )
            )
    # Suppressions without a reason are themselves violations: a
    # disable marker is an auditable decision, not a mute button.
    for mk in ctx.markers:
        if mk.directive == "disable" and not mk.reason:
            out.append(
                Violation(
                    "bare-suppression", ctx.path, mk.line,
                    "disable marker without a ' -- reason'",
                    ctx.enclosing_function(mk.line),
                    ctx.line_text(mk.line),
                )
            )
    return out


# ---------------------------------------------------------------- baseline


def load_baseline(path: str) -> Dict[str, Dict]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data.get("violations", {})


def write_baseline(path: str, violations: List[Violation]) -> None:
    table: Dict[str, Dict] = {}
    for v in sorted(violations, key=lambda v: (v.path, v.line)):
        rec = table.get(v.fingerprint)
        if rec is None:
            table[v.fingerprint] = {
                "rule": v.rule, "path": v.path, "qualname": v.qualname,
                "text": v.text, "count": 1,
            }
        else:
            rec["count"] += 1
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {"version": 1, "violations": table},
            f, indent=1, sort_keys=True,
        )
        f.write("\n")


def diff_baseline(
    violations: List[Violation], baseline: Dict[str, Dict]
) -> Tuple[List[Violation], List[str]]:
    """(new violations, fingerprints fixed since the baseline)."""
    counts: Dict[str, int] = {}
    new: List[Violation] = []
    for v in violations:
        fp = v.fingerprint
        counts[fp] = counts.get(fp, 0) + 1
        if counts[fp] > int(baseline.get(fp, {}).get("count", 0)):
            new.append(v)
    fixed = [fp for fp in baseline if fp not in counts]
    return new, fixed
