"""`# raylint:` marker grammar.

One directive per marker comment, an optional reason after ` -- `:

    # raylint: <directive> [-- <reason>]

Directives:

``dispatch-only``
    The function runs on a dispatch/reader thread: it must not block
    (no-blocking-on-dispatch roots here) and must not touch guarded
    refcount/holder state or call into ``applier-only`` functions.

``applier-only``
    The function is part of the module's declared mutation domain for
    guarded refcount/holder state (the applier thread in the sharded
    directory; the under-``self._lock`` methods in the owner tracker).
    Only functions carrying this marker may mutate ``guarded-attrs``.

``disable=<rule>[,<rule>...] -- <reason>``
    Suppress the named rule(s) at this line (trailing comment) or for
    the whole function (comment on/above the ``def`` line). The reason
    is REQUIRED: a suppression without one is itself a violation
    (rule ``bare-suppression``).

``guarded-attrs=<name>[,<name>...]``
    Module-level (own-line comment): attribute names whose mutation is
    restricted to ``applier-only`` functions in this module.

``dispatch-handlers=<glob>[,<glob>...]``
    Module-level: function-name globs (fnmatch) treated as
    ``dispatch-only`` roots without per-function markers (e.g. the
    GCS's ``_h_*`` message handlers).

``check-event-literals``
    Module-level: ALL-CAPS string literals used in comparisons in this
    module must be registered flight-recorder event names (the
    timeline stitcher in ``state.py``).
"""
from __future__ import annotations

import re
from typing import Dict, List, NamedTuple

_MARKER_RE = re.compile(r"#\s*raylint:\s*(?P<body>.+?)\s*$")
_OWN_LINE_RE = re.compile(r"^\s*#")

#: Directives that only make sense at module scope (own-line comment).
MODULE_DIRECTIVES = (
    "guarded-attrs", "dispatch-handlers", "check-event-literals",
)

#: Function-domain directives (on/above a ``def`` line).
FUNCTION_DIRECTIVES = ("dispatch-only", "applier-only")


class Marker(NamedTuple):
    line: int            # 1-based source line the comment sits on
    own_line: bool       # comment is the whole line (module/next-def)
    directive: str       # e.g. "disable", "dispatch-only"
    value: str           # payload after "=", "" when none
    reason: str          # text after " -- ", "" when none

    @property
    def values(self) -> List[str]:
        return [v.strip() for v in self.value.split(",") if v.strip()]


def parse_markers(source: str) -> List[Marker]:
    """All `# raylint:` markers in the file, line-addressed.

    Comment scan is line-based (not tokenize): a ``# raylint:`` inside
    a string literal would misparse, but the grammar is unusual enough
    that the simplicity wins — fixture tests cover the real layouts.
    """
    out: List[Marker] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _MARKER_RE.search(text)
        if m is None:
            continue
        body = m.group("body")
        reason = ""
        if " -- " in body:
            body, reason = body.split(" -- ", 1)
            body = body.strip()
            reason = reason.strip()
        if "=" in body:
            directive, value = body.split("=", 1)
        else:
            directive, value = body, ""
        out.append(
            Marker(
                line=lineno,
                own_line=bool(_OWN_LINE_RE.match(text)),
                directive=directive.strip(),
                value=value.strip(),
                reason=reason,
            )
        )
    return out


def module_directives(markers: List[Marker]) -> Dict[str, List[str]]:
    """directive -> merged values, for module-scope directives."""
    out: Dict[str, List[str]] = {}
    for mk in markers:
        if mk.own_line and mk.directive in MODULE_DIRECTIVES:
            out.setdefault(mk.directive, []).extend(mk.values or [""])
    return out
