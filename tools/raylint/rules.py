"""raylint rules: the runtime's concurrency & reliability invariants.

Each rule encodes an invariant this codebase already paid for (the PR
numbers refer to CHANGES.md):

- ``thread-domain`` — refcount/holder mutations happen only in the
  declared mutation domain (PR 2: the sharded directory's single
  applier thread; the owner tracker's under-lock methods).
- ``no-blocking-on-dispatch`` — nothing reachable from a dispatch
  handler sleeps or does IO (PR 2: background threads taxing the
  dispatch loop were measurable at storm rates).
- ``fixed-sleep-retry`` — retry loops ride ``chaos.Backoff`` /
  ``retry_call``, never a fixed ``time.sleep`` (PR 3: one retry
  policy, full jitter, budgets).
- ``raw-send-on-gcs-path`` — GCS-routed completion/ref/submit traffic
  rides the at-least-once senders (PR 4: the ``_report_done`` raw-send
  bug killed workers when a task completed mid-failover).
- ``swallowed-fault`` — a broad except either re-raises, records a
  flight-recorder event, logs, or counts; silent swallows hide
  ``ConnectionLost``/``SpillCorruptionError`` (PRs 1-10: "counted,
  never silent").
- ``event-taxonomy`` — every ``events.record()`` name and every
  timeline-stitch literal comes from the checked registry
  (``_private/event_names.py``), so ``state.py`` row stitching cannot
  silently miss renamed events.

Rules are pure AST passes over a :class:`~tools.raylint.engine.
FileContext`; each yields ``(line, message)`` pairs and the engine
applies ``disable=`` suppressions and the baseline.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .engine import FileContext, rule

# --------------------------------------------------------------- helpers


def _attr_chain(node: ast.AST) -> str:
    """Dotted name for simple attribute chains ("self.conn.send")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _contains(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


def _enclosing(ctx: FileContext, node: ast.AST, kinds) -> Optional[ast.AST]:
    cur = ctx.parent.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = ctx.parent.get(cur)
    return None


_SET_MUTATORS = {
    "add", "discard", "remove", "clear", "update", "pop", "append",
    "extend", "popitem", "setdefault",
}


# ------------------------------------------------------------ thread-domain


@rule(
    "thread-domain",
    "guarded refcount/holder attrs mutate only in applier-only functions",
)
def thread_domain(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    guarded = set(ctx.module.get("guarded-attrs", []))
    if not guarded:
        return

    def guarded_attr(node: ast.AST) -> Optional[str]:
        # entry.holders / self._counts — the attribute itself.
        if isinstance(node, ast.Attribute) and node.attr in guarded:
            return node.attr
        return None

    def legal(line: int) -> bool:
        qual = ctx.enclosing_function(line)
        leaf = qual.rsplit(".", 1)[-1]
        if leaf == "__init__":
            return True  # construction precedes publication
        return ctx.function_has(qual, "applier-only")

    for node in ast.walk(ctx.tree):
        sites: List[Tuple[int, str]] = []
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                # entry.holders = ... / self._counts[oid] = ...
                base = t.value if isinstance(t, ast.Subscript) else t
                name = guarded_attr(base)
                if name:
                    sites.append((t.lineno, f"assignment to '{name}'"))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                name = guarded_attr(base)
                if name:
                    sites.append((node.lineno, f"del on '{name}'"))
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _SET_MUTATORS
            ):
                name = guarded_attr(f.value)
                if name:
                    sites.append(
                        (node.lineno, f"'{name}.{f.attr}()' mutation")
                    )
        for line, what in sites:
            if not legal(line):
                yield (
                    line,
                    f"{what} outside the applier domain — guarded attrs "
                    f"({', '.join(sorted(guarded))}) mutate only in "
                    f"'# raylint: applier-only' functions",
                )
    # Half two: dispatch-only functions must not call into the
    # applier domain (intra-module resolution).
    applier = {
        q for q in ctx.functions if ctx.function_has(q, "applier-only")
    }
    if not applier:
        return
    applier_leaves = {q.rsplit(".", 1)[-1] for q in applier}
    for root in ctx.dispatch_roots():
        fn = ctx.functions.get(root)
        if fn is None:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # Nested defs are their own functions (usually thread
            # targets that do NOT run on the dispatch thread) — same
            # exclusion no-blocking-on-dispatch applies.
            if ctx.enclosing_function(node.lineno) != root:
                continue
            chain = _attr_chain(node.func)
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in applier_leaves and (
                chain.startswith("self.") or chain == leaf
            ):
                yield (
                    node.lineno,
                    f"dispatch-only '{root}' calls applier-only "
                    f"'{leaf}'",
                )


# -------------------------------------------------- no-blocking-on-dispatch

#: Callable chains that block the calling thread.
_BLOCKING_CHAINS = {
    "time.sleep", "select.select", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
}
#: Method names that block regardless of receiver (socket reads,
#: Backoff.sleep, blocking joins on queues).
_BLOCKING_METHODS = {"sleep", "recv", "recvfrom", "recv_into", "accept"}


def _blocking_call(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "open":
        return "open()"
    chain = _attr_chain(f)
    if chain in _BLOCKING_CHAINS:
        return chain + "()"
    if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_METHODS:
        return chain + "()"
    return None


def _call_graph(ctx: FileContext) -> Dict[str, Set[str]]:
    """Intra-module edges: bare-name calls resolve to module functions,
    ``self.x()`` to a method of the same class."""
    edges: Dict[str, Set[str]] = {}
    leaf_index: Dict[str, List[str]] = {}
    for q in ctx.functions:
        leaf_index.setdefault(q.rsplit(".", 1)[-1], []).append(q)
    for qual, fn in ctx.functions.items():
        outs: Set[str] = set()
        cls_prefix = qual.rsplit(".", 1)[0] + "." if "." in qual else ""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                # Innermost scope first: a def nested in this function,
                # a sibling (shared enclosing scope), module level, or
                # — for closures passed around — a unique leaf match.
                name = f.id
                if qual + "." + name in ctx.functions:
                    outs.add(qual + "." + name)
                elif cls_prefix + name in ctx.functions:
                    outs.add(cls_prefix + name)
                elif name in ctx.functions:
                    outs.add(name)
                elif len(leaf_index.get(name, [])) == 1:
                    outs.add(leaf_index[name][0])
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and cls_prefix
                and cls_prefix + f.attr in ctx.functions
            ):
                outs.add(cls_prefix + f.attr)
        edges[qual] = outs
    return edges


@rule(
    "no-blocking-on-dispatch",
    "no sleep/IO/socket wait reachable from dispatch-thread handlers",
)
def no_blocking_on_dispatch(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    roots = ctx.dispatch_roots()
    if not roots:
        return
    edges = _call_graph(ctx)
    # BFS: function -> a root it is reachable from (for the message).
    via: Dict[str, str] = {}
    frontier = list(roots)
    for r in roots:
        via[r] = r
    while frontier:
        cur = frontier.pop()
        for nxt in edges.get(cur, ()):
            if nxt not in via:
                via[nxt] = via[cur]
                frontier.append(nxt)
    seen: Set[Tuple[int, str]] = set()
    for qual, root in via.items():
        fn = ctx.functions[qual]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            desc = _blocking_call(node)
            if desc is None:
                continue
            # Nested defs are indexed as their own functions: a worker
            # thread body defined inside a handler does not run on the
            # dispatch thread.
            if ctx.enclosing_function(node.lineno) != qual:
                continue
            key = (node.lineno, desc)
            if key in seen:
                continue
            seen.add(key)
            where = (
                f"dispatch handler '{qual}'" if qual == root
                else f"'{qual}' (reachable from dispatch handler "
                f"'{root}')"
            )
            yield (
                node.lineno,
                f"blocking call {desc} in {where}",
            )


# ------------------------------------------------------- fixed-sleep-retry


@rule(
    "fixed-sleep-retry",
    "retry-shaped time.sleep loops must ride chaos.Backoff/retry_call",
)
def fixed_sleep_retry(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _attr_chain(node.func) != "time.sleep":
            continue
        loop = _enclosing(ctx, node, (ast.While, ast.For))
        if loop is None:
            continue
        # Retry-shaped: the sleep IS the between-attempts delay — it
        # sits inside an except handler. (A sleep at the top of a loop
        # that merely contains a try is a poll cadence, not a retry.)
        handler = _enclosing(ctx, node, (ast.ExceptHandler,))
        if handler is None or handler.lineno < loop.lineno:
            continue
        # Already on the one retry policy? next_delay()/Backoff()/
        # retry_call anywhere in the loop exempts it.
        def on_policy(n: ast.AST) -> bool:
            if isinstance(n, ast.Attribute) and n.attr == "next_delay":
                return True
            if isinstance(n, ast.Call):
                chain = _attr_chain(n.func)
                if chain.endswith("Backoff") or chain.endswith(
                    "retry_call"
                ) or chain.endswith(".sleep") and chain != "time.sleep":
                    return True
            return False

        if _contains(loop, on_policy):
            continue
        yield (
            node.lineno,
            "fixed time.sleep in a retry loop — use chaos.Backoff / "
            "chaos.retry_call (exp backoff + jitter + budget)",
        )


# ---------------------------------------------------- raw-send-on-gcs-path

#: Message types that MUST ride an at-least-once / failover-reliable
#: sender (send_reliable / request_reliable / the done-batcher / the
#: ref-flush tracker): completions, ref edges, submits, frees, puts.
RELIABLE_TYPES = {
    "submit_task", "task_done", "task_done_batch",
    "ref_flush", "update_refs", "free_objects", "put_object",
}

#: send attributes that are already reliable.
_RELIABLE_SENDERS = {"send_reliable", "request_reliable"}


def _dict_type_key(d: ast.AST) -> Optional[str]:
    if not isinstance(d, ast.Dict):
        return None
    for k, v in zip(d.keys, d.values):
        if (
            isinstance(k, ast.Constant) and k.value == "type"
            and isinstance(v, ast.Constant) and isinstance(v.value, str)
        ):
            return v.value
    return None


@rule(
    "raw-send-on-gcs-path",
    "GCS-routed completion/ref/submit traffic must use reliable senders",
)
def raw_send_on_gcs_path(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        if f.attr in _RELIABLE_SENDERS or f.attr not in (
            "send", "request",
        ):
            continue
        arg = node.args[0]
        mtype = _dict_type_key(arg)
        if mtype is None and isinstance(arg, ast.Name):
            # Resolve `msg = {"type": ...}; conn.send(msg)` within the
            # same function (last literal assignment wins).
            qual = ctx.enclosing_function(node.lineno)
            fn = ctx.functions.get(qual)
            if fn is not None:
                for stmt in ast.walk(fn):
                    if (
                        isinstance(stmt, ast.Assign)
                        and stmt.lineno < node.lineno
                        and any(
                            isinstance(t, ast.Name) and t.id == arg.id
                            for t in stmt.targets
                        )
                    ):
                        got = _dict_type_key(stmt.value)
                        if got is not None:
                            mtype = got
        if mtype in RELIABLE_TYPES:
            yield (
                node.lineno,
                f"raw .{f.attr}() of '{mtype}' — this message class "
                "must ride send_reliable/request_reliable or an "
                "at-least-once batcher (the PR 4 _report_done bug "
                "class)",
            )


# ---------------------------------------------------------- swallowed-fault

#: A handler that calls any of these is accounting for the fault.
_HANDLED_CALLS = {
    "record", "count_lost", "warning", "error", "exception", "critical",
    "debug", "info", "log", "print", "fail", "kill_point", "fault_point",
    "put_nowait", "set", "reply",
}
#: Assignments whose target mentions one of these count the fault.
_COUNTER_HINTS = re.compile(r"stats|drops|dropped|errors|lost|failed")


def _broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names: List[str] = []
    for n in ([t] if not isinstance(t, ast.Tuple) else t.elts):
        names.append(_attr_chain(n).rsplit(".", 1)[-1])
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_accounts(h: ast.ExceptHandler) -> bool:
    for n in ast.walk(h):
        if isinstance(n, ast.Raise):
            return True
        if (
            h.name is not None
            and isinstance(n, ast.Name)
            and n.id == h.name
            and isinstance(n.ctx, ast.Load)
        ):
            # `except Exception as e: ... e ...` — the fault is
            # CONVERTED (packed into an error blob, formatted into a
            # reply), not swallowed.
            return True
        if isinstance(n, ast.Call):
            f = n.func
            leaf = (
                f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else ""
            )
            if leaf in _HANDLED_CALLS:
                return True
        if isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            for t in targets:
                if _COUNTER_HINTS.search(ast.dump(t)):
                    return True
    return False


@rule(
    "swallowed-fault",
    "broad excepts must re-raise, record, log, or count — never swallow",
)
def swallowed_fault(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_handler(node):
            continue
        if _handler_accounts(node):
            continue
        yield (
            node.lineno,
            "broad except swallows the fault — re-raise, record a "
            "flight-recorder event, log, or count it (ConnectionLost/"
            "SpillCorruptionError must never vanish)",
        )


# ----------------------------------------------------------- event-taxonomy

_REGISTRY_CACHE: Optional[Dict[str, Set[str]]] = None
_CAPS_RE = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")


def _load_registry() -> Dict[str, Set[str]]:
    """Exec event_names.py standalone (no ray_tpu package import: the
    lint must run without jax/the runtime on the path)."""
    global _REGISTRY_CACHE
    if _REGISTRY_CACHE is not None:
        return _REGISTRY_CACHE
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(
        here, "..", "..", "ray_tpu", "_private", "event_names.py"
    )
    ns: Dict[str, object] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            exec(compile(f.read(), path, "exec"), ns)  # noqa: S102
    except OSError:
        _REGISTRY_CACHE = {}
        return _REGISTRY_CACHE
    _REGISTRY_CACHE = {
        "events": set(ns.get("EVENT_NAMES", ())),
        "categories": set(ns.get("CATEGORIES", ())),
        "category_consts": set(ns.get("CATEGORY_CONSTS", ())),
        "task_table": set(ns.get("TASK_TABLE_EVENTS", ())),
    }
    return _REGISTRY_CACHE


@rule(
    "event-taxonomy",
    "events.record() names and timeline-stitch literals come from the "
    "event_names registry",
)
def event_taxonomy(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    reg = _load_registry()
    if not reg:
        return
    events = reg["events"]
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (
            isinstance(f, ast.Attribute) and f.attr == "record"
            and len(node.args) >= 3
        ):
            continue
        cat, _entity, name = node.args[0], node.args[1], node.args[2]
        if isinstance(cat, ast.Constant) and isinstance(cat.value, str):
            if cat.value not in reg["categories"]:
                yield (
                    node.lineno,
                    f"unregistered event category '{cat.value}' — add "
                    "it to _private/event_names.py",
                )
        elif isinstance(cat, ast.Attribute):
            if (
                cat.attr not in reg["category_consts"]
                and _CAPS_RE.match(cat.attr)
            ):
                yield (
                    node.lineno,
                    f"unregistered category constant '{cat.attr}'",
                )
        if isinstance(name, ast.Constant) and isinstance(name.value, str):
            if name.value not in events:
                yield (
                    node.lineno,
                    f"unregistered event name '{name.value}' — add it "
                    "to _private/event_names.py so timeline stitching "
                    "and the state API can see it",
                )
    # Timeline-stitch literals (state.py opts in via module marker).
    if "check-event-literals" not in ctx.module:
        return
    known = events | reg["task_table"]
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Compare):
            continue
        consts: List[ast.Constant] = []
        for side in [node.left] + list(node.comparators):
            if isinstance(side, ast.Constant):
                consts.append(side)
            elif isinstance(side, (ast.Tuple, ast.List, ast.Set)):
                consts.extend(
                    e for e in side.elts if isinstance(e, ast.Constant)
                )
        for c in consts:
            if (
                isinstance(c.value, str) and _CAPS_RE.match(c.value)
                and c.value not in known
            ):
                yield (
                    c.lineno,
                    f"timeline stitch references unregistered event "
                    f"name '{c.value}'",
                )
