"""raylint: AST static analysis enforcing the runtime's concurrency and
reliability invariants (thread domains, one retry policy, at-least-once
GCS traffic, counted-never-silent faults, the event-name registry).

Run from the repo root:

    python -m tools.raylint                 # check against the baseline
    python -m tools.raylint --write-baseline  # re-snapshot the debt
    python -m tools.raylint --only fixed-sleep-retry ray_tpu/_private

See tools/raylint/markers.py for the ``# raylint:`` marker grammar and
the README "Static analysis & concurrency invariants" section for the
rule catalogue and the baseline workflow.
"""
from .engine import (  # noqa: F401
    RULES,
    FileContext,
    Violation,
    diff_baseline,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)
from . import rules  # noqa: F401 - registers the rule catalogue
