"""CLI: python -m tools.raylint [paths...]

Exit status: 0 when every violation is baselined, 1 when new
violations exist (CI fails), 2 on unparsable files.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .engine import (
    RULES, diff_baseline, lint_paths, load_baseline, write_baseline,
)

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")
)
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)
#: The linted tree: the runtime package. Tests and tools lint clean by
#: convention but are not invariant-bearing; keeping them out keeps
#: the baseline about the runtime.
DEFAULT_PATHS = [os.path.join(REPO_ROOT, "ray_tpu")]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="raylint")
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="snapshot current violations as the accepted debt",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every violation (ignore the baseline)",
    )
    ap.add_argument(
        "--only", action="append", default=None,
        help="run only the named rule (repeatable)",
    )
    ap.add_argument("--json", dest="json_out", default=None)
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, (_fn, doc) in sorted(RULES.items()):
            print(f"{name}: {doc}")
        return 0

    paths = args.paths or DEFAULT_PATHS
    violations, errors = lint_paths(paths, REPO_ROOT, only=args.only)
    for e in errors:
        print(f"raylint: parse error: {e}", file=sys.stderr)

    if args.write_baseline:
        if args.paths or args.only:
            # A narrowed run sees only a subset of the debt; writing
            # it wholesale would wipe every other tracked entry and
            # the next full `make lint` would drown in "new"
            # violations. Snapshot only from the default full scope.
            print(
                "raylint: refusing --write-baseline with explicit "
                "paths/--only — the baseline is a FULL-scope snapshot; "
                "run `python -m tools.raylint --write-baseline` bare",
                file=sys.stderr,
            )
            return 2
        write_baseline(args.baseline, violations)
        print(
            f"raylint: baseline written: {len(violations)} violation(s) "
            f"-> {args.baseline}"
        )
        return 2 if errors else 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, fixed = diff_baseline(violations, baseline)

    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "total": len(violations),
                    "new": [v.as_dict() for v in new],
                    "baselined": len(violations) - len(new),
                    "fixed_fingerprints": fixed,
                },
                f, indent=1,
            )

    for v in sorted(new, key=lambda v: (v.path, v.line)):
        print(v.render())
    summary = (
        f"raylint: {len(violations)} violation(s), "
        f"{len(violations) - len(new)} baselined, {len(new)} new"
    )
    if fixed:
        summary += (
            f"; {len(fixed)} baseline entr(ies) no longer fire — "
            "run --write-baseline to shrink the debt"
        )
    print(summary)
    if errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
