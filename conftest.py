# Repo-root conftest: makes `ray_tpu` importable and pins JAX to a virtual
# 8-device CPU mesh for tests (multi-chip sharding is validated on CPU; the
# real chip is reserved for bench.py).
#
# Note: this machine's sitecustomize registers the TPU backend and forces
# jax.config jax_platforms="axon,cpu" at interpreter start, so env vars
# alone don't stick — override through jax.config before any backend
# initializes.
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

# Lock-order witness (RAY_TPU_lock_witness=1, `make race-smoke`):
# install before the runtime modules under test construct their locks
# so every threading.Lock/RLock they create in this process is
# witnessed. One shared predicate (lock_witness.enabled) gates every
# process — subprocesses (heads, raylets, workers) self-install via
# the same maybe_install() off the inherited env var, so the driver
# can never diverge from the daemons on what counts as "enabled".
from ray_tpu._private import lock_witness as _lock_witness

_lock_witness.maybe_install()

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
