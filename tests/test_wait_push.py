"""Push-based wait: drain-by-wait loops never poll the head.

Reference behavior: raylet/wait_manager.h — waits are registered once
and completed by callbacks. VERDICT r3 #2's done-criterion: steady-state
wait loops produce zero check_ready messages (asserted via the head's
per-type message counters, the same harness test_local_dispatch uses).
"""
import time

import pytest

import ray_tpu
from ray_tpu._private.worker import global_client


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def tiny(x):
    return x * 2


@ray_tpu.remote
def slow(x):
    time.sleep(0.05)
    return x


def _msg_counts():
    r = global_client().request({"type": "msg_counts"})
    return r["counts"]


def test_drain_by_wait_never_polls_head(cluster):
    refs = [tiny.remote(i) for i in range(200)]
    before = _msg_counts()
    not_ready = refs
    seen = 0
    while not_ready:
        ready, not_ready = ray_tpu.wait(not_ready, num_returns=1)
        seen += len(ready)
    after = _msg_counts()
    assert seen == 200
    assert after.get("check_ready", 0) == before.get("check_ready", 0)
    assert after.get("wait_any", 0) == before.get("wait_any", 0)
    # Leased-task results resolve on the direct socket: the whole drain
    # should not even need a subscription round-trip per call — at most
    # one batched wait_subscribe for stragglers.
    assert (
        after.get("wait_subscribe", 0) - before.get("wait_subscribe", 0) <= 2
    )


def test_wait_results_correct_under_timeout(cluster):
    refs = [slow.remote(i) for i in range(8)]
    ready, rest = ray_tpu.wait(refs, num_returns=8, timeout=30)
    assert len(ready) == 8 and not rest
    assert sorted(ray_tpu.get(ready)) == list(range(8))


def test_wait_timeout_returns_partial(cluster):
    @ray_tpu.remote
    def hang():
        time.sleep(60)

    r = hang.remote()
    t0 = time.monotonic()
    ready, rest = ray_tpu.wait([r], num_returns=1, timeout=0.3)
    assert time.monotonic() - t0 < 5
    assert ready == [] and rest == [r]


def test_wait_gcs_routed_results_push(cluster):
    """Tasks with dependencies route via the GCS; their readiness must
    arrive as pushes on the one-shot subscription."""
    a = tiny.remote(1)
    b = tiny.remote(ray_tpu.get(a))  # plain value
    dep = tiny.remote(a)  # ref dependency -> GCS route
    ready, rest = ray_tpu.wait([b, dep], num_returns=2, timeout=30)
    assert len(ready) == 2 and not rest
    assert ray_tpu.get(dep) == 4


def test_wait_mixed_put_and_task_refs(cluster):
    p = ray_tpu.put(41)
    t = tiny.remote(5)
    ready, rest = ray_tpu.wait([p, t], num_returns=2, timeout=30)
    assert len(ready) == 2
    assert ray_tpu.get(p) == 41 and ray_tpu.get(t) == 10


def test_repeated_wait_on_same_refs(cluster):
    refs = [tiny.remote(i) for i in range(5)]
    for _ in range(3):
        ready, rest = ray_tpu.wait(refs, num_returns=5, timeout=30)
        assert len(ready) == 5 and not rest
