"""Channelized pubsub (reference: src/ray/pubsub/publisher.h) — user
channels, key-prefix filters, and built-in NODE_INFO/ACTOR lifecycle
events."""
import time

import pytest

import ray_tpu
from ray_tpu.util import pubsub


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_user_channel_roundtrip(cluster):
    got = []
    sub = pubsub.subscribe("chan-a", lambda k, d: got.append((k, d)))
    pubsub.publish("chan-a", "k1", {"x": 1})
    pubsub.publish("chan-a", "k2", [1, 2, 3])
    assert _wait(lambda: len(got) == 2), got
    assert got == [("k1", {"x": 1}), ("k2", [1, 2, 3])]
    sub.unsubscribe()
    pubsub.publish("chan-a", "k3", None)
    time.sleep(0.3)
    assert len(got) == 2  # nothing after unsubscribe


def test_key_prefix_filter(cluster):
    got = []
    pubsub.subscribe(
        "chan-b", lambda k, d: got.append(k), key_prefix="job:"
    )
    pubsub.publish("chan-b", "job:1", None)
    pubsub.publish("chan-b", "task:9", None)
    pubsub.publish("chan-b", "job:2", None)
    assert _wait(lambda: len(got) >= 2)
    time.sleep(0.2)
    assert got == ["job:1", "job:2"]


def test_publish_from_worker_reaches_driver(cluster):
    got = []
    pubsub.subscribe("events", lambda k, d: got.append((k, d)))

    @ray_tpu.remote
    def announce():
        from ray_tpu.util import pubsub as ps

        ps.publish("events", "from-worker", {"pid": True})
        return "sent"

    assert ray_tpu.get(announce.remote()) == "sent"
    assert _wait(lambda: got and got[0][0] == "from-worker"), got


def test_actor_lifecycle_channel(cluster):
    events = []
    pubsub.subscribe("ACTOR", lambda k, d: events.append((k, d["state"])))

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    assert _wait(lambda: any(s == "ALIVE" for _, s in events)), events
    ray_tpu.kill(a)
    assert _wait(lambda: any(s == "DEAD" for _, s in events)), events


def test_node_lifecycle_channel(cluster):
    from ray_tpu.cluster_utils import Cluster

    events = []
    pubsub.subscribe("NODE_INFO", lambda k, d: events.append(d["state"]))
    c = Cluster(initialize_head=False)
    node = c.add_node(num_cpus=1, label="pub-test")
    # Virtual add_node path doesn't emit ALIVE (no daemon registration),
    # but removal rides the death path.
    c.remove_node(node)
    # A DaemonCluster registration would emit ALIVE; death is the
    # critical signal for failure detectors.
    # (remove_node marks dead without _handle_node_death — accept
    # either outcome but require no crash and subscription liveness.)
    pubsub.publish("NODE_INFO", "probe", {"state": "PROBE"})
    assert _wait(lambda: "PROBE" in events), events
