"""Offline usage stats (reference: _private/usage/usage_lib.py —
same recording surface, local-JSONL sink, nothing leaves the host)."""
import json
import os

import pytest

import ray_tpu
from ray_tpu._private import usage_stats


@pytest.fixture
def sink(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_TEMP_DIR", str(tmp_path))
    monkeypatch.setattr(usage_stats, "_path", None)
    yield tmp_path


def test_record_and_flush(sink):
    usage_stats.record_library_usage("data")
    usage_stats.record_extra_usage_tag("train_backend", "jax")
    path = usage_stats.flush()
    assert path and os.path.exists(path)
    rows = usage_stats.read_all()
    assert rows and "data" in rows[-1]["libraries"]
    assert rows[-1]["tags"]["train_backend"] == "jax"


def test_disable_env(sink, monkeypatch):
    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
    assert usage_stats.flush() is None


def test_library_imports_register(sink):
    import ray_tpu.data  # noqa: F401
    import ray_tpu.tune  # noqa: F401

    snap = usage_stats.cluster_snapshot()
    assert "data" in snap["libraries"] and "tune" in snap["libraries"]


def test_shutdown_flushes(sink):
    ray_tpu.init(num_cpus=1, ignore_reinit_error=True)
    usage_stats.record_library_usage("core")
    ray_tpu.shutdown()
    rows = usage_stats.read_all()
    assert rows and "core" in rows[-1]["libraries"]
