"""Core API: tasks, objects, wait, errors.

Models the reference's python/ray/tests/test_basic.py coverage.
"""
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, RayTaskError, WorkerCrashedError


@ray_tpu.remote
def double(x):
    return 2 * x


def test_simple_task(ray_start):
    assert ray_tpu.get(double.remote(21)) == 42


def test_many_tasks(ray_start):
    refs = [double.remote(i) for i in range(50)]
    assert ray_tpu.get(refs) == [2 * i for i in range(50)]


def test_put_get_roundtrip(ray_start):
    for value in [1, "x", {"a": [1, 2]}, None, (1, 2)]:
        assert ray_tpu.get(ray_tpu.put(value)) == value


def test_put_get_numpy_large(ray_start):
    arr = np.random.RandomState(0).rand(1 << 20)  # 8 MiB -> shm path
    out = ray_tpu.get(ray_tpu.put(arr))
    np.testing.assert_array_equal(arr, out)


def test_object_ref_as_arg(ray_start):
    a = double.remote(1)
    b = double.remote(a)
    assert ray_tpu.get(b) == 4


def test_nested_ref_passthrough(ray_start):
    @ray_tpu.remote
    def unwrap(container):
        # Nested refs are not auto-resolved (borrowing semantics).
        inner = container["ref"]
        return ray_tpu.get(inner)

    ref = ray_tpu.put(123)
    assert ray_tpu.get(unwrap.remote({"ref": ref})) == 123


def test_multiple_returns(ray_start):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3]) == [1, 2, 3]


def test_task_error_propagates(ray_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_error_is_ray_task_error(ray_start):
    @ray_tpu.remote
    def boom():
        raise KeyError("k")

    with pytest.raises(RayTaskError):
        ray_tpu.get(boom.remote())


def test_dependent_task_fails(ray_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("upstream")

    with pytest.raises(ValueError):
        ray_tpu.get(double.remote(boom.remote()))


def test_get_timeout(ray_start):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_wait_basic(ray_start):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(20)

    ready, rest = ray_tpu.wait([fast.remote(), slow.remote()], num_returns=1, timeout=10)
    assert len(ready) == 1 and len(rest) == 1
    assert ray_tpu.get(ready[0]) == 1


def test_wait_timeout_returns_partial(ray_start):
    @ray_tpu.remote
    def slow():
        time.sleep(20)

    ready, rest = ray_tpu.wait([slow.remote()], num_returns=1, timeout=0.2)
    assert ready == [] and len(rest) == 1


def test_nested_task_submission(ray_start):
    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(double.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10)) == 21


def test_options_override(ray_start):
    assert ray_tpu.get(double.options(num_cpus=2).remote(5)) == 10


def test_cluster_resources(ray_start):
    assert ray_tpu.cluster_resources()["CPU"] == 4.0


def test_worker_crash_surfaces(ray_start):
    @ray_tpu.remote
    def die():
        import os

        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=30)


def test_free_objects(ray_start):
    ref = ray_tpu.put(np.zeros(1 << 20))
    assert ray_tpu.get(ref) is not None
    ray_tpu.free([ref])
    # Freed objects are gone from the directory; a get would block, so just
    # confirm wait() no longer reports it ready.
    time.sleep(0.2)
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    assert ready == []


def test_python_objects_with_refs_inside_returns(ray_start):
    @ray_tpu.remote
    def make_ref():
        return ray_tpu.put("inner")

    outer_ref = make_ref.remote()
    inner_ref = ray_tpu.get(outer_ref)
    assert ray_tpu.get(inner_ref) == "inner"


def test_leases_released_when_client_dies(ray_start):
    # Nested clients (actors submitting tasks) lease workers for the
    # direct task transport; killing the client must give the leased
    # workers (and their resources) back (reference: leases are tied to
    # the lessee in direct_task_transport.cc).
    import time

    @ray_tpu.remote
    def tiny():
        return 1

    @ray_tpu.remote
    class Submitter:
        def drive(self, n):
            return sum(ray_tpu.get([tiny.remote() for _ in range(n)]))

    total = ray_tpu.cluster_resources().get("CPU", 0)
    s = Submitter.remote()
    assert ray_tpu.get(s.drive.remote(20)) == 20
    ray_tpu.kill(s)
    deadline = time.time() + 10
    while time.time() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) == total:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources().get("CPU", 0) == total


def test_direct_task_transport_error_and_retry(ray_start):
    # Errors propagate through the leased path; worker death mid-task
    # falls back to GCS rescheduling (system retries).
    @ray_tpu.remote
    def boom():
        raise ValueError("nope")

    # warm the function (first call registers blob via GCS) then leased
    with pytest.raises(ValueError):
        ray_tpu.get(boom.remote())
    with pytest.raises(ValueError):
        ray_tpu.get(boom.remote())

    @ray_tpu.remote(max_retries=2)
    def die_once():
        import os
        import ray_tpu as rt

        marker = b"died_once_marker"
        if not rt._private.worker.global_client().kv_get(marker):
            rt._private.worker.global_client().kv_put(marker, b"1")
            os._exit(1)
        return "recovered"

    assert ray_tpu.get(die_once.remote(), timeout=60) == "recovered"
