"""Scheduling policies: hybrid binpack/spread default, task-level
SPREAD, NodeAffinity hard/soft.

Models the reference's scheduling policy unit tests
(src/ray/raylet/scheduling/policy/ tests): policy-level checks on a
synthetic node view plus end-to-end placement assertions on a virtual
multi-node cluster (placement observed through the per-node resource
view, since virtual nodes share one host).
"""
import os
import tempfile
import time
import uuid

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def three_nodes():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    cluster.add_node(num_cpus=4, label="b")
    cluster.add_node(num_cpus=4, label="c")
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


# ------------------------------------------------------- policy unit level
def _mk_nodes(avails, totals=None):
    from ray_tpu._private.gcs import NodeState
    from ray_tpu._private.ids import NodeID

    nodes = []
    for i, avail in enumerate(avails):
        total = (totals or avails)[i]
        nodes.append(
            NodeState(
                node_id=NodeID(bytes([i]) * 16),
                total=dict(total),
                available=dict(avail),
            )
        )
    return nodes


class _PolicyHarness:
    """Borrows the policy methods off GCSServer without starting one."""

    from ray_tpu._private.gcs import GcsServer as _G

    _node_util = _G._node_util
    _hybrid_pick = _G._hybrid_pick

    def __init__(self, seed=0):
        import random

        self._sched_rng = random.Random(seed)


def test_node_util_is_critical_resource_fraction():
    h = _PolicyHarness()
    (n,) = _mk_nodes(
        [{"CPU": 2.0, "mem": 8.0}], totals=[{"CPU": 4.0, "mem": 8.0}]
    )
    # Placing 1 CPU → 3/4 used on CPU, 0 on mem → critical = 0.75.
    assert h._node_util(n, {"CPU": 1.0}) == pytest.approx(0.75)


def test_hybrid_packs_below_threshold():
    """Nodes under the spread threshold score equal → stable id order →
    successive picks PACK onto the first node instead of scattering."""
    h = _PolicyHarness()
    nodes = _mk_nodes([{"CPU": 8.0}, {"CPU": 8.0}, {"CPU": 8.0}])
    picks = set()
    for _ in range(8):
        n = h._hybrid_pick(nodes, {"CPU": 1.0})
        picks.add(n.node_id.binary())
    assert len(picks) == 1  # all 8 picks pack (top-k of 3 nodes = 1)


def test_hybrid_spreads_when_saturated():
    """Past the threshold the policy goes least-utilized-first."""
    h = _PolicyHarness()
    full, emptier = _mk_nodes(
        [{"CPU": 1.0}, {"CPU": 4.0}],
        totals=[{"CPU": 8.0}, {"CPU": 8.0}],
    )
    # Both nodes land above 0.5 after placement → less-utilized wins.
    n = h._hybrid_pick([full, emptier], {"CPU": 1.0})
    assert n is emptier


# ------------------------------------------------------------- end to end
def _block_marker(cluster_nodes_before):
    """Node-availability snapshot diff: which nodes lost CPU."""
    after = {n["label"]: n["available"].get("CPU", 0) for n in ray_tpu.nodes()}
    return {
        lbl: cluster_nodes_before[lbl] - after.get(lbl, 0)
        for lbl in cluster_nodes_before
    }


def _avail_by_label():
    return {n["label"]: n["available"].get("CPU", 0) for n in ray_tpu.nodes()}


@ray_tpu.remote
def _hold(sec: float):
    time.sleep(sec)
    return "ok"


@ray_tpu.remote
def _hold_until(path: str):
    """Holds its CPU until the release file appears."""
    while not os.path.exists(path):
        time.sleep(0.05)
    return "ok"


def test_spread_strategy_spreads_tasks(three_nodes):
    before = _avail_by_label()
    # Flag-gated holds: placement sticks only once a worker exists, and
    # worker cold-start under a loaded host can take >10s per node — the
    # holds must outlive the slowest spawn so all 6 placements overlap
    # observably, then release instantly once asserted.
    flag = os.path.join(
        tempfile.gettempdir(), f"spread-release-{uuid.uuid4().hex}"
    )
    refs = [
        _hold_until.options(scheduling_strategy="SPREAD").remote(flag)
        for _ in range(6)
    ]
    try:
        deadline = time.time() + 60
        used = {}
        while time.time() < deadline:
            used = _block_marker(before)
            if sum(used.values()) >= 6:
                break
            time.sleep(0.1)
        # SPREAD: 6 tasks over 3 four-CPU nodes → every node took two.
        assert all(v == 2 for v in used.values()), used
    finally:
        with open(flag, "w") as f:
            f.write("go")
        try:
            ray_tpu.get(refs, timeout=30)
        finally:
            os.unlink(flag)


def test_default_hybrid_packs_first_node(three_nodes):
    before = _avail_by_label()
    refs = [_hold.remote(3.0) for _ in range(2)]
    deadline = time.time() + 10
    while time.time() < deadline:
        used = _block_marker(before)
        if sum(used.values()) >= 2:
            break
        time.sleep(0.1)
    used = _block_marker(before)
    # 2 one-CPU tasks on an empty 3x4-CPU cluster stay under the 0.5
    # threshold on one node → both pack together.
    assert sorted(used.values()) == [0, 0, 2], used
    ray_tpu.get(refs)


def test_node_affinity_hard_pins(three_nodes):
    target = next(n for n in ray_tpu.nodes() if n["label"] == "c")
    before = _avail_by_label()
    refs = [
        _hold.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=target["node_id"], soft=False
            )
        ).remote(2.0)
        for _ in range(3)
    ]
    deadline = time.time() + 10
    while time.time() < deadline:
        used = _block_marker(before)
        if used.get("c", 0) >= 3:
            break
        time.sleep(0.1)
    used = _block_marker(before)
    assert used.get("c") == 3 and sum(used.values()) == 3, used
    ray_tpu.get(refs)


def test_node_affinity_hard_to_missing_node_fails(three_nodes):
    ref = _hold.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=b"\xff" * 16, soft=False
        )
    ).remote(0.1)
    with pytest.raises(ray_tpu.exceptions.TaskUnschedulableError):
        ray_tpu.get(ref, timeout=10)


def test_node_affinity_soft_falls_back(three_nodes):
    """Soft affinity to a gone node still schedules somewhere."""
    ref = _hold.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=b"\xee" * 16, soft=True
        )
    ).remote(0.1)
    assert ray_tpu.get(ref, timeout=15) == "ok"
