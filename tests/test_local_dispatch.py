"""Raylet local dispatch: intra-node task chains lease from the node's
own daemon, not the head.

Reference behavior: the raylet owns local scheduling
(src/ray/raylet/scheduling/cluster_task_manager.cc:44,
local_task_manager.cc:112) with periodic resource-view sync to the GCS
(ray_syncer.h:88). Here: workers a raylet spawns lease follow-up work
from the raylet's local pool over a node-local socket; the head sees
only amortized bookkeeping (batched task_done, heartbeat resource
sync), asserted via the head's per-type message counters.
"""
import time

import pytest

import ray_tpu
from ray_tpu._private.worker import global_client
from ray_tpu.cluster_utils import DaemonCluster


@pytest.fixture
def daemon_cluster():
    cluster = DaemonCluster(head_node_args={"num_cpus": 0, "tcp_port": 0})
    yield cluster
    cluster.shutdown()


@ray_tpu.remote
def leaf(x):
    return x + 1


@ray_tpu.remote
def chain_driver(n):
    # Runs ON the raylet node; its nested submissions should lease from
    # the local raylet, not the head.
    import ray_tpu as rt

    total = 0
    for i in range(n):
        total += rt.get(leaf.remote(i))
    return total


def _head_counts():
    reply = global_client().request({"type": "msg_counts"})
    return reply["counts"]


def test_intra_node_chain_stays_off_head(daemon_cluster):
    daemon_cluster.add_node(num_cpus=4)

    # Warm up: ships the function blobs, spawns the chain worker, and
    # lets the raylet's local pool come up.
    assert ray_tpu.get(chain_driver.remote(3), timeout=120) == 6

    before = _head_counts()
    n = 60
    assert ray_tpu.get(chain_driver.remote(n), timeout=180) == n * (n + 1) // 2
    after = _head_counts()

    # The head granted no leases for the chain's leaf tasks...
    leases = after.get("lease_worker", 0) - before.get("lease_worker", 0)
    assert leases <= 1, f"head granted {leases} leases for an intra-node chain"
    # ...and per-task head traffic is amortized bookkeeping only
    # (batched task_done, ref flushes, heartbeats) — far below one
    # message per task.
    per_task_msgs = sum(after.values()) - sum(before.values())
    assert per_task_msgs < n, (
        f"{per_task_msgs} head messages for {n} intra-node tasks: "
        f"{ {k: after.get(k, 0) - before.get(k, 0) for k in after} }"
    )


@pytest.fixture
def delayed_head_cluster():
    # Everything runs on one machine, so a head hop costs the same as a
    # node-local hop and the designed benefit of local dispatch (no
    # NETWORK round trip to a contended head) cannot show. Model the
    # network the way the reference does in its own tests
    # (RAY_testing_asio_delay_us): inject a 3 ms delay into head-side
    # lease handling only.
    cluster = DaemonCluster(
        head_node_args={
            "num_cpus": 0,
            "tcp_port": 0,
            "_system_config": {
                "testing_rpc_delay_us": "lease_worker=3000:3000"
            },
        }
    )
    yield cluster
    cluster.shutdown()


def test_local_dispatch_beats_remote_head_leasing(delayed_head_cluster):
    """Cold dispatch bursts with a modeled head RTT.

    On one machine both paths share a single core, so scheduler noise
    swamps the hop-count difference either way; the load-bearing claim
    (the head never sees intra-node dispatch) is the message-count test
    above. This test reports both rates and bounds the local path to
    the same order of magnitude."""
    delayed_head_cluster.add_node(num_cpus=4)

    @ray_tpu.remote
    def burst(n, local):
        import os
        import time as _t

        import ray_tpu as rt
        from ray_tpu._private.worker import global_client as gc

        if not local:
            os.environ.pop("RAY_TPU_LOCAL_RAYLET", None)
        rt.get(leaf.remote(0))  # ship the blob once
        best = 0.0
        for _ in range(3):
            # Cold burst: drop warm leases so each round pays dispatch.
            client = gc()
            with client._lease_lock:
                leases = [l for pool in client._leases.values() for l in pool]
                client._leases.clear()
            for lease in leases:
                lease["returned"] = True
                lease["conn"].close()
                client._send_lease_return(
                    lease["worker_id"], lease.get("raylet", False)
                )
            t0 = _t.perf_counter()
            rt.get([leaf.remote(i) for i in range(n)])
            best = max(best, n / (_t.perf_counter() - t0))
        return best

    local = ray_tpu.get(burst.remote(100, True), timeout=240)
    via_head = ray_tpu.get(burst.remote(100, False), timeout=240)
    print(f"cold dispatch with 3ms head RTT: head-leased {via_head:,.0f}/s, "
          f"raylet-leased {local:,.0f}/s")
    # Same order of magnitude (per the docstring): on a 1-core shared
    # box the absolute ratio swings several x between runs (flaked at
    # 0.2 in a full-suite run) — the load-bearing no-head-hop property
    # is the message-count test above; this only guards collapse.
    assert local > via_head * 0.1, (via_head, local)


@ray_tpu.remote(num_tpus=1)
def tpu_leaf(x):
    import os

    return (x, os.environ.get("TPU_VISIBLE_CHIPS"))


@ray_tpu.remote
def tpu_chain_driver(n):
    # Runs ON the raylet node; nested single-chip TPU submissions lease
    # from the LOCAL raylet (dedicated chip per local TPU worker).
    import ray_tpu as rt

    out = [rt.get(tpu_leaf.remote(i)) for i in range(n)]
    return out


def test_tpu_tasks_lease_locally(daemon_cluster):
    daemon_cluster.add_node(num_cpus=2, resources={"TPU": 2.0})

    # Warm up until the cold-started local TPU worker serves the whole
    # chain (first submissions fall back to the GCS route while the
    # dedicated-chip worker spawns).
    deadline = time.time() + 60
    while time.time() < deadline:
        first = ray_tpu.get(tpu_chain_driver.remote(2), timeout=180)
        if all(c is not None for _, c in first):
            break
        time.sleep(0.5)
    else:
        pytest.fail(f"local TPU worker never served the chain: {first}")

    before = _head_counts()
    n = 12
    out = ray_tpu.get(tpu_chain_driver.remote(n), timeout=180)
    after = _head_counts()
    assert [v for v, _ in out] == list(range(n))
    # Every task ran on a worker pinned to a dedicated local chip.
    chips = {c for _, c in out}
    assert chips <= {"0", "1"} and chips, chips
    # The head granted no leases for the chain's TPU tasks (the head
    # lease pool is CPU-only; these leased from the node daemon).
    leases = after.get("lease_worker", 0) - before.get("lease_worker", 0)
    assert leases <= 1, f"head granted {leases} leases for local TPU tasks"


def test_tpu_local_leases_sync_head_resource_view(daemon_cluster):
    daemon_cluster.add_node(num_cpus=2, resources={"TPU": 2.0})

    @ray_tpu.remote(num_tpus=1)
    def quick_tpu():
        import os

        return os.environ.get("TPU_VISIBLE_CHIPS")

    @ray_tpu.remote(num_tpus=1)
    def slow_tpu():
        import time as _t

        _t.sleep(4.0)
        return "done"

    @ray_tpu.remote
    def hold_tpu_lease():
        """Runs ON the raylet node. After warming the local TPU pool,
        holds ONE locally-leased chip: the task reaches the head only
        via the heartbeat's local_tpus_in_use sync, which must drain
        the head's availability view."""
        import time as _t

        import ray_tpu as rt
        from ray_tpu._private.worker import global_client

        # Warm until the local TPU worker serves nested submissions
        # (early ones take the GCS route while it cold-starts).
        deadline = _t.monotonic() + 60
        while _t.monotonic() < deadline:
            rt.get(quick_tpu.remote())
            counts = global_client().request({"type": "msg_counts"})[
                "counts"
            ]
            before_submits = counts.get("submit_task", 0)
            rt.get(quick_tpu.remote())
            counts = global_client().request({"type": "msg_counts"})[
                "counts"
            ]
            if counts.get("submit_task", 0) == before_submits:
                break  # served without a head submit: local lease live
            _t.sleep(0.5)
        else:
            return "never-local", None, None

        # First call of each function ships its blob via the head by
        # design; warm slow_tpu past that before the measured round.
        rt.get(slow_tpu.remote())
        counts = global_client().request({"type": "msg_counts"})["counts"]
        before_submits = counts.get("submit_task", 0)
        ref = slow_tpu.remote()
        # Sample the head's availability while the local lease is held;
        # only the heartbeat sync can move it for this task.
        low = 99.0
        for _ in range(30):
            avail = global_client().cluster_info()["available"]
            low = min(low, avail.get("TPU", 0.0))
            _t.sleep(0.15)
        out = rt.get(ref)
        counts = global_client().request({"type": "msg_counts"})["counts"]
        submits = counts.get("submit_task", 0) - before_submits
        return out, low, submits

    out, low, submits = ray_tpu.get(hold_tpu_lease.remote(), timeout=240)
    assert out == "done", out
    assert submits == 0, (
        f"slow_tpu went through the head ({submits} submits) — "
        "not a local lease"
    )
    assert low <= 1.0, (
        f"head TPU view never drained below 2: min available {low}"
    )
