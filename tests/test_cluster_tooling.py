"""State API, timeline, metrics, cluster harness, jobs, autoscaler, CLI.

Models the reference's python/ray/tests coverage of util/state,
ray.timeline, util/metrics, cluster_utils, job submission, and the
autoscaler fake-provider loop.
"""
import json
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_state_api_lists(cluster):
    from ray_tpu.util.state import (
        list_actors,
        list_nodes,
        list_tasks,
        list_workers,
        summarize_tasks,
    )

    @ray_tpu.remote
    def f(x):
        return x

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.options(name="state_test_actor").remote()
    ray_tpu.get(a.ping.remote())
    ray_tpu.get([f.remote(i) for i in range(5)])

    actors = list_actors()
    assert any(x["name"] == "state_test_actor" for x in actors)
    nodes = list_nodes()
    assert len(nodes) >= 1 and nodes[0]["alive"]
    workers = list_workers()
    assert len(workers) >= 1
    tasks = list_tasks()
    f_tasks = [t for t in tasks if t["name"] == "f"]
    assert len(f_tasks) == 5
    assert all(t["state"] == "FINISHED" for t in f_tasks)
    summary = summarize_tasks()
    assert summary["by_func_name"]["f"]["FINISHED"] == 5


def test_list_tasks_read_your_writes(cluster):
    """A list issued immediately after get() must include every task the
    caller saw finish, even when completions rode the leased-worker
    direct path whose task_done records are batched (the GCS forces a
    worker flush barrier before answering — gcs._barrier_flush_events)."""
    from ray_tpu.util.state import list_tasks

    @ray_tpu.remote
    def g(x):
        return x

    done = 0
    for burst in range(4):
        ray_tpu.get([g.remote(i) for i in range(8)])
        done += 8
        g_tasks = [t for t in list_tasks() if t["name"] == "g"]
        assert len(g_tasks) == done, f"burst {burst}: {len(g_tasks)}/{done}"


def test_timeline_export(cluster, tmp_path):
    @ray_tpu.remote
    def slow():
        time.sleep(0.05)
        return 1

    ray_tpu.get([slow.remote() for _ in range(4)])
    out = tmp_path / "trace.json"
    ray_tpu.timeline(str(out))
    trace = json.loads(out.read_text())
    spans = [t for t in trace if t["name"] == "slow"]
    assert len(spans) == 4
    assert all(t["ph"] == "X" and t["dur"] >= 50_000 * 0.5 for t in spans)


def test_metrics_counter_gauge(cluster):
    from ray_tpu.util.metrics import Counter, Gauge, get_metrics_snapshot

    c = Counter("test_requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = Gauge("test_qsize")
    g.set(7.0)
    snap = get_metrics_snapshot()
    series = {tuple(s["tags"].items()): s["value"]
              for s in snap["test_requests"]["series"]}
    assert series[(("route", "/a"),)] == 3.0
    assert snap["test_qsize"]["series"][0]["value"] == 7.0


def test_cluster_add_remove_node(cluster):
    c = Cluster(initialize_head=False)
    node = c.add_node(num_cpus=2, resources={"special": 1})
    assert ray_tpu.cluster_resources().get("special") == 1.0

    @ray_tpu.remote(resources={"special": 1})
    def on_special():
        return "ran"

    assert ray_tpu.get(on_special.remote()) == "ran"
    c.remove_node(node)
    deadline = time.time() + 10
    while time.time() < deadline:
        if "special" not in {
            k
            for n in ray_tpu.nodes()
            if n["alive"]
            for k in n["total"]
        }:
            break
        time.sleep(0.1)


def test_job_submission(cluster):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job ran ok')\""
    )
    status = client.wait_until_finish(job_id, timeout_s=60)
    assert status == JobStatus.SUCCEEDED
    assert "job ran ok" in client.get_job_logs(job_id)
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)

    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    assert client.wait_until_finish(bad, timeout_s=60) == JobStatus.FAILED


def test_autoscaler_scales_up_and_down(cluster):
    from ray_tpu.autoscaler import Autoscaler

    scaler = Autoscaler(
        {"cpu_worker": {"resources": {"CPU": 2, "scale": 2}, "max_workers": 3}},
        idle_timeout_s=2.0,
        interval_s=0.2,
    )
    scaler.start()
    try:
        # Demand needing the custom resource only autoscaled nodes have.
        @ray_tpu.remote(resources={"scale": 1})
        def burst(i):
            time.sleep(0.3)
            return i

        refs = [burst.remote(i) for i in range(6)]
        assert sorted(ray_tpu.get(refs, timeout=90)) == list(range(6))
        assert scaler.num_launches >= 1
        # Idle nodes terminate after the timeout.
        deadline = time.time() + 30
        while time.time() < deadline:
            if scaler.num_terminations >= scaler.num_launches:
                break
            time.sleep(0.25)
        assert scaler.num_terminations >= 1
    finally:
        scaler.stop()


def test_cli_status_and_list(tmp_path):
    """Drive the CLI against a standalone head (start → status → list →
    stop), exercising the session file + address='auto' path."""
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--head", "--num-cpus", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            r = subprocess.run(
                [sys.executable, "-m", "ray_tpu", "status"],
                env=env, capture_output=True, text=True, timeout=60,
            )
            if r.returncode == 0 and "Cluster status" in r.stdout:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("CLI status never succeeded")
        r = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "list", "nodes"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0 and "node_id" in r.stdout
    finally:
        subprocess.run(
            [sys.executable, "-m", "ray_tpu", "stop"], env=env,
            capture_output=True, timeout=30,
        )
        try:
            head.wait(timeout=20)
        except subprocess.TimeoutExpired:
            head.kill()
