"""Dashboard HTTP API + tracing spans."""
import json
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_dashboard_serves_state(cluster):
    from ray_tpu.dashboard import start_dashboard

    url = start_dashboard(port=18266)

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(3)])

    with urllib.request.urlopen(f"{url}/") as r:
        shell = r.read()
        assert b"ray_tpu dashboard" in shell
        assert b"/static/app.js" in shell  # SPA shell loads the app
    with urllib.request.urlopen(f"{url}/static/app.js") as r:
        js = r.read()
        assert r.headers.get_content_type() == "application/javascript"
        # every nav page has a renderer
        for page in (b"overview", b"nodes", b"jobs", b"serve", b"profile"):
            assert b"PAGES." + page in js
    with urllib.request.urlopen(f"{url}/static/style.css") as r:
        assert r.headers.get_content_type() == "text/css"
    try:
        urllib.request.urlopen(f"{url}/static/../__init__.py")
        assert False, "traversal must 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    with urllib.request.urlopen(f"{url}/api/jobs") as r:
        assert json.loads(r.read()) == []  # no jobs submitted yet
    with urllib.request.urlopen(f"{url}/api/cluster") as r:
        cluster_info = json.loads(r.read())
        assert cluster_info["total"]["CPU"] == 4.0
    with urllib.request.urlopen(f"{url}/api/tasks") as r:
        tasks = json.loads(r.read())
        assert any(t["name"] == "f" for t in tasks)
    with urllib.request.urlopen(f"{url}/api/nodes") as r:
        assert len(json.loads(r.read())) >= 1


def test_tracing_spans_parent_child(cluster, monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACE", "1")
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def child_task(x):
        time.sleep(0.02)
        return x * 2

    with tracing.span("driver_block"):
        ref = child_task.remote(21)
        assert ray_tpu.get(ref) == 42

    spans = tracing.get_trace()
    names = {s["name"] for s in spans}
    assert "driver_block" in names and "child_task" in names
    driver = next(s for s in spans if s["name"] == "driver_block")
    child = next(s for s in spans if s["name"] == "child_task")
    # Same trace; the task span is a child of the driver span.
    assert child["trace_id"] == driver["trace_id"]
    assert child["parent_span_id"] == driver["span_id"]
    assert child["end"] - child["start"] >= 0.015
