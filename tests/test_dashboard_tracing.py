"""Dashboard HTTP API + tracing spans."""
import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_dashboard_serves_state(cluster):
    from ray_tpu.dashboard import start_dashboard

    url = start_dashboard(port=18266)

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(3)])

    with urllib.request.urlopen(f"{url}/") as r:
        assert b"ray_tpu dashboard" in r.read()
    with urllib.request.urlopen(f"{url}/api/cluster") as r:
        cluster_info = json.loads(r.read())
        assert cluster_info["total"]["CPU"] == 4.0
    with urllib.request.urlopen(f"{url}/api/tasks") as r:
        tasks = json.loads(r.read())
        assert any(t["name"] == "f" for t in tasks)
    with urllib.request.urlopen(f"{url}/api/nodes") as r:
        assert len(json.loads(r.read())) >= 1


def test_tracing_spans_parent_child(cluster, monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACE", "1")
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def child_task(x):
        time.sleep(0.02)
        return x * 2

    with tracing.span("driver_block"):
        ref = child_task.remote(21)
        assert ray_tpu.get(ref) == 42

    spans = tracing.get_trace()
    names = {s["name"] for s in spans}
    assert "driver_block" in names and "child_task" in names
    driver = next(s for s in spans if s["name"] == "driver_block")
    child = next(s for s in spans if s["name"] == "child_task")
    # Same trace; the task span is a child of the driver span.
    assert child["trace_id"] == driver["trace_id"]
    assert child["parent_span_id"] == driver["span_id"]
    assert child["end"] - child["start"] >= 0.015
