"""Worker spawning: pipelined zygote forks and their failure escapes
(reference behavior: worker_pool.cc StartWorkerProcess async spawn with
registration-failure cleanup)."""
import subprocess
import sys

from ray_tpu._private.spawn import ForkedProc


def test_forked_proc_fallback_rescues_failed_fork():
    """A zygote fork failure escapes to the cold-path Popen: the handle
    resolves to the fallback child and nobody is told of a death."""
    deaths = []
    proc = ForkedProc(
        on_fail=lambda: deaths.append(1),
        fallback=lambda: subprocess.Popen([sys.executable, "-c", "pass"]),
    )
    proc._fail()  # what the zygote reply loop does on a pid-less reply
    assert proc.pid > 0
    assert proc.wait(timeout=30) == 0
    assert proc.poll() == 0  # reaped via the Popen handle
    assert not deaths


def test_forked_proc_on_fail_when_fallback_also_fails():
    deaths = []

    def bad_fallback():
        raise OSError("no more processes")

    proc = ForkedProc(on_fail=lambda: deaths.append(1), fallback=bad_fallback)
    proc._fail()
    assert proc.poll() == 1
    assert deaths == [1]


def test_forked_proc_signal_before_resolve_is_delivered():
    """A kill issued while the fork is in flight lands when the pid
    resolves (the reply loop runs _resolve)."""
    proc = ForkedProc()
    proc.kill()  # queued: no pid yet
    child = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])
    proc._resolve(child.pid)
    assert child.wait(timeout=30) != 0  # SIGKILL delivered on resolve
