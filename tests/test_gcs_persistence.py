"""GCS persistence + head restart recovery.

Reference behavior: the Redis-backed gcs store_client
(src/ray/gcs/store_client/redis_store_client.h) and
NotifyGCSRestart (src/ray/raylet/node_manager.h:614): kill the head,
restart it on the same endpoint, and the cluster recovers — daemons
rejoin, named/detached actors restart from their creation specs, KV
survives, and tasks queued at the old head complete.
"""
import os
import secrets
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_head(session_dir: str, port: int, authkey: str,
                extra_env: dict = None) -> subprocess.Popen:
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu._private.head_main",
            "--session-dir", session_dir,
            "--tcp-port", str(port),
            "--authkey", authkey,
            "--num-cpus", "0",
        ],
        env={**os.environ, "PYTHONPATH": REPO, **(extra_env or {})},
        stderr=subprocess.PIPE,
    )
    # Wait for the listening line.
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stderr.readline().decode(errors="replace")
        if "head up" in line:
            return proc
        if proc.poll() is not None:
            raise RuntimeError(f"head exited: {proc.stderr.read().decode()}")
    raise TimeoutError("head did not come up")


def _spawn_raylet(address: str, authkey: str, resources: str) -> subprocess.Popen:
    return subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu._private.raylet",
            "--address", address,
            "--authkey", authkey,
            "--resources", resources,
            "--transfer-host", "127.0.0.1",
        ],
        env={**os.environ, "PYTHONPATH": REPO},
        stderr=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
    )


def _run_driver(code: str, address: str) -> str:
    out = subprocess.run(
        [sys.executable, "-c", code, address],
        env={**os.environ, "PYTHONPATH": REPO},
        capture_output=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr.decode(errors="replace")
    return out.stdout.decode(errors="replace")


PHASE1 = """
import sys, time
import ray_tpu

ray_tpu.init(address=sys.argv[1])

@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0
    def mark(self, key):
        import ray_tpu as rt
        from ray_tpu._private.worker import global_client
        self.n += 1
        global_client().kv_put(key.encode(), str(self.n).encode())
        return self.n

# Detached + named + restartable: survives this driver, restarts after
# head failover, and its method calls route via the GCS (so they queue
# head-side while the actor is still pending on the 'late' resource).
c = Counter.options(
    name="survivor", lifetime="detached", max_restarts=3,
    resources={"late": 1},
).remote()
c.mark.remote("queued_marker")
time.sleep(1.0)  # let the buffered call + creation spec land in the GCS
from ray_tpu._private.worker import global_client
global_client().kv_put(b"phase1", b"done")
time.sleep(0.5)  # persist tick
print("PHASE1-OK")
"""

PHASE2 = """
import sys, time
import ray_tpu
from ray_tpu._private.worker import global_client

ray_tpu.init(address=sys.argv[1])
client = global_client()
assert client.kv_get(b"phase1") == b"done", "kv lost across restart"

# Named actor resolves after head restart.
c = ray_tpu.get_actor("survivor")

# The task queued at the OLD head completed after failover.
deadline = time.time() + 60
val = None
while time.time() < deadline:
    val = client.kv_get(b"queued_marker")
    if val is not None:
        break
    time.sleep(0.5)
assert val is not None, "queued task never completed after head restart"

# And the restarted actor serves new calls.
n = ray_tpu.get(c.mark.remote("post_restart"), timeout=60)
assert n >= 1
print("PHASE2-OK", val.decode(), n)
"""


def test_head_restart_recovers_state(tmp_path):
    session_dir = str(tmp_path / "headsess")
    port = _free_port()
    authkey = secrets.token_bytes(16).hex()
    address = f"127.0.0.1:{port}?{authkey}"

    head = _spawn_head(session_dir, port, authkey)
    raylet1 = _spawn_raylet(f"127.0.0.1:{port}", authkey, '{"CPU": 2}')
    try:
        time.sleep(1.0)
        assert "PHASE1-OK" in _run_driver(PHASE1, address)

        # SIGKILL the head mid-session: the actor is still PENDING on
        # the missing 'late' resource, its first call queued head-side.
        head.kill()
        head.wait(timeout=10)
        time.sleep(0.5)

        head = _spawn_head(session_dir, port, authkey)

        # The surviving raylet rejoins; a new node brings the 'late'
        # resource so the detached actor can finally schedule.
        raylet2 = _spawn_raylet(
            f"127.0.0.1:{port}", authkey, '{"CPU": 1, "late": 1}'
        )
        try:
            out = _run_driver(PHASE2, address)
            assert "PHASE2-OK" in out
        finally:
            raylet2.kill()
    finally:
        for p in (raylet1, head):
            try:
                p.kill()
            except Exception:
                pass


def test_mid_persist_kill_loads_last_complete_generation(tmp_path):
    """ISSUE 9 satellite: a head killed MID persist tick — new table
    files on disk, manifest not yet swapped (chaos kill point
    gcs.mid_persist) — must never leave a torn snapshot: the restarted
    head loads the last COMPLETE generation (the manifest-last atomic
    rename ordering is the crash-consistency contract)."""
    import pickle

    session_dir = str(tmp_path / "headsess")
    port = _free_port()
    authkey = secrets.token_bytes(16).hex()
    address = f"127.0.0.1:{port}?{authkey}"

    # The 1st dirty persist tick (marker A) completes; the 2nd (marker
    # B) dies between the table-file writes and the manifest swap.
    head = _spawn_head(
        session_dir, port, authkey,
        extra_env={
            "RAY_TPU_chaos_spec": "kill:gcs.mid_persist=2?role=head",
            "RAY_TPU_chaos_seed": "1",
        },
    )
    state_dir = os.path.join(session_dir, "gcs_state.d")

    def manifest_kv_file():
        try:
            with open(os.path.join(state_dir, "manifest.pkl"), "rb") as f:
                return pickle.load(f).get("kv")
        except (OSError, pickle.PickleError):
            return None

    try:
        _run_driver(
            """
import sys
import ray_tpu
from ray_tpu._private.worker import global_client
ray_tpu.init(address=sys.argv[1])
global_client().kv_put(b"marker_a", b"1")
print("A-OK")
""",
            address,
        )
        # Wait for tick 1 (marker_a) to land in the manifest.
        deadline = time.time() + 20
        while time.time() < deadline and manifest_kv_file() is None:
            time.sleep(0.1)
        gen1_kv = manifest_kv_file()
        assert gen1_kv is not None, "first persist never landed"

        # marker_b dirties the kv table; the persist tick for it dies
        # at the kill point (after table files, before manifest swap).
        subprocess.run(
            [sys.executable, "-c", """
import sys
import ray_tpu
from ray_tpu._private.worker import global_client
ray_tpu.init(address=sys.argv[1])
global_client().kv_put(b"marker_b", b"1")
""", address],
            env={**os.environ, "PYTHONPATH": REPO},
            timeout=60,
        )
        try:
            head.wait(timeout=30)  # the kill point fires on that tick
        except subprocess.TimeoutExpired:
            raise AssertionError("head survived the mid-persist kill point")
        # Torn state on disk: a NEWER kv table file exists but the
        # manifest still names the last complete generation.
        assert manifest_kv_file() == gen1_kv
        newer = [
            f for f in os.listdir(state_dir)
            if f.startswith("kv.") and not f.endswith(".tmp")
            and f != gen1_kv
        ]
        assert newer, "kill point fired before the torn window"

        head = _spawn_head(session_dir, port, authkey)
        out = _run_driver(
            """
import sys
import ray_tpu
from ray_tpu._private.worker import global_client
ray_tpu.init(address=sys.argv[1])
c = global_client()
assert c.kv_get(b"marker_a") == b"1", "complete generation lost"
print("RESTORED", c.kv_get(b"marker_b"))
""",
            address,
        )
        # marker_a (last complete cut) MUST be there; marker_b belongs
        # to the torn tick and must read as cleanly absent, not corrupt.
        assert "RESTORED None" in out
    finally:
        try:
            head.kill()
        except Exception:
            pass


def test_segmented_persistence_rewrites_only_dirty_tables(tmp_path):
    """A KV put must not re-serialize the actor/object tables
    (reference: the Redis store writes per key; the old single-pickle
    snapshot was O(cluster state) per write-batch)."""
    import ray_tpu
    from ray_tpu._private.worker import _global, global_client

    ray_tpu.init(num_cpus=2, _temp_dir=str(tmp_path))
    try:
        @ray_tpu.remote
        class Keep:
            def ping(self):
                return "ok"

        a = Keep.options(name="seg_actor").remote()
        assert ray_tpu.get(a.ping.remote()) == "ok"
        ref = ray_tpu.put(b"x" * 64)  # inline object -> objects table
        state_dir = os.path.join(_global.node.session_dir, "gcs_state.d")

        def tables_present():
            if not os.path.isdir(state_dir):
                return set()
            return {f.split(".")[0] for f in os.listdir(state_dir)}

        deadline = time.time() + 10
        while time.time() < deadline and not (
            {"actors", "objects", "manifest"} <= tables_present()
        ):
            time.sleep(0.1)
        def mtimes():
            return {
                f: os.path.getmtime(os.path.join(state_dir, f))
                for f in os.listdir(state_dir)
            }

        # Quiesce: async task_done batches from the warm-up calls dirty
        # the actors table a beat later — baseline only once the files
        # have been stable for a full second.
        before = mtimes()
        deadline = time.time() + 20
        while time.time() < deadline:
            time.sleep(1.0)
            now = mtimes()
            if now == before:
                break
            before = now
        for i in range(5):
            global_client().kv_put(f"seg{i}".encode(), b"v")
        def newest(table):
            files = [
                f for f in os.listdir(state_dir)
                if f.startswith(table + ".") and not f.endswith(".tmp")
            ]
            return max(files, default=None)

        before_files = {
            t: newest(t) for t in ("kv", "actors", "objects",
                                   "named_actors")
        }
        deadline = time.time() + 10
        while time.time() < deadline:
            if newest("kv") != before_files["kv"]:
                break
            time.sleep(0.1)
        assert newest("kv") != before_files["kv"], "kv never persisted"
        for t in ("actors", "objects", "named_actors"):
            if before_files[t] is not None:
                assert newest(t) == before_files[t], (
                    f"{t} table rewritten by a pure KV put"
                )
        del ref
    finally:
        ray_tpu.shutdown()
