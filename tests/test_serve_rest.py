"""Serve REST config API + dashboard task/actor drill-down.

Reference behavior being matched: dashboard/modules/serve (PUT/GET/
DELETE of declarative application configs over HTTP) and the
dashboard's task/actor drill-down views.
"""
import json
import sys
import textwrap
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def app_dir(tmp_path_factory):
    return tmp_path_factory.mktemp("serve_rest_apps")


@pytest.fixture(scope="module")
def dash(app_dir):
    import os

    # The REST deploy imports the application INSIDE the dashboard
    # actor process; PYTHONPATH set before init propagates to spawned
    # workers (a real user ships code via runtime_env py_modules).
    old = os.environ.get("PYTHONPATH")
    os.environ["PYTHONPATH"] = (
        f"{app_dir}:{old}" if old else str(app_dir)
    )
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    from ray_tpu.dashboard import start_dashboard

    url = start_dashboard(port=18280)
    yield url
    if old is None:
        os.environ.pop("PYTHONPATH", None)
    else:
        os.environ["PYTHONPATH"] = old
    try:
        from ray_tpu import serve

        serve.shutdown()
    except Exception:  # noqa: BLE001
        pass
    ray_tpu.shutdown()


def _req(url, method="GET", body=None, timeout=60):
    req = urllib.request.Request(url, method=method)
    data = None
    if body is not None:
        data = json.dumps(body).encode()
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, data=data, timeout=timeout) as r:
        raw = r.read()
        return r.status, json.loads(raw) if raw else None


def test_serve_rest_deploy_get_delete(dash, app_dir):
    mod = app_dir / "rest_app_mod.py"
    mod.write_text(
        textwrap.dedent(
            """
            from ray_tpu import serve

            @serve.deployment
            class Upper:
                def __call__(self, x):
                    return str(x).upper()

            app = Upper.bind()
            """
        )
    )
    sys.path.insert(0, str(app_dir))
    try:
        # PUT the declarative config: deploys over HTTP, no CLI.
        status, apps = _req(
            f"{dash}/api/serve/applications/",
            method="PUT",
            body={
                "applications": [
                    {
                        "name": "rest_app",
                        "route_prefix": None,
                        "import_path": "rest_app_mod:app",
                        "deployments": [
                            {"name": "Upper", "num_replicas": 2}
                        ],
                    }
                ]
            },
            timeout=120,
        )
        assert status == 200
        assert apps["rest_app"]["status"] == "RUNNING"
        assert apps["rest_app"]["deployments"]["Upper"]["num_replicas"] == 2

        # The app actually serves.
        from ray_tpu import serve

        handle = serve.get_app_handle("rest_app")
        assert handle.remote("hi").result(timeout_s=30) == "HI"

        # GET reflects live status; the dashboard shows it without CLI.
        status, apps = _req(f"{dash}/api/serve/applications/")
        assert status == 200 and "rest_app" in apps

        # Bad config -> 400 with an error, not a 500.
        try:
            _req(
                f"{dash}/api/serve/applications/",
                method="PUT",
                body={"applications": [{"name": "x"}]},
            )
            raised = False
        except urllib.error.HTTPError as e:
            raised = True
            assert e.code == 400
        assert raised

        # DELETE tears everything down.
        req = urllib.request.Request(
            f"{dash}/api/serve/applications/", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 204
        status, apps = _req(f"{dash}/api/serve/applications/")
        assert apps == {}
    finally:
        sys.path.remove(str(app_dir))


def test_task_and_actor_drilldown(dash):
    @ray_tpu.remote
    class Worker:
        def work(self, n):
            return n * 2

    w = Worker.remote()
    assert ray_tpu.get(w.work.remote(21)) == 42

    # Find the actor id via the state API the dashboard uses.
    from ray_tpu.util.state import list_actors

    actors = [a for a in list_actors(limit=1000) if a.get("state") == "ALIVE"]
    assert actors
    aid = actors[-1]["actor_id"]
    status, detail = _req(f"{dash}/api/actor/{aid}")
    assert status == 200
    assert detail["actor"]["actor_id"] == aid

    from ray_tpu.util.state import list_tasks

    tasks = list_tasks(limit=1000)
    assert tasks
    tid = tasks[-1]["task_id"]
    status, detail = _req(f"{dash}/api/task/{tid}")
    assert status == 200
    assert detail["task"]["task_id"] == tid

    # Unknown ids 404.
    with pytest.raises(urllib.error.HTTPError):
        _req(f"{dash}/api/actor/ffffffffffff")


def test_per_node_timeseries(dash):
    deadline = time.time() + 30
    while time.time() < deadline:
        with urllib.request.urlopen(
            f"{dash}/api/metrics_timeseries", timeout=10
        ) as r:
            series = json.loads(r.read())["series"]
        if any(name.startswith("CPU used @") for name in series):
            return
        time.sleep(1)
    pytest.fail(f"no per-node series in {sorted(series)}")


def test_serve_put_malformed_body_is_400(dash):
    req = urllib.request.Request(
        f"{dash}/api/serve/applications/",
        method="PUT",
        data=b"not json at all",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 400
