import os
import tempfile

import pytest

import ray_tpu


def pytest_configure(config):
    # With the witness armed, point every process — this one and the
    # spawned heads/raylets/workers, via env inheritance — at ONE
    # sidecar violations file. sessionfinish scans it, so an inversion
    # witnessed inside a daemon fails the run too; violations() alone
    # only ever sees the driver process.
    from ray_tpu._private import lock_witness

    if lock_witness.enabled() and not os.environ.get(
        lock_witness.FILE_ENV
    ):
        path = os.path.join(
            tempfile.gettempdir(),
            f"rtpu_lock_witness_{os.getpid()}.log",
        )
        try:
            os.unlink(path)
        except OSError:
            pass
        os.environ[lock_witness.FILE_ENV] = path


def pytest_sessionfinish(session, exitstatus):
    """With the lock witness armed (make race-smoke), a suite that ran
    green but witnessed a lock-order inversion still FAILS — the
    violation is a deadlock waiting for production traffic to align."""
    from ray_tpu._private import lock_witness

    if lock_witness.installed():
        vs = lock_witness.violations()
        rep = lock_witness.witness_report()
        print(f"\n[lock-witness] {rep}")
        side = os.environ.get(lock_witness.FILE_ENV)
        side_text = ""
        if side and os.path.exists(side):
            with open(side, encoding="utf-8") as f:
                side_text = f.read().strip()
            try:
                os.unlink(side)  # consumed: don't leak one per run
            except OSError:
                pass
        if vs or side_text:
            if side_text:
                # The sidecar already holds this process's findings
                # (pid-tagged) alongside any daemon's — printing the
                # in-memory list too would show each driver inversion
                # twice.
                print(
                    "[lock-witness] sidecar findings (all processes, "
                    "incl. spawned daemons):"
                )
                print(side_text)
            else:
                for v in vs:
                    print(v.render())
            session.exitstatus = 3


@pytest.fixture
def ray_start():
    """Fresh local cluster per test (reference: conftest ray_start_regular)."""
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
