import pytest

import ray_tpu


@pytest.fixture
def ray_start():
    """Fresh local cluster per test (reference: conftest ray_start_regular)."""
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
