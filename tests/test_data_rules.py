"""Optimizer-rule framework + lance/mongo datasources.

Reference strategy: data/tests/test_operator_fusion.py and
test_optimizer.py assert on the *rewritten logical plan*, not just
results — each rule gets plan-level unit tests here, then the sources
get end-to-end reads against local fixtures.
"""
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data._plan import FusedMap, Limit, LogicalPlan, MapLike, Read
from ray_tpu.data._rules import (
    ColumnPruningPushdown,
    LimitPushdown,
    OperatorFusion,
    apply_rules,
)
from ray_tpu.data.datasource import (
    LanceDatasource,
    MongoDatasource,
    ParquetDatasource,
    write_lance_dataset,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


# ------------------------------------------------------------ rule units


def test_operator_fusion_merges_map_runs():
    ops = [
        MapLike("map_rows", {"fn": lambda r: r}),
        MapLike("filter", {"fn": lambda r: True}),
        MapLike("map_batches", {"fn": lambda b: b}),
    ]
    out = OperatorFusion().apply(ops)
    assert len(out) == 1
    assert isinstance(out[0], FusedMap)
    assert [k for k, _ in out[0].transforms] == [
        "map_rows", "filter", "map_batches",
    ]
    assert out[0].name == "map_rows+filter+map_batches"


def test_limit_pushdown_crosses_row_preserving_only():
    row = MapLike("map_rows", {"fn": lambda r: r})
    flt = MapLike("filter", {"fn": lambda r: True})
    out = LimitPushdown().apply([flt, row, Limit(5)])
    # crosses map_rows, stops at filter (cardinality-changing)
    assert [type(o).__name__ if not isinstance(o, MapLike) else o.kind
            for o in out] == ["filter", "Limit", "map_rows"]


def test_column_pruning_pushes_into_parquet(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": [1, 2], "b": [3, 4], "c": [5, 6]}), path)
    ds = rd.read_parquet(path).select_columns(["a", "b"])
    out = apply_rules(list(ds._plan.ops), [ColumnPruningPushdown()])
    # the select op is gone; the (copied) source carries the projection
    assert len(out) == 1
    assert isinstance(out[0], Read)
    assert out[0].datasource._columns == ["a", "b"]
    # the original plan's shared datasource was NOT mutated
    orig = ds._plan.ops[0].datasource
    assert orig._columns is None


def test_column_pruning_never_widens(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": [1], "b": [2]}), path)
    src = ParquetDatasource(path, columns=["a"])
    assert not src.prune_columns(["a", "b"])  # widening refused
    assert src.prune_columns(["a"])


def test_pruning_skipped_behind_filter(tmp_path):
    # a filter between read and select may touch any column: no pushdown
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": [1, 2], "b": [3, 4]}), path)
    ds = (
        rd.read_parquet(path)
        .filter(lambda r: r["b"] > 0)
        .select_columns(["a"])
    )
    out = apply_rules(list(ds._plan.ops), [ColumnPruningPushdown()])
    assert len(out) == 3  # unchanged
    assert out[0].datasource._columns is None


def test_select_columns_end_to_end(cluster, tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table({"a": [1, 2, 3], "b": [4.0, 5.0, 6.0], "c": ["x", "y", "z"]}),
        path,
    )
    rows = rd.read_parquet(path).select_columns(["a", "c"]).take_all()
    assert rows == [
        {"a": 1, "c": "x"}, {"a": 2, "c": "y"}, {"a": 3, "c": "z"},
    ]


# ----------------------------------------------------------------- lance


def test_lance_roundtrip_and_projection(cluster, tmp_path):
    uri = str(tmp_path / "ds.lance")
    v1 = write_lance_dataset(
        uri,
        {"id": list(range(10)), "text": [f"row{i}" for i in range(10)]},
        max_rows_per_fragment=4,
    )
    assert v1 == 1
    ds = rd.read_lance(uri)
    assert ds.count() == 10
    assert sorted(r["id"] for r in ds.take_all()) == list(range(10))
    # fragment-parallel: 10 rows at 4/fragment = 3 fragments
    assert len(LanceDatasource(uri).get_read_tasks(8)) == 3

    # column projection reads only the id files
    only_ids = rd.read_lance(uri, columns=["id"]).take_all()
    assert all(set(r) == {"id"} for r in only_ids)

    # select_columns pushes down into the scan
    ds2 = rd.read_lance(uri).select_columns(["text"])
    out = apply_rules(list(ds2._plan.ops), [ColumnPruningPushdown()])
    assert len(out) == 1
    assert out[0].datasource._columns == ["text"]


def test_lance_append_and_time_travel(cluster, tmp_path):
    uri = str(tmp_path / "ds.lance")
    write_lance_dataset(uri, {"id": [1, 2], "text": ["a", "b"]})
    v2 = write_lance_dataset(uri, {"id": [3], "text": ["c"]})
    assert v2 == 2
    assert rd.read_lance(uri).count() == 3
    assert rd.read_lance(uri, version=1).count() == 2
    with pytest.raises(ValueError):
        write_lance_dataset(uri, {"other": [1]})  # schema mismatch
    with pytest.raises(ValueError):
        # same names, changed type: also refused (old fragments would
        # silently disagree with the new manifest)
        write_lance_dataset(uri, {"id": ["x"], "text": ["c"]})
    with pytest.raises(ValueError):
        rd.read_lance(uri, version=9)
    with pytest.raises(ValueError):
        rd.read_lance(uri, columns=["nope"])


# ----------------------------------------------------------------- mongo


class _FakeCursor:
    def __init__(self, docs):
        self._docs = docs

    def sort(self, key):
        return _FakeCursor(sorted(self._docs, key=lambda d: d[key]))

    def skip(self, n):
        return _FakeCursor(self._docs[n:])

    def limit(self, n):
        return _FakeCursor(self._docs[:n])

    def __iter__(self):
        return iter(self._docs)


class _FakeCollection:
    """The pymongo Collection surface MongoDatasource drives: equality
    and $gte/$lt range filters plus include/exclude projections."""

    def __init__(self, docs):
        self._docs = docs

    @staticmethod
    def _match(doc, flt):
        for k, cond in flt.items():
            if isinstance(cond, dict):
                if "$gte" in cond and not doc[k] >= cond["$gte"]:
                    return False
                if "$lt" in cond and not doc[k] < cond["$lt"]:
                    return False
            elif doc.get(k) != cond:
                return False
        return True

    def count_documents(self, flt):
        return sum(1 for d in self._docs if self._match(d, flt))

    def find(self, flt, projection=None):
        docs = [d for d in self._docs if self._match(d, flt)]
        if projection:
            include = {k for k, v in projection.items() if v}
            exclude = {k for k, v in projection.items() if not v}
            docs = [
                {k: v for k, v in d.items()
                 if (not include or k in include) and k not in exclude}
                # _id rides along unless excluded, as in mongo
                | ({"_id": d["_id"]}
                   if "_id" not in exclude and include else {})
                for d in docs
            ]
        return _FakeCursor(docs)


def _make_coll(n=20):
    return _FakeCollection([
        {"_id": i, "x": i * i, "tag": "even" if i % 2 == 0 else "odd"}
        for i in range(n)
    ])


def test_mongo_partitioned_read(cluster):
    coll = _make_coll()
    ds = rd.read_mongo(lambda: coll, parallelism=4)
    # 4 disjoint _id ranges cover the collection exactly once
    assert len(MongoDatasource(lambda: coll).get_read_tasks(4)) == 4
    rows = ds.take_all()
    assert sorted(r["_id"] for r in rows) == list(range(20))
    assert all(r["x"] == r["_id"] ** 2 for r in rows)


def test_mongo_filter_and_projection(cluster):
    coll = _make_coll()
    rows = rd.read_mongo(
        lambda: coll, filter={"tag": "even"}, projection=["x"],
        parallelism=2,
    ).take_all()
    assert len(rows) == 10
    assert all(set(r) == {"x"} for r in rows)

    # select_columns pushes its projection into the cursor
    ds = rd.read_mongo(lambda: coll).select_columns(["tag"])
    out = apply_rules(list(ds._plan.ops), [ColumnPruningPushdown()])
    assert len(out) == 1
    assert out[0].datasource._projection == ["tag"]
    rows = ds.take_all()
    assert all(set(r) == {"tag"} for r in rows)
