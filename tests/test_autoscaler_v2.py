"""Autoscaler v2: instance-manager lifecycle + reconciler over real
node-daemon processes.

Reference: python/ray/autoscaler/v2/tests — state-machine unit tests +
an end-to-end loop: demand appears -> instance launched -> daemon
registers -> task runs -> idle -> drain -> terminate.
"""
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler.v2 import (
    ALLOCATED,
    ALLOCATION_FAILED,
    QUEUED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATED,
    CloudProvider,
    Instance,
    InstanceManager,
    ProcessCloudProvider,
    Reconciler,
)


# ------------------------------------------------------- state machine
def test_instance_lifecycle_transitions():
    im = InstanceManager()
    inst = im.create("cpu", {"CPU": 2.0})
    assert inst.status == QUEUED
    im.transition(inst, REQUESTED)
    im.transition(inst, ALLOCATED)
    im.transition(inst, RAY_RUNNING)
    assert inst.history == [QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING]
    with pytest.raises(ValueError):
        im.transition(inst, ALLOCATED)  # backwards


def test_allocation_failure_retries_then_terminates():
    im = InstanceManager()
    inst = im.create("cpu", {"CPU": 1.0})
    im.transition(inst, REQUESTED)
    im.transition(inst, ALLOCATION_FAILED)
    im.transition(inst, QUEUED)  # retry path
    im.transition(inst, REQUESTED)
    im.transition(inst, ALLOCATION_FAILED)
    im.transition(inst, TERMINATED)  # give up
    assert inst.status == TERMINATED


class _FlakyProvider(CloudProvider):
    """Fails the first launch; succeeds after."""

    def __init__(self):
        self.calls = 0
        self._live = {}

    def launch(self, instance: Instance) -> str:
        self.calls += 1
        if self.calls == 1:
            raise RuntimeError("quota")
        cid = f"cloud-{self.calls}"
        self._live[cid] = {}
        return cid

    def terminate(self, cloud_instance_id: str) -> None:
        self._live.pop(cloud_instance_id, None)

    def running_instances(self):
        return dict(self._live)


def test_reconciler_retries_failed_launches(monkeypatch):
    ray_tpu.init(num_cpus=1, ignore_reinit_error=True)
    try:
        provider = _FlakyProvider()
        rec = Reconciler(
            {"cpu": {"resources": {"CPU": 2.0}, "max_workers": 1}},
            provider,
        )
        ref = _need_two_cpus.remote()
        deadline = time.time() + 20
        while time.time() < deadline and not rec.im.instances(REQUESTED):
            rec.step()
            time.sleep(0.2)
        # First launch failed, retry succeeded; exactly one live record.
        assert provider.calls >= 2
        assert len(rec.im.instances(REQUESTED, ALLOCATED)) == 1
        del ref
    finally:
        ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=2)
def _need_two_cpus():
    time.sleep(1.0)
    return "ran"


def test_v2_end_to_end_scale_up_run_scale_down():
    """Full loop against a REAL daemon subprocess: unplaceable task ->
    launch -> daemon joins over TCP -> task completes -> idle ->
    drained -> process terminated."""
    ray_tpu.init(num_cpus=1, tcp_port=0, ignore_reinit_error=True)
    try:
        from ray_tpu._private.worker import _global

        provider = ProcessCloudProvider(
            _global.node.tcp_address, _global.node.authkey
        )
        rec = Reconciler(
            {"cpu": {"resources": {"CPU": 2.0}, "max_workers": 2}},
            provider,
            idle_timeout_s=1.0,
            drain_deadline_s=15.0,
        )
        ref = _need_two_cpus.remote()
        deadline = time.time() + 60
        while time.time() < deadline and not rec.im.instances(RAY_RUNNING):
            rec.step()
            time.sleep(0.3)
        assert rec.im.instances(RAY_RUNNING), rec.summary()
        assert ray_tpu.get(ref, timeout=60) == "ran"
        # Idle -> drain -> EVERY instance terminated, processes reaped.
        deadline = time.time() + 90
        while time.time() < deadline and (
            provider.running_instances()
            or not rec.im.instances(TERMINATED)
        ):
            rec.step()
            time.sleep(0.3)
        assert rec.im.instances(TERMINATED), rec.summary()
        assert provider.running_instances() == {}
    finally:
        ray_tpu.shutdown()
