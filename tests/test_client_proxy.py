"""Ray Client equivalent (`ray_tpu://`): thin remote drivers.

Reference behavior being matched: python/ray/util/client — a driver
connected only via TCP runs tasks/actors/streaming with everything it
creates owned server-side, and a disconnect cleans up its actors and
objects (proxier.py per-client servers).
"""
import subprocess
import sys
import textwrap
import time

import pytest

import ray_tpu


@pytest.fixture()
def proxy_cluster():
    ray_tpu.init(num_cpus=2, client_server_port=0)
    try:
        yield ray_tpu.client_server_address()
    finally:
        ray_tpu.shutdown()


def _run_client(address: str, body: str, timeout: float = 120) -> str:
    """Run a driver script in a subprocess whose ONLY route to the
    cluster is the ray_tpu:// TCP address."""
    script = textwrap.dedent(
        f"""
        import ray_tpu
        ray_tpu.init(address={address!r})
        """
    ) + textwrap.dedent(body)
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert out.returncode == 0, f"client failed:\n{out.stdout}\n{out.stderr}"
    return out.stdout


def test_tasks_puts_roundtrip(proxy_cluster):
    out = _run_client(
        proxy_cluster,
        """
        import numpy as np

        @ray_tpu.remote
        def double(x):
            return x * 2

        assert ray_tpu.get(double.remote(21)) == 42

        # Dependency chain through proxy-owned refs.
        r1 = double.remote(10)
        r2 = double.remote(r1)
        assert ray_tpu.get(r2) == 40

        # Large array: packed bytes across the proxy, both directions.
        arr = np.arange(300_000, dtype=np.int64)
        ref = ray_tpu.put(arr)
        back = ray_tpu.get(ref)
        assert (back == arr).all()

        # __main__-defined class: the session must never unpickle it.
        class Point:
            def __init__(self, x):
                self.x = x

        pref = ray_tpu.put(Point(7))
        assert ray_tpu.get(pref).x == 7

        ready, pending = ray_tpu.wait([r1, r2], num_returns=2, timeout=10)
        assert len(ready) == 2 and not pending
        print("TASKS-OK")
        """,
    )
    assert "TASKS-OK" in out


def test_actors_and_streaming(proxy_cluster):
    out = _run_client(
        proxy_cluster,
        """
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def add(self, k):
                self.n += k
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.add.remote(2)) == 2
        assert ray_tpu.get(c.add.remote(3)) == 5

        @ray_tpu.remote
        def gen(n):
            for i in range(n):
                yield i * i

        got = [ray_tpu.get(r) for r in
               gen.options(num_returns="streaming").remote(4)]
        assert got == [0, 1, 4, 9], got
        print("ACTORS-OK")
        """,
    )
    assert "ACTORS-OK" in out


def test_named_actor_and_kv(proxy_cluster):
    _run_client(
        proxy_cluster,
        """
        @ray_tpu.remote
        class Holder:
            def ping(self):
                return "pong"

        h = Holder.options(name="proxy_named", lifetime="detached").remote()
        assert ray_tpu.get(h.ping.remote()) == "pong"
        """,
    )
    # Detached actor survives the client session; visible to the local
    # driver and to a second remote client.
    h = ray_tpu.get_actor("proxy_named")
    assert ray_tpu.get(h.ping.remote()) == "pong"
    _run_client(
        proxy_cluster,
        """
        h = ray_tpu.get_actor("proxy_named")
        assert ray_tpu.get(h.ping.remote()) == "pong"
        """,
    )
    ray_tpu.kill(h)


def test_disconnect_cleans_up(proxy_cluster):
    # The client creates a named (but NON-detached) actor then exits
    # without shutdown; the session must kill it.
    _run_client(
        proxy_cluster,
        """
        import os

        @ray_tpu.remote
        class Leaky:
            def pid(self):
                return os.getpid()

        a = Leaky.options(name="proxy_leaky").remote()
        assert ray_tpu.get(a.pid.remote()) > 0
        os._exit(0)  # hard exit: no client-side cleanup at all
        """,
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            h = ray_tpu.get_actor("proxy_leaky")
            ray_tpu.get(h.pid.remote(), timeout=5)
        except Exception:
            break  # dead or gone — cleaned up
        time.sleep(0.5)
    else:
        pytest.fail("non-detached actor survived client disconnect")
