"""RLlib breadth: SAC, APPO, offline RL (BC/MARWIL), multi-agent.

Models the reference's algorithm test strategy: learning tests with
reward thresholds (rllib/tuned_examples/sac/pendulum_sac.py,
appo/cartpole_appo.py, bc/cartpole_bc.py) and multi-agent CartPole
(tuned_examples/ppo/multi_agent_cartpole_ppo.py).
"""
import os
import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


# ------------------------------------------------------------------- SAC
def test_sac_module_sample_action_logp():
    """Squashed-Gaussian logp matches a numeric change-of-variables
    check and actions respect the env bounds."""
    import gymnasium as gym
    import jax

    from ray_tpu.rllib.algorithms.sac import SACModule

    env = gym.make("Pendulum-v1")
    mod = SACModule(
        env.observation_space, env.action_space, {"fcnet_hiddens": (8,)}
    )
    params = mod.init_params(jax.random.PRNGKey(0))
    obs = np.random.default_rng(0).standard_normal((16, 3)).astype(np.float32)
    a, logp = mod.sample_action(params, obs, jax.random.PRNGKey(1))
    a, logp = np.asarray(a), np.asarray(logp)
    assert a.shape == (16, 1) and logp.shape == (16,)
    assert (a >= env.action_space.low - 1e-5).all()
    assert (a <= env.action_space.high + 1e-5).all()
    assert np.isfinite(logp).all()


def test_sac_pendulum_learns(cluster):
    from ray_tpu.rllib.algorithms.sac import SACConfig

    algo = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4)
        .training(
            train_batch_size=256,
            num_steps_sampled_before_learning_starts=1500,
            sample_timesteps_per_iteration=1500,
            updates_per_iteration=350,
            lr=1e-3,
        )
        .debugging(seed=0)
        .build()
    )
    # Random policy on Pendulum averages about -1200; a learning SAC
    # clears -900 within a few thousand env steps.
    best = -1e9
    for _ in range(12):
        r = algo.train()
        if np.isfinite(r["episode_return_mean"]):
            best = max(best, r["episode_return_mean"])
        if best > -900.0:
            break
    algo.stop()
    assert best > -900.0, f"SAC failed to learn Pendulum: best={best}"


# ------------------------------------------------------------------ APPO
def test_appo_loss_clips_ratio():
    """The clipped surrogate must bound the policy update for ratios
    outside [1-clip, 1+clip] (vs IMPALA's unclipped PG)."""
    import gymnasium as gym
    import jax

    from ray_tpu.rllib.algorithms.appo import APPOConfig, APPOLearner
    from ray_tpu.rllib.core.rl_module import DiscretePolicyModule

    cfg = APPOConfig().environment("CartPole-v1")
    spec = cfg.module_spec(
        gym.spaces.Box(-1, 1, (4,), np.float32), gym.spaces.Discrete(2)
    )
    learner = APPOLearner(module_spec=spec, config=cfg.learner_config())
    learner.build()
    T = cfg.rollout_fragment_length
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.standard_normal((8, T, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, (8, T)).astype(np.int64),
        "rewards": np.ones((8, T), np.float32),
        "terminateds": np.zeros((8, T), np.float32),
        # Behavior policy wildly off → big ratios → clip engages.
        "action_logp": np.full((8, T), -8.0, np.float32),
        "bootstrap_obs": rng.standard_normal((8, 4)).astype(np.float32),
        "mask": np.ones((8, T), np.float32),
    }
    loss, metrics = learner.compute_loss(
        learner.params, {k: np.asarray(v) for k, v in batch.items()},
        jax.random.PRNGKey(0),
    )
    assert np.isfinite(float(loss))
    assert float(metrics["mean_rho"]) > 1.0  # off-policy regime


def test_appo_cartpole_learns(cluster):
    from ray_tpu.rllib.algorithms.appo import APPOConfig

    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=50)
        .training(train_batch_size=500, lr=5e-4, use_kl_loss=True)
        .debugging(seed=0)
        .build()
    )
    # Same learning envelope as the IMPALA pipeline test (the shared
    # async machinery): 150 iterations, best-of threshold.
    best = 0.0
    for _ in range(150):
        r = algo.train()
        if "episode_return_mean" in r and np.isfinite(
            r["episode_return_mean"]
        ):
            best = max(best, r["episode_return_mean"])
        if best >= 50.0:
            break
    algo.stop()
    assert best >= 50.0, f"APPO failed to learn CartPole: best={best}"


# --------------------------------------------------------------- offline
def _scripted_cartpole_episodes(n_episodes: int, seed: int = 0):
    """Expert-ish scripted policy: push toward the pole's fall
    direction (reaches ~150-200 return)."""
    import gymnasium as gym

    from ray_tpu.rllib.env.episode import SingleAgentEpisode

    env = gym.make("CartPole-v1")
    eps = []
    rng = np.random.default_rng(seed)
    for i in range(n_episodes):
        obs, _ = env.reset(seed=int(rng.integers(0, 2**31)))
        ep = SingleAgentEpisode(initial_observation=obs)
        while True:
            action = int(obs[2] + 0.5 * obs[3] > 0)
            obs, r, term, trunc, _ = env.step(action)
            ep.add_env_step(obs, action, r, terminated=term, truncated=trunc)
            if term or trunc:
                break
        eps.append(ep.finalize())
    env.close()
    return eps


def test_offline_roundtrip(tmp_path):
    from ray_tpu.rllib.offline import SampleReader, SampleWriter

    eps = _scripted_cartpole_episodes(3)
    w = SampleWriter(str(tmp_path / "samples"))
    w.write(eps)
    w.close()
    back = SampleReader(str(tmp_path / "samples"), shuffle=False).read_all()
    assert len(back) == 3
    for a, b in zip(eps, back):
        assert len(a) == len(b)
        np.testing.assert_allclose(
            np.asarray(a.observations), np.asarray(b.observations), rtol=1e-6
        )
        np.testing.assert_array_equal(a.actions, b.actions)
        assert a.is_terminated == b.is_terminated


def test_offline_data_rides_data_library(cluster, tmp_path):
    from ray_tpu.rllib.offline import OfflineData, SampleWriter

    eps = _scripted_cartpole_episodes(5)
    w = SampleWriter(str(tmp_path / "samples"))
    w.write(eps)
    w.close()
    data = OfflineData(str(tmp_path / "samples"))
    batches = list(data.iter_episode_batches(batch_size=100))
    total = sum(len(ep) for b in batches for ep in b)
    assert total == sum(len(e) for e in eps)


def test_bc_learns_from_expert_data(cluster, tmp_path):
    from ray_tpu.rllib.algorithms.marwil import BCConfig
    from ray_tpu.rllib.offline import SampleWriter

    w = SampleWriter(str(tmp_path / "expert"))
    w.write(_scripted_cartpole_episodes(40, seed=1))
    w.close()
    algo = (
        BCConfig()
        .environment("CartPole-v1")
        .offline_data(input_=str(tmp_path / "expert"))
        .training(train_batch_size=2000, lr=1e-3, minibatch_size=128,
                  num_epochs=5)
        .debugging(seed=0)
        .build()
    )
    for _ in range(30):
        algo.train()
    ev = algo.evaluate(num_episodes=10)
    algo.stop()
    # Random CartPole is ~20; the scripted expert is ~150+. Cloning
    # should comfortably clear 80.
    assert ev["episode_return_mean"] >= 80.0, f"BC failed: {ev}"


def test_marwil_learns_from_mixed_data(cluster, tmp_path):
    """MARWIL's advantage weighting upweights the good trajectories in
    a mixed expert+random dataset."""
    import gymnasium as gym

    from ray_tpu.rllib.algorithms.marwil import MARWILConfig
    from ray_tpu.rllib.env.episode import SingleAgentEpisode
    from ray_tpu.rllib.offline import SampleWriter

    # Random-policy episodes (bad data).
    env = gym.make("CartPole-v1")
    rng = np.random.default_rng(7)
    bad = []
    for _ in range(40):
        obs, _ = env.reset(seed=int(rng.integers(0, 2**31)))
        ep = SingleAgentEpisode(initial_observation=obs)
        while True:
            a = int(rng.integers(0, 2))
            obs, r, term, trunc, _ = env.step(a)
            ep.add_env_step(obs, a, r, terminated=term, truncated=trunc)
            if term or trunc:
                break
        bad.append(ep.finalize())
    env.close()
    w = SampleWriter(str(tmp_path / "mixed"))
    w.write(_scripted_cartpole_episodes(20, seed=2))
    w.write(bad)
    w.close()
    algo = (
        MARWILConfig()
        .environment("CartPole-v1")
        .offline_data(input_=str(tmp_path / "mixed"))
        .training(train_batch_size=2000, lr=1e-3, beta=1.0)
        .debugging(seed=0)
        .build()
    )
    for _ in range(30):
        algo.train()
    ev = algo.evaluate(num_episodes=10)
    algo.stop()
    assert ev["episode_return_mean"] >= 60.0, f"MARWIL failed: {ev}"


# ------------------------------------------------------------ multi-agent
def test_multi_agent_env_wrapper():
    from ray_tpu.rllib import make_multi_agent

    env = make_multi_agent("CartPole-v1", num_agents=3)({})
    assert len(env.possible_agents) == 3
    obs, _ = env.reset(seed=0)
    assert set(obs) == set(env.possible_agents)
    actions = {aid: 0 for aid in obs}
    obs, rew, term, trunc, _ = env.step(actions)
    assert set(rew) == set(env.possible_agents)
    assert "__all__" in term
    env.close()


def _map_agent_to_policy(agent_id: str) -> str:
    return {"agent_0": "p0", "agent_1": "p1"}[agent_id]


def test_multi_agent_ppo_two_policies_learn(cluster):
    from ray_tpu.rllib import make_multi_agent
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment(make_multi_agent("CartPole-v1", num_agents=2))
        .multi_agent(
            policies={"p0": None, "p1": None},
            policy_mapping_fn=_map_agent_to_policy,
        )
        .env_runners(num_env_runners=0)
        .training(train_batch_size=2000, minibatch_size=128, num_epochs=8,
                  lr=5e-4)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    last_modules = {}
    for _ in range(25):
        r = algo.train()
        last_modules = r["env_runners"].get(
            "module_episode_return_mean", last_modules
        )
        if np.isfinite(r["episode_return_mean"]):
            best = max(best, r["episode_return_mean"])
        if best >= 60.0 and len(last_modules) == 2:
            break
    algo.stop()
    assert best >= 60.0, f"multi-agent PPO failed: best={best}"
    assert set(last_modules) == {"p0", "p1"}, last_modules


def test_multi_agent_shared_policy(cluster):
    from ray_tpu.rllib import make_multi_agent
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment(make_multi_agent("CartPole-v1", num_agents=2))
        .multi_agent(policies={"shared": None})
        .env_runners(num_env_runners=0)
        .training(train_batch_size=1000, minibatch_size=128, num_epochs=6)
        .debugging(seed=0)
        .build()
    )
    r = {}
    for _ in range(5):
        r = algo.train()
    algo.stop()
    assert any(k.startswith("shared/") for k in r["learners"]), r


# ------------------------------------------------------------------- CQL

class _PointMassEnv:
    """Stable 2-D point mass: x' = clip(x + 0.2 a), r = -|x|^2.

    Duck-typed gymnasium env (metadata/render_mode/spec for the vector
    wrapper).

    Closed-loop STABLE under an approximate controller, so offline
    learning is testable without Pendulum's compounding covariate
    shift (pure BC there needs D4RL-scale data; the reference's CQL
    learning bars live in tuned_examples on D4RL for the same
    reason)."""

    metadata = {"render_modes": []}
    render_mode = None
    spec = None

    def __init__(self, *args, **kwargs):
        import gymnasium as gym

        self.observation_space = gym.spaces.Box(-2.0, 2.0, (2,), np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
        self._x = None
        self._t = 0
        self._rng = np.random.default_rng(0)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._x = self._rng.uniform(-1.5, 1.5, 2).astype(np.float32)
        self._t = 0
        return self._x.copy(), {}

    def step(self, action):
        a = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        self._x = np.clip(self._x + 0.2 * a, -2.0, 2.0)
        self._t += 1
        r = -float(np.sum(self._x ** 2))
        return self._x.copy(), r, False, self._t >= 50, {}

    def close(self):
        pass


def _pointmass_episodes(n_episodes: int, seed: int = 0, noise: float = 0.3):
    """Behavior: proportional pull to the origin + exploration noise."""
    from ray_tpu.rllib.env.episode import SingleAgentEpisode

    env = _PointMassEnv()
    rng = np.random.default_rng(seed)
    eps = []
    for i in range(n_episodes):
        obs, _ = env.reset(seed=int(rng.integers(0, 2**31)))
        ep = SingleAgentEpisode(initial_observation=obs)
        while True:
            a = np.clip(
                -1.5 * obs + noise * rng.standard_normal(2), -1.0, 1.0
            ).astype(np.float32)
            obs, r, term, trunc, _ = env.step(a)
            ep.add_env_step(obs, a, r, terminated=term, truncated=trunc)
            if term or trunc:
                break
        eps.append(ep.finalize())
    return eps


def test_cql_learns_pointmass_offline(cluster, tmp_path):
    """CQL trains PURELY from a recorded dataset (zero env interaction
    during training); its evaluated policy must crush the random
    baseline and approach the behavior policy."""
    from ray_tpu.rllib.algorithms.cql import CQLConfig
    from ray_tpu.rllib.offline import SampleWriter

    eps = _pointmass_episodes(60, seed=2)
    behavior = float(np.mean([np.sum(e.rewards) for e in eps]))
    # Random baseline on the same env.
    rand_eps = _pointmass_episodes(20, seed=3, noise=10.0)
    random_ret = float(np.mean([np.sum(e.rewards) for e in rand_eps]))
    w = SampleWriter(str(tmp_path / "pm"))
    w.write(eps)
    w.close()

    algo = (
        CQLConfig()
        .environment(_PointMassEnv)
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
        .offline_data(input_=str(tmp_path / "pm"))
        .training(
            train_batch_size=256,
            updates_per_iteration=400,
            lr=1e-3,
            bc_iters=400,
            cql_n_actions=4,
            min_q_weight=2.0,
        )
        .debugging(seed=0)
        .build()
    )
    metrics = {}
    for _ in range(4):
        metrics = algo.train()["learners"]
    ev = algo.evaluate(num_episodes=10)
    algo.stop()
    got = ev["episode_return_mean"]
    # Conservatism sanity: Q stays near the feasible return scale.
    assert metrics["qf_mean"] < 50.0, metrics
    # Halfway-to-behavior clears the bar with a wide margin.
    bar = random_ret + 0.5 * (behavior - random_ret)
    assert got > bar, (
        f"CQL offline policy too weak: {got} "
        f"(behavior {behavior}, random {random_ret})"
    )


def test_cql_conservative_regularizer_lowers_ood_q(cluster, tmp_path):
    """The CQL-specific property: after training, Q on out-of-
    distribution (random) actions sits clearly BELOW Q on dataset
    actions — and the gap is wider than a plain SAC critic trained on
    the same batches (no conservative term)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.cql import CQLConfig
    from ray_tpu.rllib.offline import SampleWriter

    eps = _pointmass_episodes(40, seed=5)
    w = SampleWriter(str(tmp_path / "pm2"))
    w.write(eps)
    w.close()

    def gap(min_q_weight):
        algo = (
            CQLConfig()
            .environment(_PointMassEnv)
            .env_runners(num_env_runners=0)
            .offline_data(input_=str(tmp_path / "pm2"))
            .training(
                train_batch_size=256,
                updates_per_iteration=300,
                lr=1e-3,
                bc_iters=0,
                cql_n_actions=4,
                min_q_weight=min_q_weight,
            )
            .debugging(seed=0)
            .build()
        )
        for _ in range(3):
            algo.train()
        learner = algo.learner_group._local
        batch = algo.replay.sample(512)
        obs = jnp.asarray(batch["obs"])
        acts = jnp.asarray(batch["actions"])
        rng = np.random.default_rng(0)
        rand = jnp.asarray(
            rng.uniform(-1, 1, acts.shape).astype(np.float32)
        )
        q_data, _ = learner.module.q_values(learner.params, obs, acts)
        q_rand, _ = learner.module.q_values(learner.params, obs, rand)
        algo.stop()
        return float(jnp.mean(q_rand) - jnp.mean(q_data))

    cql_gap = gap(5.0)
    plain_gap = gap(0.0)
    # Conservative training pushes OOD Q below data Q...
    assert cql_gap < 0.0, cql_gap
    # ...and by a clearly wider margin than the unregularized critic.
    assert cql_gap < plain_gap - 0.5, (cql_gap, plain_gap)


# ------------------------------------------------- tuned_examples runner

def test_tuned_examples_registry_and_ppo_regression(cluster):
    """The declarative pass/fail pattern (reference: tuned_examples/):
    run the fastest config end-to-end, assert the bar is genuinely
    enforced (an impossible bar fails)."""
    from ray_tpu.rllib import tuned_examples as tx

    paths = tx.list_examples()
    names = {os.path.basename(p) for p in paths}
    assert {"cartpole_ppo.yaml", "cartpole_dqn.yaml",
            "pendulum_sac.yaml", "cartpole_dreamerv3.yaml"} <= names

    res = tx.run_regression(
        os.path.join(tx.EXAMPLES_DIR, "cartpole_ppo.yaml")
    )
    assert res.passed, (res.best, res.iterations)
    assert res.best["episode_return_mean"] >= 80.0
    assert len(res.history) == res.iterations

    # The bar is real: an unreachable stop within 1 iteration fails.
    import tempfile

    import yaml

    with open(os.path.join(tx.EXAMPLES_DIR, "cartpole_ppo.yaml")) as f:
        spec = yaml.safe_load(f)
    spec["stop"] = {"episode_return_mean": 1e9}
    spec["max_iterations"] = 1
    with tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", delete=False
    ) as f:
        yaml.safe_dump(spec, f)
        impossible = f.name
    res2 = tx.run_regression(impossible)
    assert not res2.passed and res2.iterations == 1
    os.unlink(impossible)


# -------------------------------------------------------------- DreamerV3

def test_twohot_symlog_roundtrip():
    """Twohot encode/decode is (approximately) the identity through
    the symlog bins, and encodings are proper distributions."""
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.dreamerv3 import _TwoHot

    th = _TwoHot(41)
    xs = jnp.asarray([-50.0, -3.2, -1.0, 0.0, 0.7, 2.5, 99.0])
    enc = th.encode(xs)
    np.testing.assert_allclose(np.asarray(enc.sum(-1)), 1.0, atol=1e-5)
    dec = np.asarray(th.decode(jnp.log(enc + 1e-8)))
    # Exact inside the bin range; clipped at the symlog edges.
    for x, d in zip(np.asarray(xs), dec):
        lo, hi = -np.expm1(20.0), np.expm1(20.0)
        assert abs(d - np.clip(x, lo, hi)) < 0.05 * max(1.0, abs(x)), (x, d)


def test_dreamerv3_cartpole_learns_in_imagination(cluster):
    """World-model RL end-to-end via the TUNED EXAMPLE (single source
    of truth for the hyperparameters): the return climbs well clear of
    random (~20) within a few thousand env steps — learning happens IN
    the model, ~32 replayed steps per env step."""
    from ray_tpu.rllib import tuned_examples as tx

    res = tx.run_regression(
        os.path.join(tx.EXAMPLES_DIR, "cartpole_dreamerv3.yaml")
    )
    assert res.passed, (res.best, res.iterations)
    assert res.best["episode_return_mean"] >= 55.0
