"""TPU-slice autoscaler provider: atomic multi-host slices.

Reference behavior being matched: the GCP provider's whole-slice
queued-resource semantics (autoscaler/_private/gcp/node_provider.py) —
create/delete whole slices, gang node types, rollback of partial
creations.
"""
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler.tpu_slice import (
    MockTpuSliceApi,
    PartialSliceError,
    SliceType,
    TpuSliceProvider,
)
from ray_tpu.autoscaler.v2 import (
    ALLOCATION_FAILED,
    RAY_RUNNING,
    TERMINATED,
    Instance,
    Reconciler,
)

V5E8 = SliceType(
    accelerator="v5e-8",
    hosts=2,
    host_resources={"CPU": 2.0, "TPU": 4.0},
    max_slices=2,
)


@pytest.fixture()
def head():
    ray_tpu.init(num_cpus=1, tcp_port=0, ignore_reinit_error=True)
    from ray_tpu._private.worker import _global

    api = MockTpuSliceApi()
    provider = TpuSliceProvider(
        api,
        {"tpu-v5e-8": V5E8},
        _global.node.tcp_address,
        _global.node.authkey,
    )
    try:
        yield api, provider
    finally:
        api.shutdown()
        ray_tpu.shutdown()


def test_partial_creation_rolls_back_whole_slice(head):
    api, provider = head
    api.fail_next.append([1])  # host 1 of the first create fails
    inst = Instance(
        instance_id="abc123",
        node_type="tpu-v5e-8",
        resources=dict(V5E8.host_resources),
        hosts=2,
    )
    with pytest.raises(PartialSliceError):
        provider.launch(inst)
    # Atomicity: the surviving host was deleted with the slice — no
    # leaked quota, nothing reported running.
    assert "slice-abc123" in api.deleted
    assert api.list_slices() == {}


def test_pg_demand_drives_slice_scale_up_with_retry(head):
    """A placement group demanding the v5e-8 gang (head resource +
    per-host TPU bundles) makes the reconciler launch ONE whole slice;
    a partial creation on the first attempt rolls back and retries."""
    api, provider = head
    rec = Reconciler(
        provider.node_types(),
        provider,
        idle_timeout_s=300.0,  # no scale-down during the test
    )
    rec.step()  # autoscaler running => GCS queues over-capacity PGs
    api.fail_next.append([0])  # first slice creation partially fails

    from ray_tpu.util.placement_group import placement_group

    pg = placement_group(
        [
            {"TPU-v5e-8-head": 1.0, "TPU": 4.0},
            {"TPU": 4.0},
        ],
        strategy="STRICT_SPREAD",
    )
    deadline = time.time() + 90
    while time.time() < deadline and not rec.im.instances(RAY_RUNNING):
        rec.step()
        time.sleep(0.3)
    assert rec.im.instances(RAY_RUNNING), rec.summary()
    # Retry happened: one failed creation (rolled back), one success —
    # and only ONE live slice serves both bundles (gang, not 2 slices).
    assert api.create_calls == 2
    assert rec.im.instances(ALLOCATION_FAILED) == []
    assert len(api.list_slices()) == 1
    assert all(m["hosts"] == 2 for m in api.list_slices().values())
    # The gang actually becomes placeable: the PG reservation completes.
    assert pg.wait(timeout_seconds=60), "placement group never became ready"
    from ray_tpu.util.placement_group import remove_placement_group

    remove_placement_group(pg)


def test_host_loss_kills_whole_slice(head):
    api, provider = head
    rec = Reconciler(
        provider.node_types(),
        provider,
        idle_timeout_s=300.0,
    )
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    rec.step()  # autoscaler running => GCS queues over-capacity PGs
    pg = placement_group([{"TPU": 4.0}, {"TPU": 4.0}], strategy="STRICT_SPREAD")
    deadline = time.time() + 90
    while time.time() < deadline and not rec.im.instances(RAY_RUNNING):
        rec.step()
        time.sleep(0.3)
    assert rec.im.instances(RAY_RUNNING), rec.summary()
    remove_placement_group(pg)

    # Kill ONE host VM: the slice is no longer whole — the reconciler
    # must terminate the ENTIRE slice (atomic), not limp on one host.
    (name, procs), = api._slices.items()
    procs[0].kill()
    deadline = time.time() + 60
    while time.time() < deadline and not rec.im.instances(TERMINATED):
        rec.step()
        time.sleep(0.3)
    assert rec.im.instances(TERMINATED), rec.summary()
    assert api.list_slices() == {}
