"""Observability depth: Prometheus exposition, metrics timeseries,
dashboard log viewer, live worker stack profiling.

Models the reference's dashboard/metrics-agent surface
(dashboard/modules/, _private/metrics_agent.py,
reporter/profile_manager.py).
"""
import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_prometheus_text_format():
    from ray_tpu.util.metrics import prometheus_text

    snap = {
        "lat_ms": {
            "kind": "histogram",
            "description": "latency",
            "boundaries": [1.0, 10.0],
            "series": [{"tags": {"ep": "a"}, "sum": 12.5, "counts": [3, 2, 1]}],
        },
        "busy": {
            "kind": "gauge",
            "description": "",
            "series": [{"tags": {"node": "n1"}, "value": 2.0}],
        },
        "weird name-1": {
            "kind": "counter",
            "description": "d",
            "series": [{"tags": {}, "value": 7}],
        },
    }
    text = prometheus_text(snap)
    assert '# TYPE lat_ms histogram' in text
    assert 'lat_ms_bucket{ep="a",le="1.0"} 3' in text
    assert 'lat_ms_bucket{ep="a",le="+Inf"} 6' in text
    assert 'lat_ms_count{ep="a"} 6' in text
    assert 'busy{node="n1"} 2.0' in text
    # Invalid chars sanitized to underscores.
    assert "weird_name_1 7" in text


def test_metrics_endpoint_serves_user_and_core(cluster):
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util.metrics import Counter

    c = Counter("my_requests", description="reqs", tag_keys=("route",))
    c.inc(3.0, tags={"route": "x"})

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(2)])
    url = start_dashboard(port=18270)
    deadline = time.time() + 10
    text = ""
    while time.time() < deadline:
        with urllib.request.urlopen(f"{url}/metrics") as r:
            text = r.read().decode()
        if "my_requests" in text:
            break
        time.sleep(0.5)
    assert 'my_requests{route="x"} 3.0' in text
    # Core runtime series present too.
    assert "ray_tpu_resources_total" in text
    assert "ray_tpu_nodes_alive 1" in text
    assert "ray_tpu_control_messages" in text


def test_metrics_timeseries_accumulates(cluster):
    from ray_tpu.dashboard import start_dashboard

    url = start_dashboard(port=18271)
    time.sleep(5)
    with urllib.request.urlopen(f"{url}/api/metrics_timeseries") as r:
        ts = json.loads(r.read())
    assert "nodes alive" in ts["series"]
    assert len(ts["series"]["nodes alive"]) >= 2
    assert ts["series"]["nodes alive"][-1] == 1.0


def test_dashboard_log_viewer(cluster):
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def shouty():
        print("HELLO-FROM-WORKER-xyzzy")
        return 1

    ray_tpu.get(shouty.remote())
    url = start_dashboard(port=18272)
    deadline = time.time() + 15
    found = False
    while time.time() < deadline and not found:
        with urllib.request.urlopen(f"{url}/api/logs?tail=500") as r:
            lines = json.loads(r.read())["lines"]
        found = any("xyzzy" in l[2] for l in lines)
        time.sleep(0.5)
    assert found, "worker print never reached the dashboard log viewer"


def test_worker_stack_profiling(cluster):
    """A live stack dump from a worker stuck in user code shows the
    user frame (the case profiling exists for)."""
    import threading

    @ray_tpu.remote
    def stuck_in_user_code():
        time.sleep(8.0)
        return 1

    ref = stuck_in_user_code.remote()
    # Find the busy worker.
    from ray_tpu._private.worker import global_client
    from ray_tpu.util.state import list_workers

    wid = None
    deadline = time.time() + 10
    while time.time() < deadline and wid is None:
        for w in list_workers():
            if w.get("state") == "BUSY":
                wid = bytes.fromhex(w["worker_id"])
                break
        time.sleep(0.2)
    assert wid is not None, "no busy worker found"
    reply = global_client().request(
        {"type": "worker_stacks", "worker_id": wid}, timeout=15.0
    )
    assert reply.get("ok"), reply
    assert "stuck_in_user_code" in reply["text"]
    assert "--- thread" in reply["text"]
    ray_tpu.get(ref)


def test_worker_stacks_unknown_worker(cluster):
    from ray_tpu._private.worker import global_client

    reply = global_client().request(
        {"type": "worker_stacks", "worker_id": b"\x00" * 16}, timeout=10.0
    )
    assert not reply.get("ok")


def test_sampling_profile_folded_stacks(cluster):
    """?mode=sample returns a statistical profile in folded-flamegraph
    format with the busy function dominating (reference:
    profile_manager.py py-spy -f capture)."""
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util.state import list_workers

    @ray_tpu.remote
    class Spinner:
        def pid(self):
            import os as _os

            return _os.getpid()

        def spin_hot_loop_marker(self, seconds):
            import time as _t

            t_end = _t.monotonic() + seconds
            x = 0
            while _t.monotonic() < t_end:
                x += 1
            return x

    s = Spinner.remote()
    target_pid = ray_tpu.get(s.pid.remote())
    ref = s.spin_hot_loop_marker.remote(8.0)

    url = start_dashboard(port=18273)
    # Select the spinner's worker by pid: other actors (the dashboard
    # itself) are also "is_actor" workers.
    wid = next(
        w["worker_id"]
        for w in list_workers(limit=100)
        if w["pid"] == target_pid
    )
    with urllib.request.urlopen(
        f"{url}/api/profile/{wid}?mode=sample&duration=2", timeout=30
    ) as r:
        folded = r.read().decode()
    assert folded.startswith("# folded stacks:")
    lines = [l for l in folded.splitlines()[1:] if l.strip()]
    assert lines, folded
    # Every line is "stack;frames count".
    for line in lines[:5]:
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit(), line
    # The hot loop dominates the samples.
    assert "spin_hot_loop_marker" in folded
    ray_tpu.get(ref, timeout=60)
