"""Observability depth: Prometheus exposition, metrics timeseries,
dashboard log viewer, live worker stack profiling, and the
flight-recorder event pipeline (_private/events.py).

Models the reference's dashboard/metrics-agent surface
(dashboard/modules/, _private/metrics_agent.py,
reporter/profile_manager.py) plus the task-event path
(task_event_buffer.h → gcs_task_manager.h → timeline).
"""
import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_prometheus_text_format():
    from ray_tpu.util.metrics import prometheus_text

    snap = {
        "lat_ms": {
            "kind": "histogram",
            "description": "latency",
            "boundaries": [1.0, 10.0],
            "series": [{"tags": {"ep": "a"}, "sum": 12.5, "counts": [3, 2, 1]}],
        },
        "busy": {
            "kind": "gauge",
            "description": "",
            "series": [{"tags": {"node": "n1"}, "value": 2.0}],
        },
        "weird name-1": {
            "kind": "counter",
            "description": "d",
            "series": [{"tags": {}, "value": 7}],
        },
    }
    text = prometheus_text(snap)
    assert '# TYPE lat_ms histogram' in text
    assert 'lat_ms_bucket{ep="a",le="1.0"} 3' in text
    assert 'lat_ms_bucket{ep="a",le="+Inf"} 6' in text
    assert 'lat_ms_count{ep="a"} 6' in text
    assert 'busy{node="n1"} 2.0' in text
    # Invalid chars sanitized to underscores.
    assert "weird_name_1 7" in text


def test_prometheus_label_value_escaping():
    """Exposition format requires backslash, quote AND newline escaped
    in label values — a raw newline splits the sample line and corrupts
    the whole scrape (regression: newline was passed through)."""
    from ray_tpu.util.metrics import prometheus_text

    snap = {
        "m": {
            "kind": "gauge",
            "description": "",
            "series": [
                {
                    "tags": {"err": 'a"b\\c\nd'},
                    "value": 1.0,
                }
            ],
        },
    }
    text = prometheus_text(snap)
    assert '\\n' in text
    assert 'm{err="a\\"b\\\\c\\nd"} 1.0' in text
    # Every sample stays on one physical line.
    for line in text.splitlines():
        assert line.startswith(("#", "m")) or not line


def test_metrics_endpoint_serves_user_and_core(cluster):
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util.metrics import Counter

    c = Counter("my_requests", description="reqs", tag_keys=("route",))
    c.inc(3.0, tags={"route": "x"})

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(2)])
    url = start_dashboard(port=18270)
    deadline = time.time() + 10
    text = ""
    while time.time() < deadline:
        with urllib.request.urlopen(f"{url}/metrics") as r:
            text = r.read().decode()
        if "my_requests" in text:
            break
        time.sleep(0.5)
    assert 'my_requests{route="x"} 3.0' in text
    # Core runtime series present too.
    assert "ray_tpu_resources_total" in text
    assert "ray_tpu_nodes_alive 1" in text
    assert "ray_tpu_control_messages" in text


def test_metrics_timeseries_accumulates(cluster):
    from ray_tpu.dashboard import start_dashboard

    url = start_dashboard(port=18271)
    time.sleep(5)
    with urllib.request.urlopen(f"{url}/api/metrics_timeseries") as r:
        ts = json.loads(r.read())
    assert "nodes alive" in ts["series"]
    assert len(ts["series"]["nodes alive"]) >= 2
    assert ts["series"]["nodes alive"][-1] == 1.0


def test_dashboard_log_viewer(cluster):
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def shouty():
        print("HELLO-FROM-WORKER-xyzzy")
        return 1

    ray_tpu.get(shouty.remote())
    url = start_dashboard(port=18272)
    deadline = time.time() + 15
    found = False
    while time.time() < deadline and not found:
        with urllib.request.urlopen(f"{url}/api/logs?tail=500") as r:
            lines = json.loads(r.read())["lines"]
        found = any("xyzzy" in l[2] for l in lines)
        time.sleep(0.5)
    assert found, "worker print never reached the dashboard log viewer"


def test_worker_stack_profiling(cluster):
    """A live stack dump from a worker stuck in user code shows the
    user frame (the case profiling exists for)."""
    import threading

    @ray_tpu.remote
    def stuck_in_user_code():
        time.sleep(8.0)
        return 1

    ref = stuck_in_user_code.remote()
    # Find the busy worker.
    from ray_tpu._private.worker import global_client
    from ray_tpu.util.state import list_workers

    wid = None
    deadline = time.time() + 10
    while time.time() < deadline and wid is None:
        for w in list_workers():
            if w.get("state") == "BUSY":
                wid = bytes.fromhex(w["worker_id"])
                break
        time.sleep(0.2)
    assert wid is not None, "no busy worker found"
    reply = global_client().request(
        {"type": "worker_stacks", "worker_id": wid}, timeout=15.0
    )
    assert reply.get("ok"), reply
    assert "stuck_in_user_code" in reply["text"]
    assert "--- thread" in reply["text"]
    ray_tpu.get(ref)


def test_worker_stacks_unknown_worker(cluster):
    from ray_tpu._private.worker import global_client

    reply = global_client().request(
        {"type": "worker_stacks", "worker_id": b"\x00" * 16}, timeout=10.0
    )
    assert not reply.get("ok")


def test_sampling_profile_folded_stacks(cluster):
    """?mode=sample returns a statistical profile in folded-flamegraph
    format with the busy function dominating (reference:
    profile_manager.py py-spy -f capture)."""
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util.state import list_workers

    @ray_tpu.remote
    class Spinner:
        def pid(self):
            import os as _os

            return _os.getpid()

        def spin_hot_loop_marker(self, seconds):
            import time as _t

            t_end = _t.monotonic() + seconds
            x = 0
            while _t.monotonic() < t_end:
                x += 1
            return x

    s = Spinner.remote()
    target_pid = ray_tpu.get(s.pid.remote())
    ref = s.spin_hot_loop_marker.remote(8.0)

    url = start_dashboard(port=18273)
    # Select the spinner's worker by pid: other actors (the dashboard
    # itself) are also "is_actor" workers.
    wid = next(
        w["worker_id"]
        for w in list_workers(limit=100)
        if w["pid"] == target_pid
    )
    with urllib.request.urlopen(
        f"{url}/api/profile/{wid}?mode=sample&duration=2", timeout=30
    ) as r:
        folded = r.read().decode()
    assert folded.startswith("# folded stacks:")
    lines = [l for l in folded.splitlines()[1:] if l.strip()]
    assert lines, folded
    # Every line is "stack;frames count".
    for line in lines[:5]:
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit(), line
    # The hot loop dominates the samples.
    assert "spin_hot_loop_marker" in folded
    ray_tpu.get(ref, timeout=60)


# ---------------------------------------------------- flight recorder


def test_flight_recorder_ring_overflow_drop_accounting():
    """Overflow evicts oldest, counts every drop, and the counter
    resets per drain so batches never double-count."""
    from ray_tpu._private.events import TASK, FlightRecorder

    rec = FlightRecorder(capacity=4, enabled=True, source="unit")
    for i in range(10):
        rec.record(TASK, f"t{i}", "SUBMITTED")
    assert len(rec) == 4
    items, dropped = rec.drain()
    assert len(items) == 4 and dropped == 6
    # Oldest evicted: the survivors are the newest four.
    assert [it[3] for it in items] == ["t6", "t7", "t8", "t9"]
    # Drain is destructive and resets the drop counter.
    items, dropped = rec.drain()
    assert items == [] and dropped == 0


def test_flight_recorder_disabled_records_nothing():
    from ray_tpu._private.events import TASK, FlightRecorder

    rec = FlightRecorder(capacity=4, enabled=False)
    rec.record(TASK, "t", "SUBMITTED")
    assert len(rec) == 0 and rec.dropped == 0


def test_aggregator_span_expansion_and_phase_histograms():
    """One SUBMIT_SPAN + one EXEC_SPAN (the compact hot-path form)
    expand into all seven transitions and feed the six phase
    histograms."""
    from ray_tpu._private.events import (
        TASK,
        TASK_PHASES,
        TASK_TRANSITIONS,
        EventAggregator,
    )

    agg = EventAggregator(per_job_cap=100)
    t0 = 1000.0
    agg.ingest(
        [
            (t0, 1.0, TASK, "tid1", "SUBMIT_SPAN",
             {"t_submit": t0, "t_queue": t0 + 1, "t_lease": t0 + 2}),
            (t0 + 6, 2.0, TASK, "tid1", "EXEC_SPAN",
             {"t_fork": t0 + 3, "t_start": t0 + 4, "t_end": t0 + 5,
              "t_seal": t0 + 6, "worker": "w1"}),
        ],
        source="unit",
    )
    names = [e["event"] for e in agg.task_transitions("tid1")]
    assert names == list(TASK_TRANSITIONS)
    summary = agg.summary()
    for phase in TASK_PHASES:
        assert sum(summary["phase_counts"][phase]) == 1
        assert summary["phase_sums"][phase] == pytest.approx(1.0)


def test_aggregator_per_job_retention_counts_evictions():
    from ray_tpu._private.events import TASK, EventAggregator

    agg = EventAggregator(per_job_cap=5)
    agg.ingest(
        [(float(i), float(i), TASK, f"t{i}", "SUBMITTED", None)
         for i in range(12)],
        source="jobA",
    )
    summary = agg.summary()
    assert summary["jobs"]["jobA"] == 5
    assert summary["drops"]["jobA"] == 7  # evictions, never silent
    # Ring drops from the shipping batch land beside retention drops.
    agg.ingest([], source="jobA", ring_dropped=3)
    assert agg.summary()["drops"]["jobA"] == 10


def test_aggregator_merges_local_ring_before_shipped_batches():
    """The driver/head SUBMIT_SPAN sits in the process-local ring while
    the worker's EXEC_SPAN ships on the next done-batch flush; the
    aggregator must drain the local ring ahead of shipped batches or
    every task's submit/queue/lease phases collapse to zero width and
    an orphan open-task entry leaks per task."""
    from ray_tpu._private.events import (
        TASK,
        TASK_PHASES,
        EventAggregator,
        FlightRecorder,
    )

    rec = FlightRecorder(capacity=100, enabled=True, source="driver")
    agg = EventAggregator(per_job_cap=100)
    agg.local_recorder = rec
    t0 = 1000.0
    rec.record(
        TASK, "tid", "SUBMIT_SPAN",
        {"t_submit": t0, "t_queue": t0 + 1, "t_lease": t0 + 2},
    )
    agg.ingest(
        [(t0 + 6, 0.0, TASK, "tid", "EXEC_SPAN",
          {"t_fork": t0 + 3, "t_start": t0 + 4, "t_end": t0 + 5,
           "t_seal": t0 + 6, "worker": "w"})],
        source="worker-1",
    )
    summary = agg.summary()
    for phase in TASK_PHASES:
        assert summary["phase_sums"][phase] == pytest.approx(1.0), phase
    assert not agg._open  # sealed and fully merged, no orphan


def test_aggregator_list_nonpositive_limit_returns_nothing():
    """limit=0 must not invert into 'everything' via a -0 slice (the
    dashboard passes user-supplied limits straight through)."""
    from ray_tpu._private.events import TASK, EventAggregator

    agg = EventAggregator(per_job_cap=10)
    agg.ingest([(1.0, 0.0, TASK, "t", "SUBMITTED", None)], source="j")
    assert agg.list(limit=0) == []
    assert agg.list(limit=-5) == []
    assert len(agg.list(limit=10)) == 1


def test_stitch_clamps_cross_process_clock_skew():
    """A worker wall clock behind the head's must not yield negative
    phase durations — boundaries clamp monotone."""
    from ray_tpu._private.events import TASK_PHASES, stitch_task_phases

    evs = [
        {"category": "task", "entity": "t", "event": e, "timestamp": ts}
        for e, ts in (
            ("SUBMITTED", 100.0),
            ("QUEUED", 100.5),
            ("LEASED", 101.0),
            ("FORKED", 100.2),  # skewed: behind the lease timestamp
            ("EXEC_START", 100.3),
            ("EXEC_END", 102.0),
            ("SEALED", 102.1),
        )
    ]
    rows = stitch_task_phases(evs)["t"]
    assert [r["name"] for r in rows] == list(TASK_PHASES)
    for a, b in zip(rows, rows[1:]):
        assert a["dur"] >= 0
        assert b["ts"] == pytest.approx(a["ts"] + a["dur"])


def test_task_timeline_six_phases_e2e(cluster, tmp_path):
    """A 3-task run yields a valid Chrome trace with one stitched row
    per task: six phases, monotonically ordered and contiguous; the
    `ray_tpu events --task` surface returns the same transitions."""
    from ray_tpu._private.events import TASK_PHASES, TASK_TRANSITIONS
    from ray_tpu._private.state import task_transitions, timeline
    from ray_tpu.util.state import list_cluster_events

    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get([f.remote(i) for i in range(3)]) == [0, 2, 4]

    deadline = time.time() + 20
    tids = []
    while time.time() < deadline:
        evs = list_cluster_events(category="task", limit=10_000)
        by = {}
        for e in evs:
            by.setdefault(e["entity"], set()).add(e["event"])
        tids = [
            t for t, names in by.items()
            if set(TASK_TRANSITIONS) <= names
        ]
        if len(tids) >= 3:
            break
        time.sleep(0.3)
    assert len(tids) >= 3, f"complete lifecycles: {len(tids)}"

    out = tmp_path / "trace.json"
    timeline(str(out))
    trace = json.loads(out.read_text())  # valid Chrome trace JSON
    assert isinstance(trace, list)
    by_task = {}
    for row in trace:
        if row.get("cat") == "task_phase":
            by_task.setdefault(row["args"]["task_id"], []).append(row)
    for tid in tids:
        rows = by_task[tid]
        assert [r["name"] for r in rows] == list(TASK_PHASES)
        for a, b in zip(rows, rows[1:]):
            assert a["dur"] >= 0
            # Contiguous + monotone: each phase starts where the
            # previous ended.
            assert b["ts"] == pytest.approx(a["ts"] + a["dur"])

    # Same transitions through the per-task read the CLI uses.
    names = [e["event"] for e in task_transitions(tids[0])]
    assert set(TASK_TRANSITIONS) <= set(names)
    ts = [e["timestamp"] for e in task_transitions(tids[0])]
    assert ts == sorted(ts)


def test_events_cli_lists_task_transitions(cluster, monkeypatch, capsys):
    from ray_tpu._private.events import TASK_TRANSITIONS
    from ray_tpu.scripts import cli
    from ray_tpu.util.state import list_cluster_events

    @ray_tpu.remote
    def g():
        return 1

    ray_tpu.get(g.remote())
    deadline = time.time() + 20
    tid = None
    while time.time() < deadline and tid is None:
        for e in list_cluster_events(category="task", event="SEALED"):
            tid = e["entity"]
        if tid is None:
            time.sleep(0.3)
    assert tid is not None
    monkeypatch.setattr(cli, "_connect", lambda: None)
    cli.main(["events", "--task", tid])
    table = capsys.readouterr().out
    for name in ("SUBMITTED", "EXEC_START", "SEALED"):
        assert name in table
    cli.main(["events", "--task", tid, "--json"])
    rows = json.loads(capsys.readouterr().out)
    assert {r["event"] for r in rows} >= set(TASK_TRANSITIONS[:1])


def test_event_drops_exported_as_prometheus_counter(cluster):
    """Deliberate ring overflow: the drop count ships with the batch,
    lands in the aggregator, and surfaces as a Prometheus counter —
    never silently lost."""
    from ray_tpu._private import events as ev
    from ray_tpu._private.worker import global_client
    from ray_tpu.util.metrics import (
        flight_recorder_snapshot,
        prometheus_text,
    )
    from ray_tpu.util.state import summarize_events

    rec = ev.FlightRecorder(capacity=4, enabled=True, source="overflow-t")
    for i in range(20):
        rec.record(ev.TASK, f"x{i}", "SUBMITTED")
    items, dropped = rec.drain()
    assert dropped == 16
    global_client().send(
        {
            "type": "event_batch",
            "events": items,
            "events_dropped": dropped,
            "source": rec.source,
        }
    )
    deadline = time.time() + 10
    while time.time() < deadline:
        if summarize_events()["drops"].get("overflow-t", 0) >= 16:
            break
        time.sleep(0.2)
    text = prometheus_text(flight_recorder_snapshot())
    assert "# TYPE ray_tpu_flight_recorder_dropped_total counter" in text
    assert (
        'ray_tpu_flight_recorder_dropped_total{source="overflow-t"} 16'
        in text
    )


def test_flight_recorder_overhead_budget(cluster):
    """The recorder is always-on, so it must be nearly free: ≤5% on
    the single_client_tasks_async shape vs recorder disabled.

    Shared CI hosts swing far more than the 5% signal between fixed
    windows, so the measurement is built to survive that: both configs
    run in ONE cluster, A/B-ed with the runtime recording toggle in
    tightly-paired off/on segments so drift hits both sides alike.
    Each attempt produces two independent estimators —

    - wall: each side's fastest single batch (external load only ever
      slows a batch down, so per-side minima converge to true cost);
    - cpu: median over pairs of the segment ratio of driver-process
      CPU per task (`time.process_time` spans all threads of the
      driver process, which hosts the client loop, GCS dispatch AND
      the event indexer — exactly where recorder cost lands — and
      neighbors' load cannot inflate it);

    and the budget must fail BOTH estimators on EVERY attempt before
    the test does. A real regression (overhead well past 5%) fails
    them all; a one-sided load spike cannot."""
    import statistics

    from ray_tpu.util.state import set_events_recording

    @ray_tpu.remote
    def tiny():
        return b"ok"

    batch = 200
    # Warm up: spawn workers, grow the lease pool to steady state.
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 3.0:
        ray_tpu.get([tiny.remote() for _ in range(batch)])

    def segment(rounds: int):
        """(fastest single-batch wall seconds, CPU seconds) over
        `rounds` batches."""
        best_wall = float("inf")
        c0 = time.process_time()
        for _ in range(rounds):
            t0 = time.perf_counter()
            ray_tpu.get([tiny.remote() for _ in range(batch)])
            best_wall = min(best_wall, time.perf_counter() - t0)
        return best_wall, time.process_time() - c0

    attempts = []
    try:
        for _attempt in range(4):
            wall_on = wall_off = float("inf")
            cpu_ratios = []
            for _ in range(6):
                set_events_recording(False)
                w_off, c_off = segment(5)
                set_events_recording(True)
                w_on, c_on = segment(5)
                wall_off = min(wall_off, w_off)
                wall_on = min(wall_on, w_on)
                if c_on > 0:
                    cpu_ratios.append(c_off / c_on)
            wall_ratio = wall_off / wall_on
            cpu_ratio = statistics.median(cpu_ratios) if cpu_ratios else 1.0
            attempts.append((wall_ratio, cpu_ratio))
            if wall_ratio >= 0.95 or cpu_ratio >= 0.95:
                break
        else:
            raise AssertionError(
                "flight recorder overhead over budget on every attempt "
                "and both estimators: (wall, cpu) off/on ratios "
                f"{[('%.3f' % w, '%.3f' % c) for w, c in attempts]} "
                "all < 0.95"
            )
    finally:
        set_events_recording(True)  # leave the cluster fixture as found
