"""Scale-envelope stress: many nodes / many actors / deep task queues.

Reference envelope: release/benchmarks/README.md:9-31 — 2,000 nodes,
1M queued tasks, 10k+ concurrent actors/tasks (many_nodes 588 tasks/s,
many_actors 604 actors/s). This host has one core, so the CI-budget
versions here run at reduced-but-representative scale and assert
correctness under load; ray_perf --only scale records the throughput
numbers into PERF.json at full stress scale.

What each test is designed to crack:
- virtual-node churn: the head's node table, scheduler scan, and PG
  2PC accounting at 120+ nodes
- deep queues: the pending-task queue's dequeue path at 20k backlog
  (an O(queue) rescan per grant would time out here)
- actor fan: actor state machine + worker pool under dozens of
  concurrent creations, then a broadcast call storm
"""
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
)


@pytest.fixture
def head():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_many_virtual_nodes_register_and_list(head):
    cluster = Cluster(initialize_head=False)
    t0 = time.monotonic()
    for i in range(120):
        cluster.add_node(num_cpus=4, label=f"n{i}")
    dt = time.monotonic() - t0
    nodes = ray_tpu.nodes()
    assert len(nodes) >= 121  # head + 120
    # Registration must stay sub-linear-ish: > 30/s even on this host.
    assert dt < 4.0, f"120 node registrations took {dt:.1f}s"


def test_1k_nodes_deep_queue_stays_responsive(head):
    """The head envelope at reference shape (release/benchmarks/
    README.md: 2k nodes / 1M queued): 1,000 registered nodes must not
    slow the dispatch path. Nodes carry capacity no {CPU: 1} task can
    use, so every queued task scans past them — the per-scheduling-
    class pending queues make that one probe per class per pass
    (gcs._PendingQueue), not one per task."""
    cluster = Cluster(initialize_head=False)
    t0 = time.monotonic()
    for i in range(1000):
        cluster.add_node(resources={"CPU": 0.001}, label=f"v{i}")
    reg_dt = time.monotonic() - t0
    assert len(ray_tpu.nodes()) >= 1001
    assert reg_dt < 30.0, f"1k registrations took {reg_dt:.1f}s"

    @ray_tpu.remote(num_cpus=1)
    def unit(i):
        return i

    n = 10_000
    t0 = time.monotonic()
    refs = [unit.remote(i) for i in range(n)]
    out = ray_tpu.get(refs, timeout=600)
    rate = n / (time.monotonic() - t0)
    assert len(out) == n and out[-1] == n - 1
    # Must stay in the same envelope as the 120-node drain (was ~6k/s
    # before per-class queues O(queue x nodes) would collapse this).
    assert rate > 300, f"drained at {rate:.0f}/s with 1k nodes registered"


def test_class_queues_stay_fair_under_saturation(head):
    """Two resource classes contending for the same saturated CPUs:
    the per-class pending queues rotate (gcs._schedule_once
    move_to_end), so neither class starves while the other streams
    (the old global FIFO's arrival-order property, class-granular)."""
    @ray_tpu.remote(num_cpus=1)
    def big(i):
        time.sleep(0.01)
        return ("big", i)

    @ray_tpu.remote(num_cpus=0.5)
    def small(i):
        time.sleep(0.01)
        return ("small", i)

    # Saturate 2 CPUs with 80 queued tasks across two classes and watch
    # completion order: the first finishers must include BOTH classes
    # (a starved class would finish strictly after the other drained).
    done_kinds = []
    pending = [big.remote(i) for i in range(40)] + [
        small.remote(i) for i in range(40)
    ]
    while pending and len(done_kinds) < 40:
        ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=120)
        done_kinds.extend(kind for kind, _ in ray_tpu.get(ready))
    assert {"big", "small"} <= set(done_kinds), (
        f"one class starved: first finishers {done_kinds[:10]}"
    )


def test_pg_churn_across_many_nodes(head):
    """PG create/remove across a wide cluster: bundle reservation is a
    per-node 2PC against the resource ledger; churn must not leak."""
    cluster = Cluster(initialize_head=False)
    for i in range(100):
        cluster.add_node(num_cpus=2, label=f"n{i}")
    before = ray_tpu.available_resources()
    for round_ in range(5):
        pgs = [
            placement_group([{"CPU": 1}] * 4, strategy="SPREAD")
            for _ in range(25)
        ]
        for pg in pgs:
            assert pg.wait(timeout_seconds=30)
        for pg in pgs:
            remove_placement_group(pg)
    # Every bundle released: the ledger returns to its starting state.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if ray_tpu.available_resources() == before:
            break
        time.sleep(0.2)
    assert ray_tpu.available_resources() == before


def test_deep_task_queue_drains(head):
    """20k tasks against 2 CPU slots: the backlog must drain without
    the dequeue path collapsing (reference: many_tasks queues 1M)."""

    @ray_tpu.remote(num_cpus=1)
    def unit(i):
        return i

    n = 20_000
    t0 = time.monotonic()
    refs = [unit.remote(i) for i in range(n)]
    t_submit = time.monotonic() - t0
    out = ray_tpu.get(refs, timeout=600)
    t_total = time.monotonic() - t0
    assert out[0] == 0 and out[-1] == n - 1 and len(out) == n
    rate = n / t_total
    # Well over the reference's 588/s envelope even while queued deep.
    assert rate > 300, f"drained at {rate:.0f}/s (submit {t_submit:.1f}s)"


def test_many_actor_fan(head):
    """Dozens of concurrent actor creations + a call storm: the actor
    state machine, worker pool, and direct transport under fan-out."""

    @ray_tpu.remote(num_cpus=0.01)
    class Cell:
        def __init__(self, i):
            self.i = i
            self.calls = 0

        def bump(self):
            self.calls += 1
            return (self.i, self.calls)

    n_actors = 100
    t0 = time.monotonic()
    actors = [Cell.remote(i) for i in range(n_actors)]
    ray_tpu.get([a.bump.remote() for a in actors], timeout=300)
    create_rate = n_actors / (time.monotonic() - t0)
    # Zygote fork-spawn keeps creation out of interpreter-cold-start
    # territory even on one core (was 1.6/s before the fork server).
    assert create_rate > 3, f"actor creation at {create_rate:.1f}/s"
    # 19 more calls each, all in flight together: ~2k concurrent results.
    refs = [a.bump.remote() for _ in range(19) for a in actors]
    out = ray_tpu.get(refs, timeout=300)
    assert len(out) == n_actors * 19
    per = {}
    for i, c in out:
        per[i] = max(per.get(i, 0), c)
    assert all(per[i] == 20 for i in range(n_actors))
    for a in actors:
        ray_tpu.kill(a)


def test_forked_workers_mint_unique_ids(head):
    """Zygote-forked workers MUST re-seed their id generators: two forks
    sharing the parent's prefix+counter would mint colliding task ids
    (ids.py _reseed_after_fork)."""

    @ray_tpu.remote(num_cpus=0.01)
    class G:
        def ids(self, n):
            from ray_tpu._private.ids import fast_unique_bytes

            return [fast_unique_bytes() for _ in range(n)]

    gens = [G.remote() for _ in range(8)]
    batches = ray_tpu.get([g.ids.remote(200) for g in gens], timeout=120)
    all_ids = [i for b in batches for i in b]
    assert len(set(all_ids)) == len(all_ids), "forked workers minted duplicate ids"
    for g in gens:
        ray_tpu.kill(g)


def test_queue_survives_node_removal(head):
    """Queued work bound for a node that dies must not wedge the queue:
    remaining capacity keeps draining (reference: cluster_task_manager
    spillback + lineage)."""
    cluster = Cluster(initialize_head=False)
    node = cluster.add_node(num_cpus=2, label="doomed")

    @ray_tpu.remote(num_cpus=1)
    def unit(i):
        return i

    refs = [unit.remote(i) for i in range(200)]
    time.sleep(0.2)
    cluster.remove_node(node)
    out = ray_tpu.get(refs, timeout=300)
    assert len(out) == 200 and out[99] == 99
