"""JaxTrainer: worker group, report contract, checkpoints, restart.

Models reference coverage in python/ray/train/tests (backend executor,
session, checkpointing) on the local cluster.
"""
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def ray4(tmp_path):
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield str(tmp_path)
    ray_tpu.shutdown()


def test_single_worker_report(ray4):
    def loop(config):
        for i in range(3):
            rt_train.report({"iter": i, "loss": 1.0 / (i + 1)})

    result = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=ray4),
    ).fit()
    assert result.error is None
    assert result.metrics["iter"] == 2
    assert len(result.metrics_history) == 3


def test_multi_worker_ranks(ray4):
    def loop(config):
        ctx = rt_train.get_context()
        rt_train.report({"rank": ctx.world_rank, "world": ctx.world_size})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=3),
        run_config=RunConfig(storage_path=ray4),
    ).fit()
    assert result.error is None
    assert result.metrics["world"] == 3
    assert result.metrics["rank"] == 0  # rank-0 metrics surface


def test_checkpoint_persistence(ray4):
    def loop(config):
        ctx = rt_train.get_context()
        for i in range(2):
            if ctx.world_rank == 0:
                d = f"/tmp/ray_tpu_test_ckpt_{os.getpid()}_{i}"
                os.makedirs(d, exist_ok=True)
                with open(os.path.join(d, "state.txt"), "w") as f:
                    f.write(f"iter={i}")
                rt_train.report({"iter": i}, checkpoint=Checkpoint(d))
            else:
                rt_train.report({"iter": i})

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=ray4),
    ).fit()
    assert result.error is None
    assert result.checkpoint is not None
    with open(os.path.join(result.checkpoint.as_directory(), "state.txt")) as f:
        assert f.read() == "iter=1"


def test_train_error_surfaces(ray4):
    def loop(config):
        raise ValueError("train loop blew up")

    with pytest.raises(Exception):
        JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(storage_path=ray4),
        ).fit()


def test_jax_training_in_worker(ray4):
    """End-to-end: real jax training inside the worker actor (CPU)."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import CONFIGS, LlamaForCausalLM
        from ray_tpu.models.llama import causal_lm_loss

        cfg = CONFIGS["llama-tiny"]
        model = LlamaForCausalLM(cfg)
        ids = jnp.ones((2, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), ids)
        tx = optax.sgd(1e-2)
        opt = tx.init(params)

        @jax.jit
        def step(p, o):
            loss, g = jax.value_and_grad(
                lambda p_: causal_lm_loss(model.apply(p_, ids), ids)
            )(p)
            up, o = tx.update(g, o)
            return optax.apply_updates(p, up), o, loss

        losses = []
        for _ in range(3):
            params, opt, loss = step(params, opt)
            losses.append(float(loss))
            rt_train.report({"loss": float(loss)})
        assert losses[-1] <= losses[0]

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=ray4),
    ).fit()
    assert result.error is None
    assert "loss" in result.metrics


def test_orbax_save_load_roundtrip(tmp_path):
    import jax.numpy as jnp

    from ray_tpu.train import load_pytree, save_pytree

    tree = {"w": jnp.arange(8.0).reshape(2, 4), "step": jnp.asarray(3)}
    path = str(tmp_path / "ckpt")
    save_pytree(path, tree)
    restored = load_pytree(path)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(tree["w"]))
