"""Actors: creation, ordering, named actors, failure semantics.

Models the reference's python/ray/tests/test_actor.py coverage.
"""
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n

    def read(self):
        return self.n


def test_actor_basic(ray_start):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    assert ray_tpu.get(c.incr.remote(10)) == 11


def test_actor_constructor_args(ray_start):
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote()) == 100


def test_actor_method_ordering(ray_start):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_actor_state_isolated(ray_start):
    a, b = Counter.remote(), Counter.remote()
    ray_tpu.get(a.incr.remote())
    assert ray_tpu.get(b.read.remote()) == 0


def test_actor_handle_passing(ray_start):
    @ray_tpu.remote
    def bump(counter):
        return ray_tpu.get(counter.incr.remote())

    c = Counter.remote()
    assert ray_tpu.get(bump.remote(c)) == 1
    assert ray_tpu.get(c.read.remote()) == 1


def test_named_actor(ray_start):
    Counter.options(name="global_counter").remote(5)
    time.sleep(0.1)
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.read.remote()) == 5


def test_named_actor_duplicate_fails(ray_start):
    Counter.options(name="dup").remote()
    with pytest.raises(ValueError, match="already taken"):
        Counter.options(name="dup").remote()


def test_get_if_exists(ray_start):
    a = Counter.options(name="gie", get_if_exists=True).remote(7)
    ray_tpu.get(a.read.remote())  # ensure created
    b = Counter.options(name="gie", get_if_exists=True).remote(7)
    ray_tpu.get(a.incr.remote())
    assert ray_tpu.get(b.read.remote()) == 8


def test_kill_actor(ray_start):
    c = Counter.remote()
    ray_tpu.get(c.incr.remote())
    ray_tpu.kill(c)
    with pytest.raises(RayActorError):
        ray_tpu.get(c.incr.remote(), timeout=10)


def test_actor_creation_failure(ray_start):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("cannot construct")

        def ping(self):
            return "pong"

    b = Broken.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.ping.remote(), timeout=10)


def test_actor_method_error(ray_start):
    @ray_tpu.remote
    class Faulty:
        def bad(self):
            raise ValueError("method error")

        def good(self):
            return "ok"

    f = Faulty.remote()
    with pytest.raises(ValueError):
        ray_tpu.get(f.bad.remote())
    # Actor survives method errors.
    assert ray_tpu.get(f.good.remote()) == "ok"


def test_async_actor(ray_start):
    @ray_tpu.remote
    class AsyncActor:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncActor.remote()
    assert ray_tpu.get([a.work.remote(i) for i in range(5)]) == [0, 2, 4, 6, 8]


def test_max_concurrency_threaded(ray_start):
    @ray_tpu.remote(max_concurrency=4)
    class Slow:
        def work(self):
            time.sleep(0.3)
            return 1

    s = Slow.remote()
    ray_tpu.get(s.work.remote())  # warm up: actor creation + worker spawn
    start = time.monotonic()
    ray_tpu.get([s.work.remote() for _ in range(4)])
    elapsed = time.monotonic() - start
    # 4 concurrent 0.3s calls should take well under 4*0.3s serial time.
    assert elapsed < 1.0


def test_actor_death_by_exit(ray_start):
    @ray_tpu.remote
    class Dying:
        def die(self):
            import os

            os._exit(1)

        def ping(self):
            return "pong"

    d = Dying.remote()
    assert ray_tpu.get(d.ping.remote()) == "pong"
    d.die.remote()
    with pytest.raises(RayActorError):
        ray_tpu.get(d.ping.remote(), timeout=10)


def test_actors_dont_hold_cpus(ray_start):
    # Actors default to 0 CPUs for their lifetime, so many actors coexist
    # on few cores (reference: ray_option_utils defaults).
    counters = [Counter.remote() for _ in range(8)]
    assert ray_tpu.get([c.incr.remote() for c in counters]) == [1] * 8
