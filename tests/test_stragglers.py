"""Gray-failure tolerance: health-scorer hysteresis, hedged execution
adjudication, and quarantine scheduling semantics.

Policy-level checks borrow the scorer / hedge methods off GcsServer
without starting one (the test_scheduling_policy harness idiom), so
every state machine transition is asserted deterministically; one small
live-cluster test pins the end-to-end property that a quarantined node
takes no new leases and drains back into service on readmission. The
full under-chaos behaviour (slowexec + throttle, PULL_RELEAD, head-kill
composition) lives in ray_perf's straggler_soak / make straggler-smoke.
"""
import os
import threading
import time
from collections import deque

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu._private.config import RayConfig
from ray_tpu._private.gcs import (
    GcsServer,
    NodeState,
    WorkerHandle,
    W_ACTOR,
    W_BUSY,
    W_IDLE,
    stale_node_ids,
)
from ray_tpu._private.ids import ActorID, NodeID, TaskID, WorkerID
from ray_tpu._private.task_spec import TaskSpec


@pytest.fixture(autouse=True)
def _default_config():
    # Harness tests read thresholds straight off RayConfig; make sure a
    # previous test's _system_config isn't still loaded.
    RayConfig.initialize()
    yield


# ----------------------------------------------------------- construction
def _mk_node(i, cpus=4.0):
    n = NodeState(
        node_id=NodeID(bytes([i]) * 16),
        total={"CPU": cpus},
        available={"CPU": cpus},
        conn=object(),  # only daemon nodes (with a control conn) score
    )
    n.last_heartbeat = time.monotonic()
    n.prev_heartbeat = n.last_heartbeat - 0.1
    return n


class _FakeConn:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)


def _mk_spec(name="unit", **kw):
    defaults = dict(
        task_id=TaskID(os.urandom(16)),
        name=name,
        function_id=b"\x00" * 16,
        function_blob=None,
        args_blob=b"",
        resources={"CPU": 1.0},
    )
    defaults.update(kw)
    return TaskSpec(**defaults)


def _mk_worker(node, state=W_IDLE):
    return WorkerHandle(
        worker_id=WorkerID(os.urandom(16)),
        node_id=node.node_id,
        state=state,
        conn=_FakeConn(),
    )


# ------------------------------------------------------- scorer hysteresis
class _ScorerHarness:
    """Borrows the health scorer off GcsServer without starting one."""

    _score_nodes = GcsServer._score_nodes

    def __init__(self):
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self.nodes = {}
        self._quarantine_stats = {"quarantined": 0, "readmitted": 0}

    def _update_straggler_metrics(self):
        pass

    def add(self, node):
        self.nodes[node.node_id.binary()] = node
        return node

    def sweep(self, node, *bad):
        """One scoring sweep with the given bad signals set on `node`.

        Refreshes the heartbeat first so the only degradation measured
        is what the test injects (a stale monotonic heartbeat is itself
        a bad signal)."""
        node.last_heartbeat = time.monotonic()
        node.prev_heartbeat = node.last_heartbeat - 0.1
        for attr, val in bad:
            setattr(node, attr, val)
        self._score_nodes(1.0)


def test_single_slow_sweep_never_quarantines():
    h = _ScorerHarness()
    n = h.add(_mk_node(1))
    h.sweep(n, ("hb_gap_max", 100.0))
    # One blip: EWMA moves to 1 - alpha/2, nowhere near any threshold.
    assert n.health_score == pytest.approx(0.875)
    assert not n.suspect and not n.quarantined
    for _ in range(30):
        h.sweep(n)
    assert n.health_score > 0.99
    assert not n.quarantined
    assert h._quarantine_stats["quarantined"] == 0


def test_sustained_degradation_suspects_then_quarantines():
    h = _ScorerHarness()
    n = h.add(_mk_node(1))
    # Two signals per sweep (jitter + pull re-leads) -> sample 0.0:
    # 1.0 -> .75 -> .5625 (suspect) -> .4219 -> .3164 (quarantine).
    for i in range(1, 5):
        h.sweep(n, ("hb_gap_max", 100.0), ("releads", 1))
        if i < 4:
            assert not n.quarantined, f"quarantined too early (sweep {i})"
    assert n.suspect
    assert n.quarantined
    assert n.health_score < RayConfig.health_quarantine_score
    assert h._quarantine_stats["quarantined"] == 1
    # Staying degraded doesn't re-count the transition.
    h.sweep(n, ("hb_gap_max", 100.0), ("releads", 1))
    assert h._quarantine_stats["quarantined"] == 1


def test_readmission_needs_consecutive_healthy_windows():
    h = _ScorerHarness()
    n = h.add(_mk_node(1))
    n.quarantined = True
    n.health_score = 0.9
    h.sweep(n)
    h.sweep(n)
    assert n.quarantined and n.healthy_windows == 2
    # One relapse resets the consecutive-window counter.
    h.sweep(n, ("hb_gap_max", 100.0))
    assert n.quarantined and n.healthy_windows == 0
    h.sweep(n)
    h.sweep(n)
    assert n.quarantined  # only 2 consecutive so far
    h.sweep(n)
    assert not n.quarantined and not n.suspect
    assert h._quarantine_stats["readmitted"] == 1


def test_quarantined_silent_node_still_fences():
    # Quarantine is probation, NOT the fence path: a quarantined node
    # that goes truly silent must still reach the heartbeat-timeout
    # sweep (the PR 13 incarnation fence).
    n = _mk_node(3)
    n.quarantined = True
    n.last_heartbeat = 100.0
    assert stale_node_ids(
        [n], now_mono=160.0, period_s=1.0, threshold=5
    ) == [n.node_id.binary()]


def test_connless_nodes_never_scored():
    # The head's own node and virtual/driver nodes have no heartbeat
    # stream; whatever garbage sits in their counters must not decay
    # their score.
    h = _ScorerHarness()
    n = h.add(_mk_node(1))
    n.conn = None
    h.sweep(n, ("hb_gap_max", 100.0), ("releads", 5))
    assert n.health_score == 1.0 and not n.suspect


# --------------------------------------------------------- hedge launcher
class _HedgeHarness:
    """Borrows the hedge launcher + adjudicator off GcsServer."""

    _launch_hedges = GcsServer._launch_hedges
    _dispatch_hedge = GcsServer._dispatch_hedge
    _hedge_adjudicate = GcsServer._hedge_adjudicate
    _hedge_drop_reporter = GcsServer._hedge_drop_reporter
    _task_resources = GcsServer._task_resources
    _release_task_resources = GcsServer._release_task_resources
    _node_util = GcsServer._node_util
    _pick_worker = GcsServer._pick_worker
    _packable = staticmethod(GcsServer._packable)

    def __init__(self):
        self._lock = threading.RLock()
        self.nodes = {}
        self.workers = {}
        self.placement_groups = {}
        self._hedges = {}
        self._hedge_stats = {"launched": 0, "won": 0, "cancelled": 0}
        self._exec_durations = {}

    def overrunning_primary(self, spec, node):
        w = _mk_worker(node, state=W_BUSY)
        w.current_task = spec
        w.task_started_at = time.time() - 60.0
        node.available["CPU"] -= spec.resources.get("CPU", 0)
        self.workers[w.worker_id.binary()] = w
        self._exec_durations.setdefault(spec.name, deque([0.05] * 16))
        return w

    def idle_twin(self, node):
        w = _mk_worker(node, state=W_IDLE)
        node.pool.add(w.worker_id.binary())
        self.workers[w.worker_id.binary()] = w
        return w


def _two_node_harness(suspect_primary=True):
    h = _HedgeHarness()
    na, nb = _mk_node(1), _mk_node(2)
    na.suspect = suspect_primary
    h.nodes = {na.node_id.binary(): na, nb.node_id.binary(): nb}
    return h, na, nb


def test_overrun_on_suspect_node_hedges_to_healthy_node():
    h, na, nb = _two_node_harness()
    spec = _mk_spec()
    primary = h.overrunning_primary(spec, na)
    twin = h.idle_twin(nb)
    h._launch_hedges()
    assert h._hedge_stats["launched"] == 1
    assert twin.state == W_BUSY and twin.current_task is spec
    [msg] = twin.conn.sent
    assert msg["type"] == "execute_task" and msg["hedge_seq"] == 1
    assert nb.available["CPU"] == 3.0  # duplicate lease charged
    hedge = h._hedges[spec.task_id.binary()]
    assert hedge["seqs"] == {
        primary.worker_id.binary(): None,  # pre-hedge dispatch: no echo
        twin.worker_id.binary(): 1,
    }
    assert na.overruns == 1  # scorer signal recorded too


@pytest.mark.parametrize(
    "kw",
    [
        {"actor_id": ActorID(b"\x07" * 16)},
        {"actor_creation": True},
        {"num_returns": -1},
        {"scheduling_strategy": "SPREAD"},
    ],
)
def test_pinned_and_actor_tasks_never_hedge(kw):
    h, na, nb = _two_node_harness()
    spec = _mk_spec(**kw)
    h.overrunning_primary(spec, na)
    h.idle_twin(nb)
    h._launch_hedges()
    assert not h._hedges and h._hedge_stats["launched"] == 0
    # Skipped before the overrun bump: an actor running long is not a
    # gray-failure signal (its state can't be duplicated anyway).
    assert na.overruns == 0


def test_overrun_on_healthy_node_signals_but_never_dispatches():
    h, na, nb = _two_node_harness(suspect_primary=False)
    h.overrunning_primary(_mk_spec(), na)
    h.idle_twin(nb)
    h._launch_hedges()
    assert na.overruns == 1  # bootstrap: this is how slowness surfaces
    assert not h._hedges and h._hedge_stats["launched"] == 0
    assert nb.available["CPU"] == 4.0


def test_hedge_needs_recorded_percentiles():
    h, na, nb = _two_node_harness()
    spec = _mk_spec()
    h.overrunning_primary(spec, na)
    h.idle_twin(nb)
    h._exec_durations[spec.name] = deque([0.05] * 4)  # < hedge_min_samples
    h._launch_hedges()
    assert not h._hedges and na.overruns == 0


def test_hedges_never_spawn_cold_workers():
    h, na, nb = _two_node_harness()
    h.overrunning_primary(_mk_spec(), na)  # nb has NO idle worker
    h._launch_hedges()
    assert not h._hedges
    assert nb.available["CPU"] == 4.0  # no lease charged on failure


# ----------------------------------------------------------- adjudication
def _hedged_pair(h=None):
    h = h or _HedgeHarness()
    na, nb = _mk_node(1), _mk_node(2)
    h.nodes = {na.node_id.binary(): na, nb.node_id.binary(): nb}
    spec = _mk_spec()
    primary, twin = _mk_worker(na, W_BUSY), _mk_worker(nb, W_BUSY)
    for w, node in ((primary, na), (twin, nb)):
        w.current_task = spec
        node.available["CPU"] -= 1.0
        h.workers[w.worker_id.binary()] = w
    tid = spec.task_id.binary()
    h._hedges[tid] = {
        "seqs": {primary.worker_id.binary(): None,
                 twin.worker_id.binary(): 1},
        "winner": None,
        "pending": {primary.worker_id.binary(), twin.worker_id.binary()},
    }
    return h, tid, primary, twin, na, nb


def test_first_done_wins_loser_lease_comes_home():
    h, tid, primary, twin, na, nb = _hedged_pair()
    won = h._hedge_adjudicate(tid, primary.worker_id.binary(), primary, {})
    assert won
    # Winner chosen -> the still-running twin is told to cancel.
    assert {"type": "cancel_task", "task_id": tid} in twin.conn.sent
    lost = h._hedge_adjudicate(
        tid, twin.worker_id.binary(), twin, {"hedge_seq": 1}
    )
    assert not lost
    # Exactly one side's record seals; the loser's lease is returned
    # exactly once and its worker goes back to the pool.
    assert twin.state == W_IDLE and twin.current_task is None
    assert nb.available["CPU"] == 4.0
    assert na.available["CPU"] == 3.0  # winner's lease: normal done path
    assert h._hedge_stats == {"launched": 0, "won": 1, "cancelled": 1}
    assert na.hedges_won == 1 and nb.hedges_lost == 1
    assert tid not in h._hedges  # both twins reported: entry dropped


def test_twin_beats_slow_primary():
    h, tid, primary, twin, na, nb = _hedged_pair()
    assert h._hedge_adjudicate(
        tid, twin.worker_id.binary(), twin, {"hedge_seq": 1}
    )
    assert {"type": "cancel_task", "task_id": tid} in primary.conn.sent
    assert not h._hedge_adjudicate(
        tid, primary.worker_id.binary(), primary, {}
    )
    assert primary.state == W_IDLE
    assert na.available["CPU"] == 4.0 and nb.available["CPU"] == 3.0


def test_stale_echo_fences_even_when_first_to_arrive():
    h, tid, primary, twin, na, nb = _hedged_pair()
    # A done from a worker the head never granted this task to (e.g. a
    # fenced former incarnation) can never seal, even with no winner yet.
    ghost = os.urandom(16)
    assert not h._hedge_adjudicate(tid, ghost, None, {"hedge_seq": 1})
    assert h._hedges[tid]["winner"] is None
    # A known twin echoing the wrong seq fences the same way.
    assert not h._hedge_adjudicate(
        tid, twin.worker_id.binary(), twin, {"hedge_seq": 7}
    )
    assert h._hedges[tid]["winner"] is None
    # The authentic record still wins afterwards.
    assert h._hedge_adjudicate(tid, primary.worker_id.binary(), primary, {})


def test_losing_actor_host_restores_to_actor_state():
    # A hedge twin placed on a shared actor host must hand the process
    # back to its actors, not to the fungible pool.
    h, tid, primary, twin, na, nb = _hedged_pair()
    twin.packed[b"\x07" * 16] = _mk_spec(actor_creation=True)
    h._hedge_adjudicate(tid, primary.worker_id.binary(), primary, {})
    assert not h._hedge_adjudicate(
        tid, twin.worker_id.binary(), twin, {"hedge_seq": 1}
    )
    assert twin.state == W_ACTOR
    assert nb.available["CPU"] == 4.0


def test_drop_reporter_holds_entry_until_all_twins_report():
    h, tid, primary, twin, na, nb = _hedged_pair()
    h._hedge_drop_reporter(tid, primary.worker_id.binary())
    assert tid in h._hedges  # twin still owes a report (or a death)
    h._hedge_drop_reporter(tid, twin.worker_id.binary())
    assert tid not in h._hedges


# ------------------------------------------------------------ live cluster
def test_quarantined_node_takes_no_new_leases():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.add_node(num_cpus=2, label="b")
        from ray_tpu._private.worker import _global

        gcs = _global.node.gcs
        with gcs._lock:
            b = next(
                n for n in gcs.nodes.values() if n.label == "b"
            )
            b.quarantined = True
            # Score 0.0 keeps the live scorer from readmitting it for
            # ~10 sweeps — far longer than the observation window.
            b.health_score = 0.0

        @ray_tpu.remote(num_cpus=1)
        def busy():
            time.sleep(0.8)
            return 1

        refs = [busy.remote() for _ in range(4)]
        # While the first wave runs on the head, b must stay fully idle.
        deadline = time.time() + 0.9
        while time.time() < deadline:
            with gcs._lock:
                assert b.available.get("CPU") == b.total.get("CPU")
            time.sleep(0.05)
        # list_cluster_nodes surface carries the straggler columns.
        row = next(
            r for r in ray_tpu.nodes() if r.get("label") == "b"
        )
        assert row["quarantined"] is True
        assert row["health_score"] == pytest.approx(0.0)
        assert {"hedges_won", "hedges_lost"} <= set(row)
        # Readmit: the parked half of the wave drains onto b.
        with gcs._lock:
            b.quarantined = False
            b.health_score = 1.0
            gcs._work.notify_all()
        assert ray_tpu.get(refs, timeout=30) == [1, 1, 1, 1]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
