"""Chaos engine + resilience primitives.

Reference model: python/ray/tests/test_chaos.py (resource killers over
nodes/workers) + RAY_testing_asio_delay_us (ray_config_def.h:832),
generalized here into the seeded FaultSchedule (_private/chaos.py)
woven into the transport boundary and named process kill points, plus
the shared Backoff/retry and in-order ref_flush sequencing the
hardened failure paths ride.
"""
import gc
import os
import random
import time

import pytest

import ray_tpu
from ray_tpu._private import chaos
from ray_tpu._private.chaos import (
    Backoff,
    FaultSchedule,
    InOrderSequencer,
    retry_call,
)


class _Holder:
    """Stands in for a PeerConn as the reorder hold slot."""


# ------------------------------------------------------------ determinism


def _decision_trace(schedule: FaultSchedule, mtype: str, n: int):
    return [schedule.decide(mtype) for _ in range(n)]


def test_same_seed_same_injection_sequence():
    """Acceptance: every fault rule is deterministic under a fixed
    seed — the nth decision for a message type is a pure function of
    (seed, rule, n)."""
    spec = "ref_flush=drop:0.3,pull_chunk=delay:0.5:1000:9000,x=dup:0.2"
    a = _decision_trace(FaultSchedule(spec, seed=42), "ref_flush", 200)
    b = _decision_trace(FaultSchedule(spec, seed=42), "ref_flush", 200)
    assert a == b
    assert any(d is not None for d in a)  # p=0.3 over 200 draws fires
    c = _decision_trace(FaultSchedule(spec, seed=43), "ref_flush", 200)
    assert a != c  # a different seed is a different schedule
    # Delay magnitudes are part of the deterministic stream too.
    d1 = _decision_trace(FaultSchedule(spec, seed=7), "pull_chunk", 50)
    d2 = _decision_trace(FaultSchedule(spec, seed=7), "pull_chunk", 50)
    assert d1 == d2


def test_rule_limit_and_unknown_types():
    s = FaultSchedule("a=drop:1.0@2", seed=1)
    assert [s.decide("a") is not None for _ in range(4)] == [
        True, True, False, False,
    ]
    assert s.decide("never-mentioned") is None
    with pytest.raises(ValueError):
        FaultSchedule("a=explode:1.0", seed=1)


def test_intercept_actions():
    s = FaultSchedule(
        "d=drop:1.0,u=dup:1.0,r=reorder:1.0@1,n=delay:1.0:1:1", seed=5
    )
    h = _Holder()
    assert s.intercept(h, "d", {"type": "d"}) == []
    assert s.intercept(h, "u", {"type": "u"}) == [
        {"type": "u"}, {"type": "u"},
    ]
    # Reorder: held until the NEXT message on the conn, then delivered
    # right after it (a one-slot swap).
    assert s.intercept(h, "r", {"type": "r", "i": 1}) == []
    out = s.intercept(h, "x", {"type": "x"})
    assert out == [{"type": "x"}, {"type": "r", "i": 1}]
    # A close drains anything still held — never a silent drop.
    assert s.intercept(h, "r", {"type": "r", "i": 2}) == [
        {"type": "r", "i": 2}
    ]  # @1 limit: second reorder rule application doesn't fire
    s2 = FaultSchedule("r=reorder:1.0", seed=5)
    h2 = _Holder()
    assert s2.intercept(h2, "r", {"i": 1}) == []
    assert s2.drain_held(h2) == [{"i": 1}]
    assert s2.drain_held(h2) == []


def test_kill_rule_fires_on_nth_hit(monkeypatch):
    s = FaultSchedule("kill:owner.pre_ref_flush=3", seed=9)
    killed = []
    monkeypatch.setattr(
        FaultSchedule, "_kill", lambda self: killed.append(True)
    )
    for _ in range(5):
        s.maybe_kill("owner.pre_ref_flush")
    assert len(killed) == 1  # exactly the 3rd hit
    s.maybe_kill("some.other.point")
    assert len(killed) == 1


def test_role_scoped_rules(monkeypatch):
    monkeypatch.setenv("RAY_TPU_CHAOS_ROLE", "raylet")
    s = FaultSchedule("a=drop:1.0?role=worker,b=drop:1.0?role=raylet",
                      seed=2)
    assert s.decide("a") is None  # scoped to workers; we are a raylet
    assert s.decide("b") is not None


def test_legacy_delay_spec_translation():
    s = FaultSchedule("", seed=0,
                      legacy_delay_spec="put_object=5000:5000")
    d = s.decide("put_object")
    assert d is not None and d[0] == "delay"
    assert abs(d[1] - 0.005) < 1e-9


# --------------------------------------------------------------- backoff


def test_backoff_growth_jitter_and_budget():
    bo = Backoff(base_s=0.1, cap_s=1.0, rng=random.Random(3))
    delays = [bo.next_delay() for _ in range(20)]
    assert all(d <= 1.0 for d in delays)
    assert all(d >= 0.025 for d in delays)  # base/4 floor
    # Deterministic under a seeded rng.
    bo2 = Backoff(base_s=0.1, cap_s=1.0, rng=random.Random(3))
    assert delays == [bo2.next_delay() for _ in range(20)]
    # Budget bounds total sleep.
    bo3 = Backoff(base_s=10.0, cap_s=10.0, budget_s=0.01,
                  rng=random.Random(1))
    assert bo3.next_delay() <= 0.01
    assert bo3.exhausted()
    bo3.reset()
    assert not bo3.exhausted()


def test_retry_call_retries_then_raises():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    bo = Backoff(base_s=0.001, cap_s=0.002, rng=random.Random(0))
    assert retry_call(flaky, backoff=bo) == "ok"
    assert len(calls) == 3

    def always():
        raise OSError("nope")

    with pytest.raises(OSError):
        retry_call(
            always,
            backoff=Backoff(base_s=0.001, cap_s=0.002, budget_s=0.01),
        )


# -------------------------------------------------------------- sequencer


def test_sequencer_orders_dedups_and_skips_gaps():
    sq = InOrderSequencer(gap_timeout_s=10.0)
    assert sq.offer(1, "a", now=0.0) == ["a"]
    assert sq.offer(3, "c", now=0.0) == []          # gap: buffered
    assert sq.offer(2, "b", now=0.0) == ["b", "c"]  # fills in order
    assert sq.offer(2, "b", now=0.0) == []          # duplicate
    assert sq.duplicates == 1
    # A gap that never fills is skipped after the timeout — flushed in
    # order, counted.
    assert sq.offer(6, "f", now=1.0) == []
    assert sq.offer(7, "g", now=20.0) == ["f", "g"]
    assert sq.skipped_gaps == 1
    assert sq.offer(8, "h", now=21.0) == ["h"]


def test_sequencer_baseline_is_first_seen():
    sq = InOrderSequencer()
    # Without start_seq (mid-stream attach): first seq seen is the
    # baseline, not 1.
    assert sq.offer(40, "x") == ["x"]
    assert sq.offer(41, "y") == ["y"]


def test_sequencer_start_seq_accepts_retransmitted_first_batch():
    """Regression: the FIRST batch dropped in transit must read as a
    gap awaiting its retransmit — with a first-seen baseline the later
    seq=1 retransmit would be discarded as a 'duplicate' (and its
    edges silently lost, despite having been acked)."""
    sq = InOrderSequencer(gap_timeout_s=10.0, start_seq=1)
    assert sq.offer(2, "b", now=0.0) == []   # seq 1 was dropped: gap
    assert sq.offer(1, "a", now=1.0) == ["a", "b"]  # retransmit lands
    assert sq.duplicates == 0


# ------------------------------------------------- ref_flush at-least-once


class _FakeConn:
    closed = False

    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)


class _FakeClient:
    def __init__(self):
        from ray_tpu._private.ids import WorkerID

        self.worker_id = WorkerID.from_random()
        self.conn = _FakeConn()
        self._lineage = {}

    def _wait_prune(self, oids):
        pass


def test_ref_flush_carries_seq_and_retransmits_until_acked():
    from ray_tpu._private.object_plane import owner_refs
    from ray_tpu._private.object_plane.owner_refs import OwnerRefTracker

    c = _FakeClient()
    t = OwnerRefTracker(c)
    oid = b"owned111"
    t.incr(oid, c.worker_id.binary())
    t.mark_advertised(oid)
    t.decr(oid)
    t.flush(c)
    (msg,) = c.conn.sent
    assert msg["seq"] == 1 and msg["release"] == [oid]
    # Unacked: ages past RETRANSMIT_S -> the next flush resends the
    # SAME batch (same seq — the head sequencer dedups).
    with t._lock:
        t._unacked[1][1] -= owner_refs.RETRANSMIT_S + 1
    t.flush(c)
    assert len(c.conn.sent) == 2 and c.conn.sent[1]["seq"] == 1
    assert t.stats["retransmits"] == 1
    # Ack clears it: no further resends.
    t.ack(1)
    with t._lock:
        assert not t._unacked
    t.flush(c)
    assert len(c.conn.sent) == 2
    t.stop()


def test_ref_flush_lost_batch_counted_after_max_attempts():
    from ray_tpu._private.object_plane import owner_refs
    from ray_tpu._private.object_plane.owner_refs import OwnerRefTracker

    c = _FakeClient()
    t = OwnerRefTracker(c)
    t.incr(b"borrowed", b"o" * 16)
    t.flush(c)
    with t._lock:
        t._unacked[1][2] = owner_refs.RETRANSMIT_MAX  # attempts spent
        t._unacked[1][1] -= owner_refs.RETRANSMIT_S + 1
    t.flush(c)
    with t._lock:
        assert not t._unacked
    assert t.stats["lost_batches"] == 1
    t.stop()


def test_done_batcher_retransmits_and_renumbers_on_reconnect():
    """Head failover, worker half: task_done batches are at-least-once
    (seq + ack + retransmit), and a reconnect renumbers the unacked
    tail from 1 — the restarted head's per-conn sequencer starts over,
    so the old numbering would read as a permanent gap."""
    from ray_tpu._private.worker_main import _DoneBatcher

    class _Client:
        def __init__(self):
            from ray_tpu._private.ids import WorkerID

            self.worker_id = WorkerID.from_random()
            self.conn = _FakeConn()
            self.sent = []
            self.done_ack = None
            self._conn_gen = 0

        def send(self, msg):
            self.sent.append(msg)

        def conn_failover_pending(self):
            return True

    c = _Client()
    b = _DoneBatcher(c)
    b._thread = object()  # keep the background flush loop out of this test
    assert c.done_ack == b.ack  # ack push wired at construction

    def _batches():
        return [m for m in c.sent if m.get("items")]

    b.add({"task_id": b"t1", "name": "x", "results": [], "error": None})
    b.flush()
    assert [m["seq"] for m in _batches()] == [1]
    # Unacked past the retransmit age: the next flush resends the SAME
    # batch (same seq — the head sequencer dedups), WITHOUT the
    # flight-recorder piggyback (no double ingest).
    with b._lock:
        b._unacked[1][1] -= _DoneBatcher._RETRANSMIT_S + 1
        assert "events" not in b._unacked[1][0]
    b.flush()
    assert [m["seq"] for m in _batches()] == [1, 1]
    # Second batch, first acked: a reconnect renumbers the unacked
    # tail from 1 (order preserved) and retransmits immediately.
    b.add({"task_id": b"t2", "name": "y", "results": [], "error": None})
    b.flush()
    b.ack(1)
    c.sent = []
    c._conn_gen = 1  # the client swapped to a fresh connection
    b.on_reconnect()
    resent = _batches()
    assert [m["seq"] for m in resent] == [1]
    assert resent[0]["items"][0]["task_id"] == b"t2"
    b.ack(1)
    with b._lock:
        assert not b._unacked


def test_owner_tracker_reconnect_renumbers_and_readvertises():
    """Head failover, owner half: on_reconnect renumbers unacked
    ref_flush batches, re-dirties borrowed/fallback refs so their
    edges re-send, and returns the owned-object reconcile payload
    (oid -> live borrowers) for the recovery window."""
    from ray_tpu._private.object_plane.owner_refs import OwnerRefTracker

    c = _FakeClient()
    t = OwnerRefTracker(c)
    me = c.worker_id.binary()
    owned, borrowed, other = b"o" * 16, b"b" * 16, b"x" * 16
    t.incr(owned, me)
    t.mark_advertised(owned)
    t.apply_borrow_update(b"peer1", [owned], None)  # live borrow edge
    t.incr(borrowed, other)
    t.flush(c)  # advertises `borrowed` via badd, seq 1 (never acked)
    assert [m["seq"] for m in c.conn.sent] == [1]

    c._conn_gen = 1  # the client swapped to a fresh connection
    recon = t.on_reconnect()
    assert recon == {owned: [b"peer1"]}
    with t._lock:
        assert list(t._unacked) == [1]
        assert t._unacked[1][1] == 0.0  # due immediately
        assert borrowed in t._dirty  # re-advertises on next flush
    c.conn.sent = []
    t.flush(c)
    # New batch carries the re-advertised borrow edge; the renumbered
    # unacked batch retransmits alongside it.
    new = [m for m in c.conn.sent if (other, borrowed) in m.get("badd", [])]
    assert new, f"borrow edge not re-advertised: {c.conn.sent}"
    assert any(m["seq"] == 1 for m in c.conn.sent if m is not new[0])
    t.stop()


def test_dead_borrower_late_add_ignored():
    """borrower_died sweep racing a delayed/reordered head→owner relay:
    the late add must not resurrect a borrow edge nothing will ever
    retract."""
    from ray_tpu._private.object_plane.owner_refs import OwnerRefTracker

    c = _FakeClient()
    t = OwnerRefTracker(c)
    oid = b"owned111"
    t.incr(oid, c.worker_id.binary())
    t.mark_advertised(oid)
    t.sweep_borrower(b"b" * 16)
    t.apply_borrow_update(b"b" * 16, [oid], [])  # the late relay
    assert t.stats["stale_borrow_adds"] == 1
    t.decr(oid)
    t.flush(c)
    # The release still goes out — the stale edge held nothing.
    assert any(m.get("release") == [oid] for m in c.conn.sent)
    t.stop()


# ----------------------------------------------------------- end to end


def test_chaos_delay_rule_via_system_config():
    """The chaos engine subsumes testing_rpc_delay_us: a delay rule on
    put_object visibly stretches the put round-trip. Pool disabled:
    the shm segment's put advert is async by design and never blocks,
    so the rule is only observable on the legacy synchronous path."""
    os.environ["RAY_TPU_NATIVE_STORE"] = "0"
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "chaos_spec": "put_object=delay:1.0:30000:30000",
            "chaos_seed": 11,
        },
    )
    try:
        start = time.monotonic()
        ray_tpu.get(ray_tpu.put(1))
        assert time.monotonic() - start >= 0.03
        assert chaos.active() is not None
        assert chaos.active().stats.get("delay:put_object", 0) >= 1
    finally:
        ray_tpu.shutdown()
        chaos.install("", 0)
        os.environ.pop("RAY_TPU_NATIVE_STORE", None)


def test_dropped_ref_flush_batches_still_release(monkeypatch):
    """At-least-once flush end to end: with the first TWO ref_flush
    deliveries deterministically dropped at the head's transport
    boundary, retransmission still lands the release and the entry
    frees — and the injected faults surface as CHAOS events."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "chaos_spec": "ref_flush=drop:1.0@2",
            "chaos_seed": 21,
        },
    )
    try:
        from ray_tpu._private.worker import _global, global_client

        import numpy as np

        client = global_client()
        ref = ray_tpu.put(np.zeros(300_000))
        oid = ref.id().binary()
        client._tracker.flush(client)
        del ref
        gc.collect()
        client._tracker.flush(client)
        gcs = _global.node.gcs
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if gcs.objects.get(oid) is None:
                break
            time.sleep(0.1)
        assert gcs.objects.get(oid) is None, (
            "release lost to dropped ref_flush batches",
            client._tracker.stats,
        )
        from ray_tpu.util.state import list_cluster_events

        drops = list_cluster_events(category="chaos", event="DROP",
                                    limit=10)
        assert drops, "injected drops not visible as CHAOS events"
    finally:
        ray_tpu.shutdown()
        chaos.install("", 0)


def test_oom_victim_ordering_groups_by_owner():
    """Satellite: the kill ladder's group-by-owner fairness tier — the
    job with the burst pays, not the job with one task; retriability
    and newest-first break ties inside the group."""
    from types import SimpleNamespace

    from ray_tpu._private.gcs import W_BUSY, W_LEASED, sort_oom_victims

    def w(owner, started, retries=1, state=W_BUSY):
        return SimpleNamespace(
            state=state,
            task_started_at=started,
            current_task=SimpleNamespace(
                max_retries=retries, owner_client=owner, name="t",
            ),
        )

    job_a = b"a" * 16
    job_b = b"b" * 16
    burst = [w(job_a, 10.0), w(job_a, 20.0), w(job_a, 30.0)]
    single = [w(job_b, 40.0)]
    order = sort_oom_victims(single + burst)
    # All of job A's burst dies before job B's single task is touched.
    assert [getattr(v.current_task, "owner_client") for v in order[:3]] \
        == [job_a] * 3
    assert order[0].task_started_at == 30.0  # newest in the big group
    assert order[-1].current_task.owner_client == job_b
    # Within one group, retriable ranks before non-retriable.
    mixed = [w(job_a, 10.0, retries=0), w(job_a, 5.0, retries=2)]
    order2 = sort_oom_victims(mixed)
    assert order2[0].current_task.max_retries == 2
    # Leased workers (no visible task) rank between the two.
    leased = SimpleNamespace(
        state=W_LEASED, task_started_at=50.0, current_task=None
    )
    order3 = sort_oom_victims(
        [w(job_a, 1.0, retries=0), leased]
    )
    assert order3[0].state == W_LEASED


# ------------------------------------------------------------ partitions


def test_partition_rule_grammar():
    """partition:<roleA><-><roleB>=<start>[:<heal_after>][?dir=...] —
    pair split, heal term as start+delta, and dir validation."""
    s = FaultSchedule("partition:raylet<->head=2:5?dir=a2b", seed=0)
    (rule,) = s._partition_rules
    assert (rule.role_a, rule.role_b) == ("raylet", "head")
    assert rule.start_s == 2.0
    assert rule.heal_s == 7.0  # heal_after is RELATIVE to start
    assert rule.direction == "a2b"
    # No heal term: the cut is permanent.
    s2 = FaultSchedule("partition:worker<->head=0", seed=0)
    assert s2._partition_rules[0].heal_s is None
    with pytest.raises(ValueError):
        FaultSchedule("partition:raylet=1", seed=0)  # no '<->' pair
    with pytest.raises(ValueError):
        FaultSchedule("partition:a<->b=1?dir=sideways", seed=0)


def test_partition_blocks_windows_and_direction(monkeypatch):
    """Windows are pure functions of the shared epoch env — no
    per-message RNG — so every process in the fleet agrees on when the
    cut begins and heals."""
    # Anchor the epoch 10s in the past: "now" inside the schedule ≈ 10.
    monkeypatch.setenv("RAY_TPU_chaos_epoch", str(time.time() - 10.0))
    # Active window (start 5, heal 5+100): both directions cut.
    s = FaultSchedule("partition:raylet<->head=5:100", seed=0)
    assert s.partition_blocks("raylet", "head")
    assert s.partition_blocks("head", "raylet")
    assert not s.partition_blocks("worker", "head")  # uncovered pair
    assert s.stats.get("partition:0:partition:raylet<->head=5:100") == 2
    # Not yet started (start 60): no block.
    pre = FaultSchedule("partition:raylet<->head=60", seed=0)
    assert not pre.partition_blocks("raylet", "head")
    # Already healed (start 1, heal 1+2=3 < now=10): no block, and the
    # heal edge only fires if the cut was ever observed to begin.
    healed = FaultSchedule("partition:raylet<->head=1:2", seed=0)
    assert not healed.partition_blocks("raylet", "head")
    assert "partition_heal:0:partition:raylet<->head=1:2" not in healed.stats
    # Asymmetric: a2b cuts raylet→head only; replies still flow.
    a2b = FaultSchedule("partition:raylet<->head=0?dir=a2b", seed=0)
    assert a2b.partition_blocks("raylet", "head")
    assert not a2b.partition_blocks("head", "raylet")
    b2a = FaultSchedule("partition:raylet<->head=0?dir=b2a", seed=0)
    assert not b2a.partition_blocks("raylet", "head")
    assert b2a.partition_blocks("head", "raylet")


def test_partition_begin_heal_edges_recorded(monkeypatch):
    """Transition edges surface exactly one PARTITION_BEGIN and one
    PARTITION_HEAL flight-recorder event each (plus stats), however
    many messages the window swallows."""
    from ray_tpu._private import events as _events

    monkeypatch.setenv("RAY_TPU_chaos_epoch", str(time.time() - 10.0))
    s = FaultSchedule("partition:raylet<->head=5:3", seed=0)
    rec = _events.get_recorder()
    rec.drain()
    # Force the rule through its begin edge before the heal: observe
    # the active window first by rewinding the epoch-relative clock.
    s._epoch = time.time() - 6.0  # now=6 ∈ [5, 8): active
    assert s.partition_blocks("raylet", "head")
    assert s.partition_blocks("raylet", "head")  # no second begin edge
    s._epoch = time.time() - 20.0  # now=20 ≥ 8: healed
    assert not s.partition_blocks("raylet", "head")
    assert not s.partition_blocks("raylet", "head")  # no second heal edge
    items, _ = rec.drain()
    names = [i[4] for i in items if i[2] == _events.CHAOS]
    assert names.count("PARTITION_BEGIN") == 1
    assert names.count("PARTITION_HEAL") == 1
    assert s.stats.get("partition_heal:0:partition:raylet<->head=5:3") == 1


def test_partition_blocks_module_hook(monkeypatch):
    """chaos.partition_blocks consults the installed schedule; with
    chaos off it never blocks."""
    monkeypatch.setenv("RAY_TPU_chaos_epoch", str(time.time() - 10.0))
    monkeypatch.setenv("RAY_TPU_CHAOS_ROLE", "raylet")
    chaos.install("partition:raylet<->head=0", seed=1)
    try:
        assert chaos.partition_blocks("raylet", "head")
        assert chaos.partition_blocks("head", "raylet")
        assert not chaos.partition_blocks("driver", "head")
    finally:
        chaos.install("", 0)
    assert not chaos.partition_blocks("raylet", "head")


# ------------------------------------------------- storage fault points


def test_fault_point_rules_parse_and_fire_deterministically():
    """Storage-plane fault rules (io_error:/disk_full:/truncate:) share
    the kill-rule grammar — nth-hit and probabilistic — and the same
    seeded determinism."""
    s = FaultSchedule("io_error:spill_write=2", seed=5)
    assert [s.maybe_fault("io_error:spill_write") for _ in range(4)] == [
        False, True, False, False,
    ]
    assert not s.maybe_fault("disk_full:spill")  # no rule installed
    p1 = FaultSchedule("truncate:spill_file=p:0.4", seed=11)
    p2 = FaultSchedule("truncate:spill_file=p:0.4", seed=11)
    t1 = [p1.maybe_fault("truncate:spill_file") for _ in range(100)]
    t2 = [p2.maybe_fault("truncate:spill_file") for _ in range(100)]
    assert t1 == t2 and any(t1) and not all(t1)
    p3 = FaultSchedule("truncate:spill_file=p:0.4", seed=12)
    assert t1 != [p3.maybe_fault("truncate:spill_file") for _ in range(100)]


def test_fault_point_module_hook_and_chaos_event():
    """chaos.fault_point consults the installed schedule and records a
    CHAOS FAULT flight-recorder event per injection."""
    from ray_tpu._private import events as _events

    chaos.install("disk_full:spill=1", seed=3)
    try:
        rec = _events.get_recorder()
        rec.drain()
        assert chaos.fault_point("disk_full:spill") is True
        assert chaos.fault_point("disk_full:spill") is False  # nth=1 only
        assert chaos.fault_point("io_error:spill_write") is False
        items, _ = rec.drain()
        faults = [i for i in items if i[2] == _events.CHAOS
                  and i[4] == "FAULT"]
        assert len(faults) == 1 and faults[0][3] == "disk_full:spill"
    finally:
        chaos.install("", 0)
    # chaos off: one global read, never fires.
    assert chaos.fault_point("disk_full:spill") is False


def test_spill_write_fault_points_injected(tmp_path, monkeypatch):
    """write_spill_file honors all three storage fault points: EIO,
    ENOSPC, and a post-rename truncation that read_spill_file detects
    — garbage can never restore."""
    import errno

    import numpy as np

    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import (
        SpillCorruptionError, read_spill_file, write_spill_file,
    )

    oid = ObjectID(b"s" * 16)
    payload = np.arange(1024, dtype=np.int64).tobytes()

    chaos.install("io_error:spill_write=1", seed=1)
    with pytest.raises(OSError) as ei:
        write_spill_file(str(tmp_path), oid, payload)
    assert ei.value.errno == errno.EIO

    chaos.install("disk_full:spill=1", seed=1)
    with pytest.raises(OSError) as ei:
        write_spill_file(str(tmp_path), oid, payload)
    assert ei.value.errno == errno.ENOSPC

    chaos.install("truncate:spill_file=1", seed=1)
    path = write_spill_file(str(tmp_path), oid, payload)
    with pytest.raises(SpillCorruptionError):
        read_spill_file(path)

    chaos.install("", 0)
    path = write_spill_file(str(tmp_path), oid, payload)
    assert read_spill_file(path) == payload
