"""In-jit pipeline parallelism (parallel/pipeline.py): GPipe-style
microbatch rotation over a `pipe` mesh axis, validated on the virtual
8-device CPU mesh (reference rebuild goal: SURVEY.md §2.3 — the
reference drives PP from the host via compiled DAGs; here the schedule
lives inside one SPMD program)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from ray_tpu.parallel.pipeline import (
    pipelined,
    pipeline_spec,
    sequential_reference,
    stack_stage_params,
)


def _mlp_stage(params, x):
    h = jnp.tanh(x @ params["w"] + params["b"])
    return h


def _make_stage_params(key, n_stages, d):
    per_stage = []
    for i in range(n_stages):
        k1, k2, key = jax.random.split(key, 3)
        per_stage.append(
            {
                "w": jax.random.normal(k1, (d, d)) * 0.3,
                "b": jax.random.normal(k2, (d,)) * 0.1,
            }
        )
    return per_stage


def _pipe_mesh(n):
    devs = np.array(jax.devices()[:n])
    return Mesh(devs, ("pipe",))


@pytest.mark.parametrize("n_stages,n_micro", [(4, 8), (8, 8), (4, 4)])
def test_pipelined_matches_sequential(n_stages, n_micro):
    d, mb = 16, 4
    mesh = _pipe_mesh(n_stages)
    per_stage = _make_stage_params(jax.random.PRNGKey(0), n_stages, d)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, mb, d))

    apply = jax.jit(
        pipelined(_mlp_stage, mesh=mesh, axis="pipe", n_microbatches=n_micro)
    )
    p_spec, r_spec = pipeline_spec(mesh)
    stacked = jax.device_put(stacked, p_spec)
    x_dev = jax.device_put(x, r_spec)

    got = apply(stacked, x_dev)
    want = sequential_reference(_mlp_stage, per_stage, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipelined_gradients_match_sequential():
    """jax.grad through the pipeline (transpose of ppermute = reverse
    ppermute) must equal the unpipelined gradient — the backward
    schedule falls out of the functional design, no hand-written 1F1B."""
    n_stages, n_micro, d, mb = 4, 8, 8, 2
    mesh = _pipe_mesh(n_stages)
    per_stage = _make_stage_params(jax.random.PRNGKey(2), n_stages, d)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(3), (n_micro, mb, d))

    apply = pipelined(
        _mlp_stage, mesh=mesh, axis="pipe", n_microbatches=n_micro
    )

    def loss_pipelined(params, x):
        return jnp.mean(apply(params, x) ** 2)

    def loss_sequential(per_stage, x):
        out = sequential_reference(_mlp_stage, per_stage, x)
        return jnp.mean(out ** 2)

    p_spec, r_spec = pipeline_spec(mesh)
    g_pipe = jax.jit(jax.grad(loss_pipelined))(
        jax.device_put(stacked, p_spec), jax.device_put(x, r_spec)
    )
    g_seq = jax.grad(loss_sequential)(per_stage, x)
    g_seq_stacked = stack_stage_params(g_seq)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_pipe,
        g_seq_stacked,
    )


def test_pipelined_remat_matches():
    n_stages, n_micro, d, mb = 4, 4, 8, 2
    mesh = _pipe_mesh(n_stages)
    per_stage = _make_stage_params(jax.random.PRNGKey(4), n_stages, d)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(5), (n_micro, mb, d))
    p_spec, r_spec = pipeline_spec(mesh)
    stacked_dev = jax.device_put(stacked, p_spec)
    x_dev = jax.device_put(x, r_spec)

    plain = pipelined(_mlp_stage, mesh=mesh, n_microbatches=n_micro)
    remat = pipelined(
        _mlp_stage, mesh=mesh, n_microbatches=n_micro, remat=True
    )

    def loss(f):
        return jax.jit(
            jax.grad(lambda p, x: jnp.mean(f(p, x) ** 2))
        )(stacked_dev, x_dev)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        loss(plain),
        loss(remat),
    )


def test_pipelined_wrong_microbatch_count_raises():
    mesh = _pipe_mesh(4)
    per_stage = _make_stage_params(jax.random.PRNGKey(6), 4, 8)
    stacked = stack_stage_params(per_stage)
    apply = pipelined(_mlp_stage, mesh=mesh, n_microbatches=8)
    with pytest.raises(ValueError, match="microbatch"):
        apply(stacked, jnp.zeros((4, 2, 8)))


def test_pipelined_rejects_missing_axis():
    mesh = _pipe_mesh(4)
    with pytest.raises(ValueError, match="no axis"):
        pipelined(_mlp_stage, mesh=mesh, axis="nope", n_microbatches=4)
