"""Log pipeline: worker stdout reaches the driver with (node, worker)
prefixes; `get_logs` serves the ring; dedup collapses floods.

Reference behavior: python/ray/_private/log_monitor.py +
ray_logging/__init__.py:259-294.
"""
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private.log_monitor import LogDeduplicator
from ray_tpu._private.worker import global_client


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_worker_prints_reach_driver(cluster, capfd):
    @ray_tpu.remote
    class Chatty:
        def speak(self, text):
            print(f"chatty-says {text}", flush=True)
            return text

    a = Chatty.remote()
    assert ray_tpu.get(a.speak.remote("hello-logs"), timeout=30) == "hello-logs"
    deadline = time.time() + 10
    seen = ""
    while time.time() < deadline:
        out, _ = capfd.readouterr()
        seen += out
        if "chatty-says hello-logs" in seen:
            break
        time.sleep(0.2)
    assert "chatty-says hello-logs" in seen, seen[-2000:]
    # Driver prefix carries the node and worker identity.
    line = next(l for l in seen.splitlines() if "chatty-says hello-logs" in l)
    assert line.startswith("(head worker="), line
    ray_tpu.kill(a)


def test_get_logs_ring(cluster):
    @ray_tpu.remote
    def noisy(i):
        print(f"noisy-line-{i}", flush=True)
        return i

    ray_tpu.get([noisy.remote(i) for i in range(5)])
    deadline = time.time() + 10
    lines = []
    while time.time() < deadline:
        reply = global_client().request({"type": "get_logs", "tail": 500})
        lines = [l for _, _, l in reply["lines"] if l.startswith("noisy-line-")]
        if len(set(lines)) >= 5:
            break
        time.sleep(0.2)
    assert len(set(lines)) >= 5, lines


def test_dedup_collapses_repeats():
    d = LogDeduplicator(window_s=60.0)
    entries = [("n", f"w{i}", "same warning") for i in range(50)]
    out = d.filter(entries)
    assert len(out) == 1  # 49 suppressed inside the window
    out2 = d.filter([("n", "w0", "different line")])
    assert [e[2] for e in out2] == ["different line"]
