"""bench.py's external watchdog: a wedged TPU relay can block the main
process inside a C call HOLDING the GIL, starving every in-process
timer — only a separate watchdog process can still get the one JSON
line onto stdout for the driver (observed in round 5: a bench run sat
40 minutes past its in-process deadline)."""
import json
import os
import signal
import subprocess
import sys
import time


def _load_watchdog_src():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "benchmod",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    # Executing bench.py top-level is safe: it only defines things.
    spec.loader.exec_module(mod)
    return mod._WATCHDOG_SRC


def test_external_watchdog_emits_partial_and_kills(tmp_path):
    src = _load_watchdog_src()
    partial = tmp_path / "partial.json"
    done = tmp_path / "done"
    partial.write_text(json.dumps(
        {"metric": "llama", "value": 123.0, "unit": "tok/s",
         "vs_baseline": 1.5}
    ))
    # A "main" process wedged forever (stand-in for a GIL-held C call).
    victim = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(600)"])
    out = subprocess.run(
        [sys.executable, "-c", src, str(victim.pid), str(partial),
         str(done), "3"],
        capture_output=True,
        text=True,
        timeout=60,
    )
    line = json.loads(out.stdout.strip())
    assert line["value"] == 123.0
    assert "external_watchdog" in line["error"]
    # The wedged process was killed.
    assert victim.wait(timeout=30) == -signal.SIGKILL


def test_external_watchdog_silent_when_parent_exits(tmp_path):
    src = _load_watchdog_src()
    partial = tmp_path / "partial.json"
    partial.write_text("{}")
    victim = subprocess.Popen([sys.executable, "-c", "pass"])
    victim.wait(timeout=30)  # reaped: the pid is truly gone (in real
    # use the driver shell reaps bench.py promptly)
    out = subprocess.run(
        [sys.executable, "-c", src, str(victim.pid), str(partial),
         str(tmp_path / 'done'), "30"],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert out.stdout.strip() == ""  # clean exit: no duplicate line


def test_external_watchdog_respects_done_marker(tmp_path):
    src = _load_watchdog_src()
    partial = tmp_path / "partial.json"
    partial.write_text("{}")
    done = tmp_path / "done"
    done.write_text("")  # main already printed its line
    victim = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(600)"])
    try:
        out = subprocess.run(
            [sys.executable, "-c", src, str(victim.pid), str(partial),
             str(done), "3"],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert out.stdout.strip() == ""
    finally:
        victim.kill()
