"""Multi-process crash/stress coverage for the C++ pool store.

Reference behavior: the plasma store's ASAN/TSAN CI (.bazelrc:104-126)
and crash-resilience — a client SIGKILLed mid-operation (possibly
holding the process-shared robust mutex) must not corrupt or deadlock
the pool. The heavy loop lives in native/stress_main.cpp; `make
stress-asan && store_stress_asan 100 4` is the full sanitizer run
(passes 100 rounds); this test builds and runs a bounded slice.
"""
import os
import shutil
import subprocess

import pytest

NATIVE = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
BUILD = os.path.join(
    os.path.dirname(os.path.dirname(__file__)),
    "ray_tpu", "_private", "_native",
)

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)


def _build(target: str, binary: str) -> str:
    subprocess.run(
        ["make", target], cwd=NATIVE, check=True, capture_output=True
    )
    path = os.path.join(BUILD, binary)
    assert os.path.exists(path)
    return path


def test_stress_survives_sigkill_mid_operation():
    path = _build("stress", "store_stress")
    out = subprocess.run(
        [path, "10", "4"], capture_output=True, timeout=300
    )
    assert out.returncode == 0, out.stderr.decode()
    assert b"stress OK" in out.stdout


def test_stress_asan_clean():
    path = _build("stress-asan", "store_stress_asan")
    out = subprocess.run(
        [path, "10", "4"], capture_output=True, timeout=600
    )
    assert out.returncode == 0, out.stderr.decode()
    assert b"AddressSanitizer" not in out.stderr
    assert b"stress OK" in out.stdout
