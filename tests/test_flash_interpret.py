"""Pallas flash kernels vs the XLA oracle, on CPU via interpret mode.

The kernels normally run only on real TPU; interpret mode executes the
same kernel code (including the causal block-skip control flow added
for long-context perf) bit-accurately on CPU, so CI covers fwd+bwd
numerics without a chip.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention_reference, flash_attention


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    # Scoped per-test so interpret mode never leaks into later-collected
    # test modules (which must exercise the compiled path on real TPU).
    monkeypatch.setenv("RAY_TPU_PALLAS_INTERPRET", "1")


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), jnp.float32)


@pytest.mark.parametrize(
    "tq,tk,bq,bk,causal",
    [
        (512, 512, 128, 128, True),   # 4x4 grid: skip logic active
        (512, 512, 128, 256, True),   # uneven q/k blocks across diagonal
        (384, 512, 128, 128, True),   # tq != tk (kv-cache decode chunk)
        (512, 512, 128, 128, False),  # no skipping path
        (500, 500, 128, 128, True),   # padded tails
    ],
)
def test_flash_fwd_bwd_matches_reference(tq, tk, bq, bk, causal):
    B, H, D = 1, 2, 64
    q = _rand((B, H, tq, D), 0)
    k = _rand((B, H, tk, D), 1)
    v = _rand((B, H, tk, D), 2)

    def f_flash(q, k, v):
        return flash_attention(
            q, k, v, causal=causal, block_q=bq, block_k=bk,
            force_pallas=True,
        ).sum()

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=causal).sum()

    o_flash = flash_attention(
        q, k, v, causal=causal, block_q=bq, block_k=bk, force_pallas=True
    )
    o_ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(o_flash), np.asarray(o_ref), atol=2e-3, rtol=2e-3
    )

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3
        )


def test_flash_gqa_heads():
    B, H, HKV, T, D = 1, 4, 2, 256, 64
    q = _rand((B, H, T, D), 3)
    k = _rand((B, HKV, T, D), 4)
    v = _rand((B, HKV, T, D), 5)
    o_flash = flash_attention(
        q, k, v, causal=True, block_q=128, block_k=128, force_pallas=True
    )
    o_ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(o_flash), np.asarray(o_ref), atol=2e-3, rtol=2e-3
    )


def test_causal_rejects_more_queries_than_keys():
    q = _rand((1, 2, 256, 64), 6)
    k = _rand((1, 2, 128, 64), 7)
    v = _rand((1, 2, 128, 64), 8)
    with pytest.raises(ValueError, match="Tq <= Tk"):
        flash_attention(q, k, v, causal=True, force_pallas=True)
