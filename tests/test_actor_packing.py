"""Shared actor hosts: sub-core actors pack many per worker process.

Declaring 0 < num_cpus < 1 opts an actor into co-hosting (the creation
routes to a shared host instead of booting a dedicated interpreter —
gcs._packable / _pick_worker). Reference contrast: the reference is
strictly process-per-actor (worker_pool.cc) and pays a process boot per
actor; sub-core packing is what makes many-tiny-coordinator patterns
(RL actors, serve replicas to one chip) cheap on small hosts.
"""
import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=0.05)
class Tiny:
    def __init__(self, tag=0):
        self.tag = tag
        self.n = 0

    def pid(self):
        return os.getpid()

    def incr(self):
        self.n += 1
        return self.n

    def whoami(self):
        return (self.tag, self.n)


def test_subcore_actors_share_processes(cluster):
    actors = [Tiny.remote(i) for i in range(12)]
    pids = ray_tpu.get([a.pid.remote() for a in actors])
    # 12 sub-core actors must not boot 12 interpreters.
    assert len(set(pids)) < 6, f"expected packing, got {len(set(pids))} procs"
    # Each actor keeps its own isolated state.
    for _ in range(3):
        ray_tpu.get([a.incr.remote() for a in actors])
    for i, a in enumerate(actors):
        assert ray_tpu.get(a.whoami.remote()) == (i, 3)


def test_default_actors_keep_dedicated_processes(cluster):
    @ray_tpu.remote
    class Plain:
        def pid(self):
            return os.getpid()

    plains = [Plain.remote() for _ in range(3)]
    pids = ray_tpu.get([p.pid.remote() for p in plains])
    assert len(set(pids)) == 3  # process-per-actor isolation preserved


def test_kill_packed_actor_spares_cohosted(cluster):
    actors = [Tiny.remote(i) for i in range(6)]
    pids = ray_tpu.get([a.pid.remote() for a in actors])
    assert len(set(pids)) < 6
    victim, survivors = actors[0], actors[1:]
    ray_tpu.kill(victim)
    # kill is asynchronous (reference ray.kill semantics): a direct-route
    # call racing the terminate can still land; poll until death sticks.
    deadline = time.time() + 30
    while True:
        try:
            ray_tpu.get(victim.incr.remote(), timeout=30)
        except RayActorError:
            break
        assert time.time() < deadline, "victim never died"
        time.sleep(0.1)
    # Same-process neighbors unaffected.
    assert ray_tpu.get([s.incr.remote() for s in survivors]) == [1] * 5


def test_packed_actor_graceful_exit_keeps_host(cluster):
    actors = [Tiny.remote(i) for i in range(4)]
    ray_tpu.get([a.pid.remote() for a in actors])
    ray_tpu.kill(actors[0], no_restart=True)
    time.sleep(0.2)
    # Host still serves the rest; a fresh packable actor reuses it.
    fresh = Tiny.remote(99)
    assert ray_tpu.get(fresh.whoami.remote(), timeout=60) == (99, 0)
    assert ray_tpu.get([a.incr.remote() for a in actors[1:]]) == [1, 1, 1]


def test_packed_creation_failure_spares_host(cluster):
    @ray_tpu.remote(num_cpus=0.05)
    class Boom:
        def __init__(self):
            raise RuntimeError("bad init")

        def ping(self):
            return 1

    ok = [Tiny.remote(i) for i in range(3)]
    ray_tpu.get([a.pid.remote() for a in ok])
    b = Boom.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.ping.remote(), timeout=60)
    # Co-hosted actors survived the failed construction.
    assert ray_tpu.get([a.incr.remote() for a in ok]) == [1, 1, 1]


def test_packed_actor_creation_throughput(cluster):
    """The point of packing: creation rate no longer pays a process boot
    per actor. Very conservative floor (the 1-core CI host does ~300/s)."""
    warm = Tiny.remote()
    ray_tpu.get(warm.pid.remote())
    n = 30
    t0 = time.perf_counter()
    actors = [Tiny.remote(i) for i in range(n)]
    ray_tpu.get([a.pid.remote() for a in actors], timeout=300)
    rate = n / (time.perf_counter() - t0)
    assert rate > 25, f"packed creation rate {rate:.1f}/s"
