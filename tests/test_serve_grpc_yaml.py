"""Serve gRPC ingress + declarative YAML deploy.

Reference behavior: serve/_private/proxy.py:540 (gRPCProxy) and
serve/schema.py + `serve deploy` (declarative config with in-place
reconciliation — replica count changes without downtime).
"""
import pickle
import sys
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_session():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_grpc_ingress_roundtrip(serve_session):
    import grpc

    serve.start(
        proxy=False, grpc_options=serve.GRPCOptions(host="127.0.0.1", port=0)
    )
    # Port 0: read the bound port back from the proxy actor.
    grpc_actor = ray_tpu.get_actor("SERVE_PROXY::grpc")
    addr = ray_tpu.get(grpc_actor.ready.remote(), timeout=30)

    @serve.deployment
    class Scorer:
        def __call__(self, x):
            return {"score": x * 2}

        def describe(self):
            return "scorer-v1"

    serve.run(Scorer.bind(), name="scoring", route_prefix=None)

    channel = grpc.insecure_channel(addr)
    call = channel.unary_unary("/scoring/__call__")
    reply = pickle.loads(call(pickle.dumps(((21,), {})), timeout=30))
    assert reply == {"score": 42}

    # Method routing via the path.
    describe = channel.unary_unary("/scoring/describe")
    assert pickle.loads(describe(pickle.dumps(((), {})), timeout=30)) == "scorer-v1"

    # Metadata-based routing with an arbitrary method path.
    generic = channel.unary_unary("/ray_tpu.serve.Serve/Call")
    reply = pickle.loads(
        generic(
            pickle.dumps(((5,), {})),
            metadata=(("application", "scoring"),),
            timeout=30,
        )
    )
    assert reply == {"score": 10}

    # Unknown app -> NOT_FOUND.
    with pytest.raises(grpc.RpcError) as err:
        channel.unary_unary("/nope/__call__")(pickle.dumps(((), {})), timeout=30)
    assert err.value.code() == grpc.StatusCode.NOT_FOUND
    channel.close()
    serve.delete("scoring")


def test_yaml_deploy_and_zero_downtime_rescale(serve_session, tmp_path):
    # An importable module holding the bound application.
    mod = tmp_path / "echo_app_mod.py"
    mod.write_text(
        textwrap.dedent(
            """
            from ray_tpu import serve

            @serve.deployment
            class Echo:
                def __call__(self, x):
                    return f"echo:{x}"

            app = Echo.bind()
            """
        )
    )
    sys.path.insert(0, str(tmp_path))
    try:
        config = tmp_path / "serve_config.yaml"
        config.write_text(
            textwrap.dedent(
                """
                applications:
                  - name: echo
                    route_prefix: null
                    import_path: echo_app_mod:app
                    deployments:
                      - name: Echo
                        num_replicas: 1
                """
            )
        )
        serve.deploy_config(str(config))
        handle = serve.get_app_handle("echo")
        assert handle.remote("a").result(timeout_s=30) == "echo:a"
        statuses = serve.status()
        assert statuses["echo"].deployments["Echo"].num_replicas == 1

        # Redeploy with 2 replicas; requests keep succeeding throughout.
        config.write_text(
            config.read_text().replace("num_replicas: 1", "num_replicas: 2")
        )
        import threading

        stop = threading.Event()
        failures = []

        def hammer():
            i = 0
            while not stop.is_set():
                try:
                    r = handle.remote(i).result(timeout_s=30)
                    assert r == f"echo:{i}"
                except Exception as e:  # noqa: BLE001
                    failures.append(e)
                i += 1
                time.sleep(0.05)

        t = threading.Thread(target=hammer)
        t.start()
        try:
            serve.deploy_config(str(config))
            deadline = time.time() + 60
            while time.time() < deadline:
                if serve.status()["echo"].deployments["Echo"].num_replicas == 2:
                    break
                time.sleep(0.2)
            assert (
                serve.status()["echo"].deployments["Echo"].num_replicas == 2
            ), "rescale to 2 replicas never happened"
        finally:
            stop.set()
            t.join(timeout=30)
        assert not failures, f"requests failed during redeploy: {failures[:3]}"
        serve.delete("echo")
    finally:
        sys.path.remove(str(tmp_path))
