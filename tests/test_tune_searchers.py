"""Native TPE / BOHB / Repeater searchers.

Reference behavior being matched: tune/search/hyperopt (TPE),
tune/search/bohb (BOHB), tune/search/repeater.py. The acceptance bar:
model-based search beats random search on a deterministic analytic
objective at equal trial budgets.
"""
import random

import pytest

from ray_tpu.tune import (
    BOHBSearch,
    ConcurrencyLimiter,
    Repeater,
    Searcher,
    TPESearch,
    uniform,
)
from ray_tpu.tune.search import BasicVariantGenerator, resolve_config

SPACE = {"x": uniform(-1.0, 1.0), "y": uniform(-1.0, 1.0)}


def branin_ish(cfg):
    # Smooth, deterministic, single optimum at (0.7, -0.3).
    return (cfg["x"] - 0.7) ** 2 + (cfg["y"] + 0.3) ** 2


def _run(searcher, n_trials, objective):
    searcher.set_search_properties("loss", "min", dict(SPACE))
    best = float("inf")
    for i in range(n_trials):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        loss = objective(cfg)
        best = min(best, loss)
        searcher.on_trial_complete(tid, {"loss": loss})
    return best


def _random_best(n_trials, seed, objective):
    rng = random.Random(seed)
    return min(
        objective(resolve_config(dict(SPACE), rng)) for _ in range(n_trials)
    )


def test_tpe_beats_random():
    n = 60
    tpe_best = _run(TPESearch(seed=0), n, branin_ish)
    rand_best = min(_random_best(n, s, branin_ish) for s in (0, 1, 2))
    assert tpe_best < rand_best, (tpe_best, rand_best)
    assert tpe_best < 0.01  # actually near the optimum


def test_tpe_categorical_and_int():
    from ray_tpu.tune import choice, randint

    space = {"opt": choice(["adam", "sgd", "lion"]), "layers": randint(1, 9)}

    def obj(cfg):
        return (0.0 if cfg["opt"] == "lion" else 1.0) + abs(cfg["layers"] - 6)

    searcher = TPESearch(seed=1, min_observations=6)
    searcher.set_search_properties("loss", "min", space)
    best_cfg, best = None, float("inf")
    for i in range(50):
        cfg = searcher.suggest(f"t{i}")
        loss = obj(cfg)
        if loss < best:
            best, best_cfg = loss, cfg
        searcher.on_trial_complete(f"t{i}", {"loss": loss})
    assert best == 0.0 and best_cfg["opt"] == "lion"


def test_bohb_uses_highest_informative_budget():
    """Multi-fidelity: low-budget results are misleading (optimum
    shifted); BOHB must model the highest budget once populated and
    still find the true optimum — and beat random."""

    def staged(cfg, iters):
        if iters < 3:  # low fidelity lies about the optimum
            return (cfg["x"] + 0.5) ** 2 + (cfg["y"] - 0.5) ** 2
        return branin_ish(cfg)

    bohb = BOHBSearch(seed=0, min_observations=6)
    bohb.set_search_properties("loss", "min", dict(SPACE))
    best = float("inf")
    n = 60
    for i in range(n):
        tid = f"t{i}"
        cfg = bohb.suggest(tid)
        for it in (1, 3):  # two fidelities per trial
            bohb.on_trial_result(
                tid, {"loss": staged(cfg, it), "training_iteration": it}
            )
        final = staged(cfg, 3)
        best = min(best, final)
        bohb.on_trial_complete(
            tid, {"loss": final, "training_iteration": 3}
        )
    rand_best = min(_random_best(n, s, branin_ish) for s in (0, 1, 2))
    assert best < rand_best, (best, rand_best)
    assert best < 0.01


def test_repeater_reports_mean_to_wrapped_searcher():
    class Recording(Searcher):
        def __init__(self):
            super().__init__("loss", "min")
            self.completed = []
            self._i = 0

        def suggest(self, trial_id):
            self._i += 1
            return {"x": self._i}

        def on_trial_complete(self, trial_id, result=None, error=False):
            self.completed.append((trial_id, result, error))

    inner = Recording()
    rep = Repeater(inner, repeat=3)
    rep.set_search_properties("loss", "min", dict(SPACE))
    # One config, three trials, mean of the three losses reported once.
    cfgs = [rep.suggest(f"t{k}") for k in range(3)]
    assert cfgs[0] == cfgs[1] == cfgs[2]
    for k, loss in enumerate([1.0, 2.0, 6.0]):
        rep.on_trial_complete(f"t{k}", {"loss": loss})
    assert len(inner.completed) == 1
    tid, result, error = inner.completed[0]
    assert not error and result["loss"] == pytest.approx(3.0)
    # The next suggest starts a fresh group with a new config.
    assert rep.suggest("t3") == {"x": 2}


def test_repeater_under_concurrency_limiter():
    tpe = TPESearch(seed=3, min_observations=4)
    rep = ConcurrencyLimiter(Repeater(tpe, repeat=2), max_concurrent=2)
    rep.set_search_properties("loss", "min", dict(SPACE))
    c0 = rep.suggest("a")
    c1 = rep.suggest("b")
    assert c0 == c1  # same group
    assert rep.suggest("c") is Searcher.BACKOFF  # limiter holds
    rep.on_trial_complete("a", {"loss": branin_ish(c0)})
    rep.on_trial_complete("b", {"loss": branin_ish(c1)})
    c2 = rep.suggest("c")
    assert c2 is not Searcher.BACKOFF and c2 is not None


def test_tpe_through_tuner_end_to_end(tmp_path):
    """TPE drives real trials through the Tuner/controller; num_samples
    caps an explicit searcher (reference: tune.py semantics)."""
    import ray_tpu
    from ray_tpu import tune

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        def objective(config):
            tune.report(
                {"loss": (config["x"] - 0.7) ** 2 + (config["y"] + 0.3) ** 2}
            )

        results = tune.Tuner(
            objective,
            param_space={"x": tune.uniform(-1, 1), "y": tune.uniform(-1, 1)},
            tune_config=tune.TuneConfig(
                metric="loss", mode="min", num_samples=25,
                search_alg=TPESearch(seed=0, min_observations=5),
            ),
            run_config=ray_tpu.train.RunConfig(
                storage_path=str(tmp_path), name="tpe"
            ),
        ).fit()
        assert len(results) == 25
        best = results.get_best_result()
        assert best.metrics["loss"] < 0.15
    finally:
        ray_tpu.shutdown()


def test_repeater_sequential_execution_still_repeats():
    """max_concurrent=1 shape: the lead completes before any sibling is
    suggested — the group must stay open and still collect `repeat`
    evaluations (regression: early finalize with a 1-sample mean)."""
    class Recording(Searcher):
        def __init__(self):
            super().__init__("loss", "min")
            self.completed = []
            self._i = 0

        def suggest(self, trial_id):
            self._i += 1
            return {"x": self._i}

        def on_trial_complete(self, trial_id, result=None, error=False):
            self.completed.append(result)

    inner = Recording()
    rep = Repeater(inner, repeat=3)
    rep.set_search_properties("loss", "min", dict(SPACE))
    losses = iter([1.0, 3.0, 8.0])
    cfgs = []
    for k in range(3):  # strictly sequential: suggest -> complete
        cfgs.append(rep.suggest(f"t{k}"))
        rep.on_trial_complete(f"t{k}", {"loss": next(losses)})
    assert cfgs[0] == cfgs[1] == cfgs[2]
    assert len(inner.completed) == 1
    assert inner.completed[0]["loss"] == pytest.approx(4.0)


def test_queue_searcher_not_capped_by_default_num_samples(tmp_path):
    """An explicit queue-based searcher's own budget wins over the
    TuneConfig num_samples default of 1; model-based searchers are
    capped at num_samples (regression: cap applied to all)."""
    from ray_tpu.tune.tune_controller import TuneController

    def make(alg, **kw):
        return TuneController(
            lambda cfg: None,
            param_space=dict(SPACE),
            metric="loss",
            mode="min",
            search_alg=alg,
            experiment_dir=str(tmp_path / "exp"),
            # Explicit: the default consults cluster_resources(), which
            # would auto-init (and leak) a cluster in this unit test.
            max_concurrent_trials=1,
            **kw,
        )

    gen = BasicVariantGenerator(num_samples=5)
    assert make(gen)._max_trials is None
    wrapped = ConcurrencyLimiter(BasicVariantGenerator(num_samples=5), 2)
    assert make(wrapped)._max_trials is None
    assert make(TPESearch(seed=0), num_samples=7)._max_trials == 7
