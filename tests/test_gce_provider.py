"""GCE-shaped provider (autoscaler/gce.py) against recorded API
fixtures: async operation polling, the real error taxonomy (quota 403,
stockout-in-operation, 409 adopt, 404 idempotent delete, 429 backoff),
and atomic TPU-slice rollback — plus the v2 reconciler's retry contract
on provider failures (reference: gcp/node_provider.py behavior)."""
import json
import os

import pytest

from ray_tpu.autoscaler.gce import (
    ALREADY_EXISTS,
    GceApiError,
    GceCompute,
    GceNodeProvider,
    NOT_FOUND,
    QUOTA_EXCEEDED,
    STOCKOUT,
)
from ray_tpu.autoscaler.v2 import (
    ALLOCATION_FAILED,
    Instance,
    QUEUED,
    Reconciler,
    REQUESTED,
    TERMINATED,
)

FIXTURES = json.load(
    open(os.path.join(os.path.dirname(__file__), "fixtures/gce/responses.json"))
)


def fx(key: str, **subs) -> dict:
    """Instantiate a recorded response with concrete names."""
    blob = json.dumps(FIXTURES[key])
    for k, v in subs.items():
        blob = blob.replace("{%s}" % k, str(v))
    return json.loads(blob)


def _api_error(key: str, **subs) -> GceApiError:
    body = fx(key, **subs)["error"]
    return GceApiError(
        body["code"], body["errors"][0]["reason"], body["message"]
    )


class FixtureGce(GceCompute):
    """Replays recorded responses. Mutations create pending operations
    that advance PENDING -> RUNNING -> DONE across get_operation polls
    (GCE mutations are async); tests inject error fixtures per call."""

    def __init__(self, cluster="c1", zone="us-central1-b"):
        self.cluster = cluster
        self.zone = zone
        self.vms: dict = {}
        self.tpus: dict = {}
        self.ops: dict = {}
        self.calls: list = []
        self.inject: dict = {}  # method name -> GceApiError (once)

    def _maybe_fail(self, method: str):
        err = self.inject.pop(method, None)
        if err is not None:
            raise err

    def _new_op(self, name: str, on_done, error_fixture=None) -> dict:
        op = fx("operation_pending", opname=name, zone=self.zone, name=name)
        self.ops[op["name"]] = {
            "polls": 0, "on_done": on_done, "error_fixture": error_fixture,
        }
        return op

    def insert_instance(self, zone, body):
        self.calls.append(("insert_instance", body["name"]))
        self._maybe_fail("insert_instance")
        name = body["name"]
        return self._new_op(
            name, lambda: self.vms.__setitem__(name, body)
        )

    def delete_instance(self, zone, name):
        self.calls.append(("delete_instance", name))
        self._maybe_fail("delete_instance")
        if name not in self.vms:
            raise _api_error("error_not_found", zone=zone, name=name)
        return self._new_op(f"del-{name}", lambda: self.vms.pop(name, None))

    def list_instances(self, zone, label_filter):
        out = []
        for name, body in self.vms.items():
            vm = fx(
                "instance_running",
                name=name,
                zone=zone,
                cluster=body["labels"]["ray-cluster-name"],
                node_type=body["labels"]["ray-node-type"],
            )
            if all(vm["labels"].get(k) == v for k, v in label_filter.items()):
                out.append(vm)
        return out

    def get_operation(self, zone, op_name):
        st = self.ops[op_name]
        st["polls"] += 1
        if st["polls"] == 1:
            out = fx("operation_running")
        elif st["error_fixture"]:
            out = fx(st["error_fixture"], zone=zone)
        else:
            st["on_done"]()
            out = fx("operation_done")
        out["name"] = op_name
        return out

    # ------------------------------------------------------------- TPU
    def create_tpu_node(self, zone, node_id, body):
        self.calls.append(("create_tpu_node", node_id))
        self._maybe_fail("create_tpu_node")
        err_fx = self.inject.pop("tpu_operation_error", None)
        return self._new_op(
            node_id,
            lambda: self.tpus.__setitem__(node_id, body),
            error_fixture=err_fx,
        )

    def delete_tpu_node(self, zone, node_id):
        self.calls.append(("delete_tpu_node", node_id))
        if node_id not in self.tpus and not self.inject.pop(
            "tpu_delete_exists", None
        ):
            raise _api_error("error_not_found", zone=zone, name=node_id)
        return self._new_op(
            f"del-{node_id}", lambda: self.tpus.pop(node_id, None)
        )

    def list_tpu_nodes(self, zone, label_filter):
        out = []
        for name, body in self.tpus.items():
            node = fx(
                "tpu_node_ready",
                name=name,
                zone=zone,
                cluster=body["labels"]["ray-cluster-name"],
                node_type=body["labels"]["ray-node-type"],
            )
            if all(node["labels"].get(k) == v for k, v in label_filter.items()):
                out.append(node)
        return out

    def get_tpu_operation(self, op_name):
        return self.get_operation(self.zone, op_name)


TEMPLATES = {
    "cpu8": {"machine_type": "n2-standard-8"},
    "v5e-16": {"accelerator_type": "v5litepod-16", "hosts": 2},
}


def _provider(api=None):
    api = api or FixtureGce()
    return api, GceNodeProvider(
        api,
        cluster_name=api.cluster,
        zone=api.zone,
        node_type_templates=TEMPLATES,
    )


def _inst(node_type="cpu8", iid="i-0001", hosts=1) -> Instance:
    return Instance(
        instance_id=iid, node_type=node_type, resources={"CPU": 8},
        hosts=hosts,
    )


def test_launch_polls_operation_to_done_and_lists_running():
    api, p = _provider()
    cloud_id = p.launch(_inst())
    assert cloud_id == "ray-c1-i-0001"
    # The mutation was async: at least one RUNNING poll happened.
    assert any(st["polls"] >= 2 for st in api.ops.values())
    running = p.running_instances()
    assert cloud_id in running
    assert running[cloud_id]["node_type"] == "cpu8"


def test_quota_error_is_typed_and_retryable():
    api, p = _provider()
    api.inject["insert_instance"] = _api_error(
        "error_quota", zone=api.zone, name="x"
    )
    with pytest.raises(GceApiError) as ei:
        p.launch(_inst())
    assert ei.value.reason == QUOTA_EXCEEDED
    assert ei.value.retryable


def test_rate_limit_is_retryable_bad_request_is_not():
    assert _api_error("error_rate_limited", zone="z", name="n").retryable
    assert not GceApiError(400, "invalid", "bad template").retryable


def test_already_exists_adopts_instance():
    """A retried launch whose first insert succeeded (lost response)
    adopts the live VM instead of failing — names are deterministic."""
    api, p = _provider()
    p.launch(_inst())
    api.inject["insert_instance"] = _api_error(
        "error_already_exists", zone=api.zone, name="ray-c1-i-0001"
    )
    assert p.launch(_inst()) == "ray-c1-i-0001"


def test_terminate_is_idempotent_on_404():
    api, p = _provider()
    cid = p.launch(_inst())
    p.terminate(cid)
    assert cid not in api.vms
    p.terminate(cid)  # second delete hits 404: swallowed


def test_tpu_slice_stockout_rolls_back_whole_node():
    """Stockouts surface on the DONE operation, not the create call;
    the half-provisioned node must be deleted before the error
    propagates (atomic slices never leak quota)."""
    api, p = _provider()
    api.inject["tpu_operation_error"] = "operation_done_stockout"
    api.inject["tpu_delete_exists"] = True  # node exists half-made
    with pytest.raises(GceApiError) as ei:
        p.launch(_inst("v5e-16", iid="i-tpu1", hosts=2))
    assert ei.value.reason == STOCKOUT
    assert ei.value.retryable
    assert ("delete_tpu_node", "ray-c1-i-tpu1") in api.calls
    assert "ray-c1-i-tpu1" not in api.tpus


def test_tpu_slice_launch_and_list():
    api, p = _provider()
    cid = p.launch(_inst("v5e-16", iid="i-tpu2", hosts=2))
    running = p.running_instances()
    assert running[cid] == {
        "kind": "tpu", "node_type": "v5e-16", "hosts": 2,
    }
    p.terminate(cid)
    assert cid not in api.tpus


def test_listing_filters_foreign_clusters():
    api, p = _provider()
    p.launch(_inst())
    # A VM belonging to another ray cluster in the same zone/project.
    api.vms["ray-other-i-9"] = {
        "name": "ray-other-i-9",
        "labels": {"ray-cluster-name": "other", "ray-node-type": "cpu8"},
    }
    assert set(p.running_instances()) == {"ray-c1-i-0001"}


def test_reconciler_retries_provider_failure_with_budget():
    """The v2 reconciler's contract on a raising provider: REQUESTED ->
    ALLOCATION_FAILED, re-QUEUED up to max_launch_attempts, then
    TERMINATED (reference: instance_manager retry budget)."""
    api, p = _provider()
    r = Reconciler(
        {"cpu8": {"resources": {"CPU": 8}}}, p, max_launch_attempts=2
    )
    inst = r.im.create("cpu8", {"CPU": 8})
    api.inject["insert_instance"] = _api_error(
        "error_quota", zone=api.zone, name="x"
    )
    r._launch(inst)
    assert inst.status == ALLOCATION_FAILED
    r._sync_cloud({}, now=0.0)
    assert inst.status == QUEUED
    api.inject["insert_instance"] = _api_error(
        "error_quota", zone=api.zone, name="x"
    )
    r._launch(inst)
    assert inst.status == ALLOCATION_FAILED
    r._sync_cloud({}, now=0.0)
    assert inst.status == TERMINATED  # budget exhausted

    # And a clean retry path: fresh instance launches on attempt 2.
    inst2 = r.im.create("cpu8", {"CPU": 8})
    api.inject["insert_instance"] = _api_error(
        "error_rate_limited", zone=api.zone, name="x"
    )
    r._launch(inst2)
    r._sync_cloud({}, now=0.0)
    assert inst2.status == QUEUED
    r._launch(inst2)
    assert inst2.status == REQUESTED
    assert inst2.cloud_instance_id in p.running_instances()
