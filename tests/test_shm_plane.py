"""Shared-memory object plane: cross-process refcounts, crash sweep,
spill-ladder handoff (PR 12 tentpole).

Models the reference's plasma crash tests: a SIGKILLed client must
never leak refcounts (its ledger is swept), a client killed mid-put
must never produce a sealed object (partials are freed), and a mapped
reader in ANOTHER process must pin an object against eviction until it
dies or releases.
"""
import hashlib
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.native_store import PoolStore, native_available
from ray_tpu._private.object_store import ObjectStore

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native store did not build"
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _oid(i: int) -> bytes:
    return i.to_bytes(16, "little")


def _child(code: str):
    """Spawn a python child attached to the repo; returns the Popen."""
    return subprocess.Popen(
        [sys.executable, "-u", "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, "PYTHONPATH": _REPO},
    )


def _wait_line(proc, token: str, timeout: float = 30.0) -> None:
    """Block until the child prints ``token`` on stdout."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if token in line:
            return
        if proc.poll() is not None:
            raise AssertionError(
                f"child exited rc={proc.returncode}: {proc.stderr.read()[-800:]}"
            )
    raise AssertionError(f"child never printed {token!r}")


@pytest.fixture
def pool():
    name = f"/rtpu_shmp_{os.getpid()}"
    p = PoolStore(name, create=True, pool_bytes=16 << 20, max_objects=256,
                  evict=True)
    yield p
    p.destroy()


def test_multiprocess_put_get_bit_exact(pool):
    """Bytes written by one process read bit-exact by another, and vice
    versa — the same mapping, zero copies, arbitrary binary payloads."""
    rng = np.random.RandomState(7)
    blob = rng.bytes(1 << 20)
    v = pool.create(_oid(1), len(blob))
    v[:] = blob
    del v
    assert pool.seal(_oid(1))
    code = f"""
import hashlib
from ray_tpu._private.native_store import PoolStore
p = PoolStore({pool.name!r}, create=False)
g = p.get((1).to_bytes(16, "little"))
print("HASH", hashlib.sha256(bytes(g)).hexdigest())
del g
p.release((1).to_bytes(16, "little"))
# Child-side put: parent must read it bit-exact too.
w = p.create((2).to_bytes(16, "little"), len(bytes(1)))
payload = hashlib.sha256(b"child-put").digest()[:1]
w[:] = payload
del w
p.seal((2).to_bytes(16, "little"))
print("PUT", payload.hex())
p.close()
"""
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": _REPO},
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = dict(l.split(" ", 1) for l in r.stdout.strip().splitlines())
    assert lines["HASH"] == hashlib.sha256(blob).hexdigest()
    g = pool.get(_oid(2))
    assert bytes(g).hex() == lines["PUT"].strip()
    del g
    pool.release(_oid(2))


def test_sigkill_client_refs_swept(pool):
    """A SIGKILLed reader's refcounts are reclaimed by sweep(): the
    object it pinned becomes evictable/deletable again."""
    v = pool.create(_oid(10), 1 << 20)
    del v
    pool.seal(_oid(10))
    proc = _child(f"""
import sys, time
from ray_tpu._private.native_store import PoolStore
p = PoolStore({pool.name!r}, create=False)
g = p.get((10).to_bytes(16, "little"))  # rc -> 1, never released
print("PINNED", flush=True)
time.sleep(120)
""")
    _wait_line(proc, "PINNED")
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    swept = pool.sweep()
    assert swept["clients_swept"] >= 1, swept
    assert swept["refs_dropped"] >= 1, swept
    # The pin is gone: delete frees the block immediately.
    base = pool.stats()["bytes_in_use"]
    pool.delete(_oid(10))
    assert pool.stats()["bytes_in_use"] < base


def test_eviction_respects_cross_process_reader(pool):
    """An object mapped by a LIVE reader in another process must survive
    memory pressure; once the reader dies and is swept it may go."""
    v = pool.create(_oid(20), 1 << 20)
    v[:6] = b"pinned"
    del v
    pool.seal(_oid(20))
    proc = _child(f"""
import time
from ray_tpu._private.native_store import PoolStore
p = PoolStore({pool.name!r}, create=False)
g = p.get((20).to_bytes(16, "little"))
print("PINNED", flush=True)
time.sleep(120)
""")
    _wait_line(proc, "PINNED")
    try:
        # Pressure: fill well past capacity; eviction must route around
        # the cross-process pin.
        for i in range(30):
            w = pool.create(_oid(21 + i), 1 << 20)
            if w is None:
                break
            del w
            pool.seal(_oid(21 + i))
        assert pool.contains(_oid(20)), "cross-process pin was evicted"
        g = pool.get(_oid(20))
        assert bytes(g[:6]) == b"pinned"
        del g
        pool.release(_oid(20))
    finally:
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
    swept = pool.sweep()
    assert swept["clients_swept"] >= 1
    # Reader dead + our release done: the refcount is 0 again, so
    # delete frees the block immediately (a lingering pin would defer).
    base = pool.stats()["bytes_in_use"]
    pool.delete(_oid(20))
    assert pool.stats()["bytes_in_use"] < base, "dead reader still pins"


def test_kill_mid_put_partial_never_seals(pool):
    """Seeded crash between create and seal: the unsealed partial must
    be reclaimed by sweep and must NEVER become visible."""
    proc = _child(f"""
import time
from ray_tpu._private.native_store import PoolStore
p = PoolStore({pool.name!r}, create=False)
w = p.create((30).to_bytes(16, "little"), 1 << 20)
w[:7] = b"partial"
print("MIDPUT", flush=True)   # crash point: created, not sealed
time.sleep(120)
""")
    _wait_line(proc, "MIDPUT")
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    assert not pool.contains(_oid(30))  # unsealed: invisible
    swept = pool.sweep()
    assert swept["clients_swept"] >= 1, swept
    assert swept["partials_reclaimed"] >= 1, swept
    assert not pool.contains(_oid(30)), "partial sealed after sweep"
    # The arena space is reusable: same id, fresh create succeeds.
    w = pool.create(_oid(30), 1 << 20)
    assert w is not None
    del w
    stats = pool.sweep_stats()
    assert stats["partials_reclaimed"] >= 1


def test_sweep_is_idempotent_and_self_preserving(pool):
    """sweep() from the owner never sweeps the live caller, and a
    second sweep with no new deaths is a no-op."""
    v = pool.create(_oid(40), 1024)
    del v
    pool.seal(_oid(40))
    g = pool.get(_oid(40))  # our own pin
    first = pool.sweep()
    assert first["clients_swept"] == 0
    assert pool.contains(_oid(40))
    del g
    pool.release(_oid(40))


def test_same_host_pull_rides_shm_not_socket(monkeypatch):
    """A pull between two node stores on one host maps the provider's
    pool and copies once — the chunked TCP path is never entered."""
    import secrets

    from ray_tpu._private.object_transfer import (
        ObjectFetcher, ObjectTransferServer,
    )

    prov_name = f"/rtpu_prov_{os.getpid()}"
    cons_name = f"/rtpu_cons_{os.getpid()}"
    provider_pool = PoolStore(prov_name, create=True, pool_bytes=8 << 20)
    consumer_pool = PoolStore(cons_name, create=True, pool_bytes=8 << 20)
    authkey = secrets.token_bytes(8)
    server = fetcher = None
    try:
        monkeypatch.setenv("RAY_TPU_POOL_NAME", prov_name)
        provider_store = ObjectStore()
        monkeypatch.setenv("RAY_TPU_POOL_NAME", cons_name)
        consumer_store = ObjectStore()

        oid = ObjectID(_oid((os.getpid() << 16) + 77))
        arr = np.random.RandomState(3).rand(1 << 16)  # 512 KiB
        loc, _ = provider_store.put(oid, arr)
        assert loc == "pool"

        server = ObjectTransferServer(
            provider_store, "127.0.0.1:0", authkey
        )
        fetcher = ObjectFetcher(consumer_store, authkey)
        # Chunked path booby-trapped: the shm shortcut must satisfy the
        # pull before a single pull_chunk request is issued.
        def _no_tcp(*a, **k):
            raise AssertionError("same-host pull fell back to TCP chunks")
        monkeypatch.setattr(fetcher, "_pull_chunks", _no_tcp)
        assert fetcher.pull(oid, server.address, timeout=20.0)
        assert consumer_store.contains(oid)
        np.testing.assert_array_equal(consumer_store.get(oid), arr)
    finally:
        if fetcher is not None:
            fetcher.close()
        if server is not None:
            server.shutdown()
        provider_pool.destroy()
        consumer_pool.destroy()


def test_fenced_provider_segment_not_attachable(monkeypatch):
    """Membership fencing drive-by (ISSUE 18): once a raylet learns it
    was declared dead it fences its transfer server — shm_locate must
    stop naming the pool, so no NEW pull can map a segment the fleet
    already considers gone (the head may have freed the ids, and a
    fresh incarnation may recycle the pool). Pulls degrade to the
    chunked copy path — bytes, never the mapping — and complete."""
    import secrets

    from ray_tpu._private.object_transfer import (
        ObjectFetcher, ObjectTransferServer,
    )

    prov_name = f"/rtpu_fprov_{os.getpid()}"
    cons_name = f"/rtpu_fcons_{os.getpid()}"
    provider_pool = PoolStore(prov_name, create=True, pool_bytes=8 << 20)
    consumer_pool = PoolStore(cons_name, create=True, pool_bytes=8 << 20)
    authkey = secrets.token_bytes(8)
    server = fetcher = None
    try:
        monkeypatch.setenv("RAY_TPU_POOL_NAME", prov_name)
        provider_store = ObjectStore()
        monkeypatch.setenv("RAY_TPU_POOL_NAME", cons_name)
        consumer_store = ObjectStore()

        oid = ObjectID(_oid((os.getpid() << 16) + 78))
        arr = np.random.RandomState(4).rand(1 << 16)  # 512 KiB
        loc, _ = provider_store.put(oid, arr)
        assert loc == "pool"

        server = ObjectTransferServer(
            provider_store, "127.0.0.1:0", authkey
        )
        server.fence_shm()
        fetcher = ObjectFetcher(consumer_store, authkey)
        # The boot-id handshake answers fenced — the provider's pool
        # name never crosses the wire, so there is nothing to attach.
        conn = fetcher._conn_for(server.address)
        reply = conn.request(
            {"type": "shm_locate", "object_id": oid.binary()},
            timeout=10.0,
        )
        assert reply.get("ok") is False and reply.get("fenced") is True
        assert "pool" not in reply, f"fenced locate leaked pool: {reply}"
        # A new pull still completes — over chunks, never the mapping.
        chunk_pulls = []
        real_chunks = fetcher._pull_chunks

        def _counted(*a, **k):
            chunk_pulls.append(1)
            return real_chunks(*a, **k)

        monkeypatch.setattr(fetcher, "_pull_chunks", _counted)
        assert fetcher.pull(oid, server.address, timeout=20.0)
        assert chunk_pulls, "pull bypassed the fence"
        np.testing.assert_array_equal(consumer_store.get(oid), arr)
    finally:
        if fetcher is not None:
            fetcher.close()
        if server is not None:
            server.shutdown()
        provider_pool.destroy()
        consumer_pool.destroy()


def test_pool_full_hands_off_to_segment_ladder(monkeypatch):
    """Pool exhaustion must degrade to per-object segments (the spill
    ladder's first rung), never fail the put."""
    name = f"/rtpu_ladder_{os.getpid()}"
    owner = PoolStore(name, create=True, pool_bytes=4 << 20, max_objects=64)
    monkeypatch.setenv("RAY_TPU_POOL_NAME", name)
    monkeypatch.setattr(
        "ray_tpu._private.config.RayConfig.put_backpressure_timeout_s", 0.5,
        raising=False,
    )
    store = ObjectStore()
    try:
        assert store.has_pool
        locs = []
        payloads = {}
        # pid-salted ids: per-object segment names derive from the oid
        # and outlive a crashed run — fixed ids would collide with a
        # leaked /dev/shm entry from a previous failure.
        salt = os.getpid() << 16
        for i in range(8):  # 8 x 1MB into a 4MB pool: must overflow
            oid = ObjectID(_oid(salt + i))
            arr = np.full(1 << 17, i, dtype=np.float64)  # 1MB
            loc, _size = store.put(oid, arr)
            locs.append(loc)
            payloads[oid] = arr
        assert "pool" in locs, locs
        assert any(l != "pool" for l in locs), (
            f"4MB pool absorbed 8MB without segment fallback: {locs}"
        )
        # Every object readable regardless of which rung holds it.
        for oid, arr in payloads.items():
            np.testing.assert_array_equal(store.get(oid), arr)
        for oid in payloads:
            store.delete(oid)
    finally:
        store.close()
        owner.destroy()
