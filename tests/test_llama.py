"""Llama model: shapes, loss, sharded training step on the CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import CONFIGS, LlamaForCausalLM
from ray_tpu.models.llama import causal_lm_loss
from ray_tpu.parallel import MeshSpec, shard_params

CFG = CONFIGS["llama-tiny"]


@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG)
    ids = jnp.zeros((2, 32), jnp.int32)
    return model.init(jax.random.PRNGKey(0), ids)


def test_forward_shape(tiny_params):
    model = LlamaForCausalLM(CFG)
    ids = jnp.ones((2, 32), jnp.int32)
    logits = model.apply(tiny_params, ids)
    assert logits.shape == (2, 32, CFG.vocab_size)
    assert logits.dtype == jnp.float32


def test_causal_lm_loss_decreases(tiny_params):
    model = LlamaForCausalLM(CFG)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, (4, 32)), jnp.int32)
    targets = jnp.roll(ids, -1, axis=1)
    tx = optax.adam(1e-3)
    params = tiny_params
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            return causal_lm_loss(model.apply(p, ids), targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_causality(tiny_params):
    """Changing a future token must not affect earlier logits."""
    model = LlamaForCausalLM(CFG)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, (1, 16)), jnp.int32)
    logits1 = model.apply(tiny_params, ids)
    ids2 = ids.at[0, 10].set((ids[0, 10] + 1) % CFG.vocab_size)
    logits2 = model.apply(tiny_params, ids2)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-4
    )


def test_num_params_formula(tiny_params):
    counted = sum(x.size for x in jax.tree_util.tree_leaves(tiny_params))
    assert counted == CFG.num_params()


def test_sharded_train_step_dp_tp(tiny_params):
    """Full train step jitted over a 2x2x2 (data x tensor x seq... ) mesh."""
    mesh = MeshSpec(data=2, fsdp=2, tensor=2).build()
    model = LlamaForCausalLM(CFG, mesh=mesh)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, (4, 32)), jnp.int32)
    targets = jnp.roll(ids, -1, axis=1)

    with jax.set_mesh(mesh):
        params = shard_params(tiny_params, mesh)

        @jax.jit
        def step(p):
            def loss_fn(p_):
                return causal_lm_loss(model.apply(p_, ids), targets)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            return loss, grads

        loss, grads = step(params)
    assert np.isfinite(float(loss))
    # Grad tree mirrors param tree.
    assert jax.tree_util.tree_structure(grads) == jax.tree_util.tree_structure(
        params
    )


def test_seq_parallel_matches_single_device():
    """Ring-attention model output == plain model output (f32 compute so
    the only difference is the blockwise softmax merge, ~1e-5)."""
    from dataclasses import replace

    cfg32 = replace(CFG, dtype=jnp.float32)
    mesh = MeshSpec(seq=4).build()
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg32.vocab_size, (2, 64)), jnp.int32)
    params = LlamaForCausalLM(cfg32).init(jax.random.PRNGKey(0), ids)
    plain = LlamaForCausalLM(cfg32).apply(params, ids)
    with jax.set_mesh(mesh):
        ringed = LlamaForCausalLM(cfg32, mesh=mesh).apply(params, ids)
    np.testing.assert_allclose(
        np.asarray(plain), np.asarray(ringed), atol=2e-4, rtol=1e-4
    )


def test_chunked_loss_matches_full(tiny_params):
    """chunked_causal_lm_loss (scanned LM head, logits never fully
    materialized) equals the full-logits loss — value AND gradients."""
    import numpy as np

    from ray_tpu.models.llama import causal_lm_loss, chunked_causal_lm_loss

    model = LlamaForCausalLM(CFG)
    params = tiny_params
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, CFG.vocab_size, (2, 32)),
        jnp.int32,
    )
    targets = jnp.roll(ids, -1, axis=1)

    def full(p):
        return causal_lm_loss(model.apply(p, ids), targets)

    def chunked(p):
        return chunked_causal_lm_loss(model, p, ids, targets, chunk_size=8)

    lf, gf = jax.value_and_grad(full)(params)
    lc, gc = jax.value_and_grad(chunked)(params)
    assert abs(float(lf) - float(lc)) < 1e-4, (lf, lc)
    for a, b in zip(
        jax.tree_util.tree_leaves(gf), jax.tree_util.tree_leaves(gc)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3
        )

    # Broadcastable [1, T] mask + odd length not divisible by the
    # chunk (padding path) agree with the full loss too.
    mask = (jnp.arange(ids.shape[1])[None, :] < ids.shape[1] - 3).astype(
        jnp.float32
    )
    lf = causal_lm_loss(model.apply(params, ids), targets, mask=mask)
    lc = chunked_causal_lm_loss(
        model, params, ids, targets, mask=mask, chunk_size=8
    )
    assert abs(float(lf) - float(lc)) < 1e-4
    odd_ids, odd_t = ids[:, :29], targets[:, :29]
    lf = causal_lm_loss(model.apply(params, odd_ids), odd_t)
    lc = chunked_causal_lm_loss(
        model, params, odd_ids, odd_t, chunk_size=8
    )
    assert abs(float(lf) - float(lc)) < 1e-4

    # bf16 params (the bench configuration): the chunked head must
    # accumulate in f32 and stay comparable to the full path.
    import dataclasses

    bcfg = dataclasses.replace(CFG, param_dtype=jnp.bfloat16)
    bmodel = LlamaForCausalLM(bcfg)
    bparams = bmodel.init(jax.random.PRNGKey(1), ids)
    lf = causal_lm_loss(bmodel.apply(bparams, ids), targets)
    lc = chunked_causal_lm_loss(bmodel, bparams, ids, targets, chunk_size=8)
    assert abs(float(lf) - float(lc)) < 5e-3, (lf, lc)
