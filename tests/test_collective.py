"""Out-of-graph collective API (reference: util/collective tests)."""
import numpy as np

import ray_tpu


def _worker(rank, world, value):
    from ray_tpu.util import collective as col

    col.init_collective_group(world, rank, group_name="g1")
    reduced = col.allreduce(np.full((4,), value, np.float32), group_name="g1")
    gathered = col.allgather(np.array([rank], np.int32), group_name="g1")
    bcast = col.broadcast(
        np.array([42.0]) if rank == 0 else None, src_rank=0, group_name="g1"
    )
    col.barrier(group_name="g1")
    return reduced.tolist(), [int(x[0]) for x in gathered], float(bcast[0])


def test_collective_ops(ray_start):
    world = 3
    f = ray_tpu.remote(_worker)
    results = ray_tpu.get(
        [f.remote(r, world, float(r + 1)) for r in range(world)], timeout=60
    )
    for reduced, gathered, b in results:
        assert reduced == [6.0, 6.0, 6.0, 6.0]  # 1+2+3
        assert gathered == [0, 1, 2]
        assert b == 42.0


def test_reducescatter(ray_start):
    def worker(rank, world):
        from ray_tpu.util import collective as col

        col.init_collective_group(world, rank, group_name="rs")
        shard = col.reducescatter(np.arange(4, dtype=np.float32), group_name="rs")
        return shard.tolist()

    f = ray_tpu.remote(worker)
    out = ray_tpu.get([f.remote(r, 2) for r in range(2)], timeout=60)
    # sum = [0,2,4,6]; rank0 gets [0,2], rank1 [4,6]
    assert out == [[0.0, 2.0], [4.0, 6.0]]
