"""Actor concurrency groups (reference: concurrency_group_manager.h +
ray.method(concurrency_group=...) API).

Semantics under test: a group's limit bounds ONLY that group's methods;
other groups and the default group keep flowing (the point of groups:
an actor stuck in slow compute still answers health checks on its own
"io" lane).
"""
import time

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote(concurrency_groups={"io": 4, "compute": 1})
class Grouped:
    def __init__(self):
        self.log = []

    @ray_tpu.method(concurrency_group="compute")
    def slow_compute(self):
        time.sleep(1.5)
        return "compute-done"

    @ray_tpu.method(concurrency_group="io")
    def ping(self):
        return time.time()

    def default_lane(self):
        return "default"


def test_io_group_unblocked_by_compute(cluster):
    a = Grouped.remote()
    ray_tpu.get(a.ping.remote())  # actor up
    t0 = time.time()
    slow = [a.slow_compute.remote() for _ in range(2)]  # compute limit 1
    time.sleep(0.2)  # let compute occupy its lane
    ping_t = ray_tpu.get(a.ping.remote(), timeout=10)
    ping_latency = time.time() - t0
    # The ping answered while compute was busy — well before the ~3s
    # the two serialized compute calls need.
    assert ping_latency < 1.2, f"io lane blocked: {ping_latency:.2f}s"
    assert ray_tpu.get(slow, timeout=30) == ["compute-done"] * 2
    assert ping_t <= time.time()


def test_group_limit_serializes_within_group(cluster):
    a = Grouped.remote()
    ray_tpu.get(a.ping.remote())
    t0 = time.time()
    refs = [a.slow_compute.remote() for _ in range(2)]
    ray_tpu.get(refs, timeout=30)
    # limit 1 → the two 1.5s calls serialize (≥3s), unlike the io group.
    assert time.time() - t0 >= 2.8


def test_io_group_parallel(cluster):
    @ray_tpu.remote(concurrency_groups={"io": 4})
    class P:
        @ray_tpu.method(concurrency_group="io")
        def hold(self):
            time.sleep(1.0)
            return 1

    a = P.remote()
    ray_tpu.get(a.hold.remote())
    t0 = time.time()
    assert ray_tpu.get([a.hold.remote() for _ in range(4)], timeout=20) == [1] * 4
    # 4 parallel holds on a limit-4 group finish in ~1s, not 4s.
    assert time.time() - t0 < 3.0


def test_per_call_group_override(cluster):
    a = Grouped.remote()
    ray_tpu.get(a.ping.remote())
    slow = [a.slow_compute.remote() for _ in range(2)]
    time.sleep(0.2)
    # default_lane explicitly routed into the congested compute group →
    # it queues behind both slow calls.
    t0 = time.time()
    out = ray_tpu.get(
        a.default_lane.options(concurrency_group="compute").remote(),
        timeout=30,
    )
    assert out == "default"
    assert time.time() - t0 >= 2.0, "override did not join the compute lane"
    ray_tpu.get(slow)


def test_undeclared_group_rejected(cluster):
    @ray_tpu.remote(concurrency_groups={"io": 2})
    class Bad:
        @ray_tpu.method(concurrency_group="nope")
        def f(self):
            return 1

    a = Bad.remote()
    with pytest.raises(Exception):
        ray_tpu.get(a.f.remote(), timeout=15)


def test_async_actor_groups(cluster):
    import asyncio

    @ray_tpu.remote(concurrency_groups={"limited": 1})
    class A:
        @ray_tpu.method(concurrency_group="limited")
        async def slow(self):
            await asyncio.sleep(0.8)
            return "s"

        async def fast(self):
            return "f"

    a = A.remote()
    assert ray_tpu.get(a.fast.remote(), timeout=15) == "f"
    t0 = time.time()
    refs = [a.slow.remote() for _ in range(2)]
    assert ray_tpu.get(a.fast.remote(), timeout=10) == "f"
    assert time.time() - t0 < 0.8  # default lane unblocked
    assert ray_tpu.get(refs, timeout=20) == ["s", "s"]
    assert time.time() - t0 >= 1.5  # semaphore serialized the group


def test_per_call_undeclared_group_errors(cluster):
    a = Grouped.remote()
    ray_tpu.get(a.ping.remote())
    with pytest.raises(Exception, match="concurrency group"):
        ray_tpu.get(
            a.ping.options(concurrency_group="nope").remote(), timeout=15
        )
