"""Streaming generators: num_returns="streaming" end to end.

Reference behavior: python/ray/_raylet.pyx:1289 (streaming-generator
reporting) + src/ray/core_worker/task_manager.h:208 — each yield seals
as its own object, the consumer iterates refs while the task runs.
"""
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote(num_returns="streaming")
def count_stream(n, delay=0.0):
    for i in range(n):
        if delay:
            time.sleep(delay)
        yield i * 10


@ray_tpu.remote(num_returns="streaming")
def failing_stream():
    yield 1
    yield 2
    raise ValueError("boom mid-stream")


def test_stream_basic(cluster):
    gen = count_stream.remote(5)
    assert isinstance(gen, ray_tpu.ObjectRefGenerator)
    vals = [ray_tpu.get(ref) for ref in gen]
    assert vals == [0, 10, 20, 30, 40]


def test_stream_observes_partial_output_before_completion(cluster):
    """The defining property: the consumer sees early yields while the
    producer is still running (here: still sleeping between yields)."""
    t0 = time.monotonic()
    gen = count_stream.remote(6, delay=0.5)
    first = ray_tpu.get(next(gen))
    first_latency = time.monotonic() - t0
    assert first == 0
    # Full stream takes >=3s of producer sleeps; the first item must
    # arrive while most of that is still ahead.
    assert first_latency < 2.0, f"first item took {first_latency:.1f}s"
    rest = [ray_tpu.get(r) for r in gen]
    assert rest == [10, 20, 30, 40, 50]


def test_stream_error_surfaces_after_last_yield(cluster):
    gen = failing_stream.remote()
    assert ray_tpu.get(next(gen)) == 1
    assert ray_tpu.get(next(gen)) == 2
    with pytest.raises(ValueError, match="boom mid-stream"):
        next(gen)


def test_stream_non_generator_returns_single_item(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def plain():
        return 42

    gen = plain.remote()
    assert ray_tpu.get(next(gen)) == 42
    with pytest.raises(StopIteration):
        next(gen)


def test_actor_method_streaming(cluster):
    @ray_tpu.remote
    class Tokenizer:
        def stream_tokens(self, text):
            for tok in text.split():
                yield tok

    a = Tokenizer.remote()
    toks = [
        ray_tpu.get(r)
        for r in a.stream_tokens.options(num_returns="streaming").remote(
            "the quick brown fox"
        )
    ]
    assert toks == ["the", "quick", "brown", "fox"]
    ray_tpu.kill(a)


def test_serve_handle_streaming(cluster):
    from ray_tpu import serve

    serve.start(proxy=False)
    try:
        @serve.deployment
        class TokenGen:
            def __call__(self, prompt):
                for tok in f"echo {prompt}".split():
                    yield tok + " "

        handle = serve.run(TokenGen.bind(), name="tok", route_prefix=None)
        chunks = list(handle.options(stream=True).remote("hi there"))
        assert chunks == ["echo ", "hi ", "there "]
        serve.delete("tok")
    finally:
        serve.shutdown()


def test_serve_http_streams_partial_output_before_completion(cluster):
    """VERDICT round-2 item 3 'done' criterion: an HTTP client observes
    partial output while the handler is still producing."""
    import urllib.request

    from ray_tpu import serve

    serve.start(serve.HTTPOptions(host="127.0.0.1", port=18097))
    try:
        @serve.deployment
        async def slow_tokens(request):
            import asyncio as aio

            for i in range(5):
                yield f"tok{i} "
                await aio.sleep(0.4)

        serve.run(slow_tokens.bind(), name="stream_app", route_prefix="/")
        t0 = time.monotonic()
        resp = urllib.request.urlopen("http://127.0.0.1:18097/", timeout=30)
        first = resp.read(5)  # one chunk
        first_latency = time.monotonic() - t0
        assert first == b"tok0 "
        # Producer sleeps ~2s total after the first token; seeing it this
        # early proves the response streams rather than buffering.
        assert first_latency < 1.5, f"first chunk took {first_latency:.1f}s"
        rest = resp.read().decode()
        total_latency = time.monotonic() - t0
        assert rest == "tok1 tok2 tok3 tok4 "
        assert total_latency > first_latency + 1.0  # really was incremental
        serve.delete("stream_app")
    finally:
        serve.shutdown()


def test_stream_large_items_via_store(cluster):
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def big_blocks(n):
        for i in range(n):
            yield np.full((300 * 1024,), i, dtype=np.uint8)  # > inline cap

    got = [ray_tpu.get(r) for r in big_blocks.remote(3)]
    assert [int(g[0]) for g in got] == [0, 1, 2]
    assert all(len(g) == 300 * 1024 for g in got)
