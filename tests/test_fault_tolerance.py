"""Task retries + actor restarts + chaos.

Reference semantics: max_retries re-submits on system failure
(task_manager.h:468); retry_exceptions opts app errors into retries;
max_restarts drives the GCS actor restart state machine
(gcs_actor_manager.h:278, actor_states.rst). Chaos model:
python/ray/tests/test_chaos.py.
"""
import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError, WorkerCrashedError


def test_task_retry_on_crash(ray_start):
    marker = f"/tmp/ray_tpu_retry_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)  # crash on first attempt
        return "recovered"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "recovered"
    os.unlink(marker)


def test_task_no_retry_by_default(ray_start):
    @ray_tpu.remote
    def die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=30)


def test_app_error_retry_with_retry_exceptions(ray_start):
    marker = f"/tmp/ray_tpu_appretry_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "ok"
    os.unlink(marker)


def test_app_error_no_retry_without_flag(ray_start):
    @ray_tpu.remote(max_retries=3)
    def always_raises():
        raise RuntimeError("app error")

    with pytest.raises(RuntimeError):
        ray_tpu.get(always_raises.remote(), timeout=30)


def test_actor_restart(ray_start):
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def die(self):
            os._exit(1)

    p = Phoenix.remote()
    assert ray_tpu.get(p.incr.remote()) == 1
    p.die.remote()
    # After restart, state resets (fresh __init__) but the handle works.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(p.incr.remote(), timeout=10) == 1
            break
        except RayActorError:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not come back after restart")


def test_actor_dies_after_restart_budget(ray_start):
    @ray_tpu.remote(max_restarts=1)
    class Fragile:
        def die(self):
            os._exit(1)

        def ping(self):
            return "pong"

    f = Fragile.remote()
    assert ray_tpu.get(f.ping.remote()) == "pong"
    f.die.remote()  # restart 1
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(f.ping.remote(), timeout=10)
            break
        except RayActorError:
            time.sleep(0.2)
    f.die.remote()  # exceeds budget
    time.sleep(0.5)
    with pytest.raises(RayActorError):
        ray_tpu.get(f.ping.remote(), timeout=10)


def test_actors_survive_live_head_failover(tmp_path):
    """ISSUE 9 satellite: a detached actor and a max_restarts>0 actor
    both remain callable through a LIVE head failover — the driver
    stays connected (reconnect + replay), the raylet and its workers
    outlive the head, and the actors are either re-claimed by their
    surviving workers during the recovery window or recreated from the
    durable actor table; the named handle re-resolves afterwards."""
    from ray_tpu.cluster_utils import DaemonCluster, SupervisedHead

    head = SupervisedHead(
        session_dir=str(tmp_path / "sess"),
        # Generous window: the claim path (worker reconnect) is the
        # interesting one; a too-short window degrades to recreation.
        env={"RAY_TPU_head_recovery_grace_s": "5.0"},
    )
    cluster = None
    try:
        ray_tpu.init(address=head.address)
        cluster = DaemonCluster.attach(head.tcp_address, head.authkey)
        cluster.add_node(num_cpus=2)

        @ray_tpu.remote(max_restarts=2)
        class Phoenix:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        det = Phoenix.options(
            name="det_survivor", lifetime="detached"
        ).remote()
        reg = Phoenix.remote()
        assert ray_tpu.get(det.incr.remote(), timeout=60) == 1
        assert ray_tpu.get(reg.incr.remote(), timeout=60) == 1

        head.kill()
        assert head.wait_restarted(1, timeout=60), "head never came back"

        # Both handles stay callable through the failover (the call may
        # need a few retries while the recovery window re-binds them).
        deadline = time.monotonic() + 90
        vals = {}
        while time.monotonic() < deadline and len(vals) < 2:
            for key, h in (("det", det), ("reg", reg)):
                if key in vals:
                    continue
                try:
                    vals[key] = ray_tpu.get(h.incr.remote(), timeout=20)
                except Exception:  # noqa: BLE001 - mid-recovery
                    time.sleep(0.5)
        assert vals.get("det", 0) >= 1, "detached actor lost in failover"
        assert vals.get("reg", 0) >= 1, "restartable actor lost in failover"

        # Handle re-resolution: the durable name table still resolves,
        # and the resolved handle reaches the same live actor.
        h = ray_tpu.get_actor("det_survivor")
        assert ray_tpu.get(h.incr.remote(), timeout=30) > vals["det"]
    finally:
        if cluster is not None:
            for p in list(cluster._daemons):
                try:
                    cluster.kill_node(p)
                except Exception:  # noqa: BLE001
                    pass
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001
            pass
        head.stop()


def test_rpc_delay_injection():
    # Reference: RAY_testing_asio_delay_us (ray_config_def.h:832).
    # Pool disabled: a same-host put through the shm segment advertises
    # asynchronously and never blocks on put_object, so the delay rule
    # is only observable on the legacy synchronous path.
    os.environ["RAY_TPU_NATIVE_STORE"] = "0"
    ray_tpu.init(
        num_cpus=2,
        _system_config={"testing_rpc_delay_us": "put_object=30000:30000"},
    )
    try:
        start = time.monotonic()
        ray_tpu.get(ray_tpu.put(1))
        assert time.monotonic() - start >= 0.03
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_NATIVE_STORE", None)
