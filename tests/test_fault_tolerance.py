"""Task retries + actor restarts + chaos.

Reference semantics: max_retries re-submits on system failure
(task_manager.h:468); retry_exceptions opts app errors into retries;
max_restarts drives the GCS actor restart state machine
(gcs_actor_manager.h:278, actor_states.rst). Chaos model:
python/ray/tests/test_chaos.py.
"""
import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError, WorkerCrashedError


def test_task_retry_on_crash(ray_start):
    marker = f"/tmp/ray_tpu_retry_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)  # crash on first attempt
        return "recovered"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "recovered"
    os.unlink(marker)


def test_task_no_retry_by_default(ray_start):
    @ray_tpu.remote
    def die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=30)


def test_app_error_retry_with_retry_exceptions(ray_start):
    marker = f"/tmp/ray_tpu_appretry_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "ok"
    os.unlink(marker)


def test_app_error_no_retry_without_flag(ray_start):
    @ray_tpu.remote(max_retries=3)
    def always_raises():
        raise RuntimeError("app error")

    with pytest.raises(RuntimeError):
        ray_tpu.get(always_raises.remote(), timeout=30)


def test_actor_restart(ray_start):
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def die(self):
            os._exit(1)

    p = Phoenix.remote()
    assert ray_tpu.get(p.incr.remote()) == 1
    p.die.remote()
    # After restart, state resets (fresh __init__) but the handle works.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(p.incr.remote(), timeout=10) == 1
            break
        except RayActorError:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not come back after restart")


def test_actor_dies_after_restart_budget(ray_start):
    @ray_tpu.remote(max_restarts=1)
    class Fragile:
        def die(self):
            os._exit(1)

        def ping(self):
            return "pong"

    f = Fragile.remote()
    assert ray_tpu.get(f.ping.remote()) == "pong"
    f.die.remote()  # restart 1
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(f.ping.remote(), timeout=10)
            break
        except RayActorError:
            time.sleep(0.2)
    f.die.remote()  # exceeds budget
    time.sleep(0.5)
    with pytest.raises(RayActorError):
        ray_tpu.get(f.ping.remote(), timeout=10)


def test_rpc_delay_injection():
    # Reference: RAY_testing_asio_delay_us (ray_config_def.h:832).
    ray_tpu.init(
        num_cpus=2,
        _system_config={"testing_rpc_delay_us": "put_object=30000:30000"},
    )
    try:
        start = time.monotonic()
        ray_tpu.get(ray_tpu.put(1))
        assert time.monotonic() - start >= 0.03
    finally:
        ray_tpu.shutdown()
