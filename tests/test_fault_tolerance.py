"""Task retries + actor restarts + chaos.

Reference semantics: max_retries re-submits on system failure
(task_manager.h:468); retry_exceptions opts app errors into retries;
max_restarts drives the GCS actor restart state machine
(gcs_actor_manager.h:278, actor_states.rst). Chaos model:
python/ray/tests/test_chaos.py.
"""
import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError, WorkerCrashedError


def test_task_retry_on_crash(ray_start):
    marker = f"/tmp/ray_tpu_retry_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=2)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)  # crash on first attempt
        return "recovered"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "recovered"
    os.unlink(marker)


def test_task_no_retry_by_default(ray_start):
    @ray_tpu.remote
    def die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=30)


def test_app_error_retry_with_retry_exceptions(ray_start):
    marker = f"/tmp/ray_tpu_appretry_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky(path):
        if not os.path.exists(path):
            open(path, "w").close()
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "ok"
    os.unlink(marker)


def test_app_error_no_retry_without_flag(ray_start):
    @ray_tpu.remote(max_retries=3)
    def always_raises():
        raise RuntimeError("app error")

    with pytest.raises(RuntimeError):
        ray_tpu.get(always_raises.remote(), timeout=30)


def test_actor_restart(ray_start):
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def die(self):
            os._exit(1)

    p = Phoenix.remote()
    assert ray_tpu.get(p.incr.remote()) == 1
    p.die.remote()
    # After restart, state resets (fresh __init__) but the handle works.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(p.incr.remote(), timeout=10) == 1
            break
        except RayActorError:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not come back after restart")


def test_actor_dies_after_restart_budget(ray_start):
    @ray_tpu.remote(max_restarts=1)
    class Fragile:
        def die(self):
            os._exit(1)

        def ping(self):
            return "pong"

    f = Fragile.remote()
    assert ray_tpu.get(f.ping.remote()) == "pong"
    f.die.remote()  # restart 1
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(f.ping.remote(), timeout=10)
            break
        except RayActorError:
            time.sleep(0.2)
    f.die.remote()  # exceeds budget
    time.sleep(0.5)
    with pytest.raises(RayActorError):
        ray_tpu.get(f.ping.remote(), timeout=10)


def test_actors_survive_live_head_failover(tmp_path):
    """ISSUE 9 satellite: a detached actor and a max_restarts>0 actor
    both remain callable through a LIVE head failover — the driver
    stays connected (reconnect + replay), the raylet and its workers
    outlive the head, and the actors are either re-claimed by their
    surviving workers during the recovery window or recreated from the
    durable actor table; the named handle re-resolves afterwards."""
    from ray_tpu.cluster_utils import DaemonCluster, SupervisedHead

    head = SupervisedHead(
        session_dir=str(tmp_path / "sess"),
        # Generous window: the claim path (worker reconnect) is the
        # interesting one; a too-short window degrades to recreation.
        env={"RAY_TPU_head_recovery_grace_s": "5.0"},
    )
    cluster = None
    try:
        ray_tpu.init(address=head.address)
        cluster = DaemonCluster.attach(head.tcp_address, head.authkey)
        cluster.add_node(num_cpus=2)

        @ray_tpu.remote(max_restarts=2)
        class Phoenix:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        det = Phoenix.options(
            name="det_survivor", lifetime="detached"
        ).remote()
        reg = Phoenix.remote()
        assert ray_tpu.get(det.incr.remote(), timeout=60) == 1
        assert ray_tpu.get(reg.incr.remote(), timeout=60) == 1

        head.kill()
        assert head.wait_restarted(1, timeout=60), "head never came back"

        # Both handles stay callable through the failover (the call may
        # need a few retries while the recovery window re-binds them).
        deadline = time.monotonic() + 90
        vals = {}
        while time.monotonic() < deadline and len(vals) < 2:
            for key, h in (("det", det), ("reg", reg)):
                if key in vals:
                    continue
                try:
                    vals[key] = ray_tpu.get(h.incr.remote(), timeout=20)
                except Exception:  # noqa: BLE001 - mid-recovery
                    time.sleep(0.5)
        assert vals.get("det", 0) >= 1, "detached actor lost in failover"
        assert vals.get("reg", 0) >= 1, "restartable actor lost in failover"

        # Handle re-resolution: the durable name table still resolves,
        # and the resolved handle reaches the same live actor.
        h = ray_tpu.get_actor("det_survivor")
        assert ray_tpu.get(h.incr.remote(), timeout=30) > vals["det"]
    finally:
        if cluster is not None:
            for p in list(cluster._daemons):
                try:
                    cluster.kill_node(p)
                except Exception:  # noqa: BLE001
                    pass
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001
            pass
        head.stop()


# ---------------------------------------- membership fencing (ISSUE 18)


class _FencePeer:
    """Captures what a GCS handler sends/replies to a raylet conn."""

    def __init__(self):
        self.sent = []
        self.replies = []
        self.peer_role = None

    def send(self, msg):
        self.sent.append(msg)

    def reply(self, req_msg, **fields):
        self.replies.append(fields)


def test_monotonic_liveness_survives_wall_clock_jump():
    """Satellite: the death sweeper diffs time.monotonic() readings —
    never the wall clock — so an NTP step / VM resume between two
    sweeps declares nothing dead."""
    from types import SimpleNamespace

    from ray_tpu._private.gcs import stale_node_ids
    from ray_tpu._private.ids import NodeID

    def node(last_hb, alive=True, conn=object()):
        return SimpleNamespace(
            node_id=NodeID.from_random(), alive=alive, conn=conn,
            last_heartbeat=last_hb,
        )

    now_mono = 1000.0
    fresh = node(now_mono - 1.0)
    quiet = node(now_mono - 60.0)
    # A +2h wall-clock jump happens between heartbeat and sweep. The
    # sweep never sees it: its inputs are monotonic readings only, so
    # the freshly-heartbeating node stays alive.
    assert stale_node_ids([fresh], now_mono, 1.0, 5) == []
    # The genuinely silent node IS declared dead by monotonic delta.
    assert stale_node_ids([quiet], now_mono, 1.0, 5) == [
        quiet.node_id.binary()
    ]
    # Dead / in-process (conn=None) / never-heartbeated nodes are out
    # of scope for the sweeper.
    assert stale_node_ids([node(now_mono - 60, alive=False)],
                          now_mono, 1.0, 5) == []
    assert stale_node_ids([node(now_mono - 60, conn=None)],
                          now_mono, 1.0, 5) == []
    assert stale_node_ids([node(0.0)], now_mono, 1.0, 5) == []


def test_stale_incarnation_heartbeat_fenced(ray_start):
    """A heartbeat carrying the wrong incarnation (or an unknown
    node_id) must not refresh liveness — the head answers with ONE
    fenced push per connection and ignores the beat."""
    from ray_tpu._private.worker import _global

    gcs = _global.node.gcs
    peer = _FencePeer()
    state = {"peer": peer}
    gcs._h_register_node(
        state, {"resources": {"CPU": 1.0}, "label": "fence-unit"}
    )
    reply = peer.replies[-1]
    assert reply["ok"] and reply["incarnation"] >= 1
    nid, inc = reply["node_id"], reply["incarnation"]
    try:
        # Correct incarnation: liveness refreshes, no fence.
        gcs._h_node_heartbeat(state, {"node_id": nid, "incarnation": inc})
        assert peer.sent == []
        hb0 = gcs.nodes[nid].last_heartbeat
        # Stale incarnation: fenced push, liveness NOT refreshed.
        gcs._h_node_heartbeat(
            state, {"node_id": nid, "incarnation": inc + 1}
        )
        assert [m["type"] for m in peer.sent] == ["fenced"]
        assert gcs.nodes[nid].last_heartbeat == hb0
        # Repeat offender on the same conn: no push spam.
        gcs._h_node_heartbeat(
            state, {"node_id": nid, "incarnation": inc + 1}
        )
        assert len(peer.sent) == 1
        # Unknown node_id on a fresh conn: fenced too.
        p2 = _FencePeer()
        gcs._h_node_heartbeat({"peer": p2}, {"node_id": b"\x99" * 16})
        assert p2.sent and p2.sent[0]["type"] == "fenced"
    finally:
        gcs._handle_node_death(nid, "fence-unit cleanup")


def test_fenced_node_id_cannot_reregister(ray_start):
    """Declare-dead arms the fence: the dead node_id is rejected at
    re-registration (the zombie must rejoin as a fresh identity), and
    the fresh join is granted a strictly higher incarnation."""
    from ray_tpu._private.worker import _global

    gcs = _global.node.gcs
    peer = _FencePeer()
    gcs._h_register_node(
        {"peer": peer}, {"resources": {"CPU": 1.0}, "label": "zombie"}
    )
    nid, inc = peer.replies[-1]["node_id"], peer.replies[-1]["incarnation"]
    gcs._handle_node_death(nid, "declared dead by test")
    # The zombie replays its registration with the fenced node_id.
    p2 = _FencePeer()
    gcs._h_register_node(
        {"peer": p2}, {"node_id": nid, "resources": {"CPU": 1.0}}
    )
    assert p2.replies[-1] == {"ok": False, "fenced": True}
    assert nid not in gcs.nodes or not gcs.nodes[nid].alive
    # The normal join path (no node_id) succeeds — new identity, higher
    # incarnation than anything the dead node ever held.
    p3 = _FencePeer()
    gcs._h_register_node({"peer": p3}, {"resources": {"CPU": 1.0}})
    fresh = p3.replies[-1]
    try:
        assert fresh["ok"] and fresh["node_id"] != nid
        assert fresh["incarnation"] > inc
    finally:
        gcs._handle_node_death(fresh["node_id"], "fence-unit cleanup")


def test_stale_object_advert_rejected_after_free(ray_start):
    """A zombie's put_object advert landing AFTER its death was
    processed (objects freed) must not resurrect the freed id as a
    ghost READY entry."""
    from ray_tpu._private.gcs import W_DEAD, WorkerHandle
    from ray_tpu._private.ids import WorkerID
    from ray_tpu._private.worker import _global

    gcs = _global.node.gcs
    wid = WorkerID.from_random().binary()
    with gcs._lock:
        gcs.workers[wid] = WorkerHandle(
            worker_id=WorkerID(wid),
            node_id=gcs.head_node.node_id,
            state=W_DEAD,
        )
    oid = b"\xa5" * 16
    peer = _FencePeer()
    msg = {"type": "put_object", "object_id": oid, "inline": b"zombie",
           "size": 6, "req_id": 1}
    try:
        gcs._h_put_object({"peer": peer, "client_id": wid}, msg)
        assert peer.replies == [{"ok": False, "fenced": True}]
        assert oid not in gcs.objects, "freed id resurrected by zombie"
        # Same advert from a live (ownerless) path still lands.
        gcs._h_put_object({"peer": peer, "client_id": None}, dict(msg))
        assert peer.replies[-1] == {"ok": True}
        assert gcs.objects[oid].inline == b"zombie"
    finally:
        with gcs._lock:
            gcs.objects.pop(oid, None)
            gcs.workers.pop(wid, None)


def test_zombie_node_rejoins_with_new_incarnation(tmp_path):
    """Tentpole e2e: a raylet partitioned from the head past the death
    threshold — TCP stays ESTABLISHED, frames blackhole — is declared
    dead (incarnation bumped, node_id fenced). On heal its first
    heartbeat draws a fenced push; it self-fences and rejoins through
    the normal join path as a NEW node_id with a HIGHER incarnation,
    and a restartable actor that lived there answers exactly one
    incarnation's calls (fresh boot token, counter restarted at 1)."""
    import secrets

    from ray_tpu.cluster_utils import DaemonCluster

    ray_tpu.init(
        num_cpus=0,
        tcp_port=0,
        _system_config={
            "health_check_period_ms": 250,
            "health_check_failure_threshold": 4,
        },
    )
    cluster = DaemonCluster.attach()
    try:
        epoch = time.time()
        # Cut both directions of the raylet<->head link from t=+10s,
        # heal 6s later. Installed ONLY in the victim daemon's env: the
        # driver and the head never see the spec (gray failure).
        cluster.add_node(
            num_cpus=2,
            label="victim",
            env={
                "RAY_TPU_chaos_spec": "partition:raylet<->head=10:6",
                "RAY_TPU_chaos_seed": "7",
                "RAY_TPU_chaos_epoch": str(epoch),
                # Beat at the head's sweep cadence: the default 1s
                # period would read as missed beats under the head's
                # tightened 250ms*4 threshold.
                "RAY_TPU_health_check_period_ms": "250",
            },
        )
        victim = next(
            n for n in ray_tpu.nodes() if n["label"] == "victim"
        )
        nid0, inc0 = victim["node_id"], victim["incarnation"]

        # num_cpus=1 pins the actor to the victim — the head node has
        # zero CPUs, so nothing else can host it (or its restart).
        @ray_tpu.remote(max_restarts=4, num_cpus=1)
        class Tokened:
            def __init__(self):
                self.token = secrets.token_hex(4)
                self.n = 0

            def bump(self):
                self.n += 1
                return self.token, self.n

        a = Tokened.remote()
        tok_a, n1 = ray_tpu.get(a.bump.remote(), timeout=60)
        assert n1 == 1

        # Phase 1: the partition outlasts the death threshold — the
        # victim disappears from the live membership view.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes()
                     if n["alive"] and n["node_id"] == nid0]
            if not alive:
                break
            time.sleep(0.25)
        else:
            pytest.fail("partitioned node never declared dead")

        # Phase 2: heal -> fenced heartbeat -> self-fence -> rejoin as
        # a fresh identity with a strictly higher incarnation.
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            back = [
                n for n in ray_tpu.nodes()
                if n["alive"] and n["label"] == "victim"
                and n["node_id"] != nid0
                and n["incarnation"] > inc0
            ]
            if back:
                break
            time.sleep(0.25)
        else:
            pytest.fail("zombie never rejoined as a new incarnation")

        # Phase 3: the actor answers exactly one incarnation's calls —
        # a fresh boot token, counter restarted, strictly increasing,
        # never interleaved with the old token.
        deadline = time.monotonic() + 90
        tok_b = None
        while time.monotonic() < deadline:
            try:
                tok_b, m1 = ray_tpu.get(a.bump.remote(), timeout=15)
                break
            except Exception:  # noqa: BLE001 - mid-restart
                time.sleep(0.5)
        assert tok_b is not None, "actor never answered after rejoin"
        assert tok_b != tok_a, "old incarnation answered after fencing"
        assert m1 == 1, "restarted actor kept stale state"
        for expect in (2, 3):
            tok, m = ray_tpu.get(a.bump.remote(), timeout=30)
            assert (tok, m) == (tok_b, expect)
    finally:
        for p in list(cluster._daemons):
            try:
                cluster.kill_node(p)
            except Exception:  # noqa: BLE001
                pass
        ray_tpu.shutdown()


def test_rpc_delay_injection():
    # Reference: RAY_testing_asio_delay_us (ray_config_def.h:832).
    # Pool disabled: a same-host put through the shm segment advertises
    # asynchronously and never blocks on put_object, so the delay rule
    # is only observable on the legacy synchronous path.
    os.environ["RAY_TPU_NATIVE_STORE"] = "0"
    ray_tpu.init(
        num_cpus=2,
        _system_config={"testing_rpc_delay_us": "put_object=30000:30000"},
    )
    try:
        start = time.monotonic()
        ray_tpu.get(ray_tpu.put(1))
        assert time.monotonic() - start >= 0.03
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_NATIVE_STORE", None)
