"""Distributed refcounting + lineage reconstruction.

Reference behaviors modeled: reference_count.h:61 (instance counting,
refs-inside-objects pinning), object_recovery_manager.h:41 +
task_manager.h:269 (owner resubmits the producing task when the data is
lost), worker_killing/eviction interplay.
"""
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.worker import _global, global_client

BIG = 300_000  # floats, ~2.4 MB serialized: forced to the shm store


@pytest.fixture
def ray4():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _directory_size():
    return len(_global.node.gcs.objects)


def _entry(ref):
    return _global.node.gcs.objects.get(ref.id().binary())


def _flush_refs():
    client = global_client()
    client._tracker.flush(client)


def test_auto_free_on_last_ref_drop(ray4):
    @ray_tpu.remote
    def make():
        return np.zeros(BIG)

    ref = make.remote()
    _ = ray_tpu.get(ref)
    _flush_refs()  # add_ref lands
    assert _entry(ref) is not None
    oid = ref.id()
    del ref
    import gc

    gc.collect()
    _flush_refs()  # removal lands -> directory reclaims
    deadline = time.time() + 5
    while time.time() < deadline:
        if _global.node.gcs.objects.get(oid.binary()) is None:
            break
        time.sleep(0.05)
    assert _global.node.gcs.objects.get(oid.binary()) is None
    # The shm data is gone too.
    assert not global_client().store.contains(oid)


def test_put_object_freed_when_ref_dies(ray4):
    arr = np.random.rand(BIG)
    ref = ray_tpu.put(arr)
    _flush_refs()
    oid = ref.id()
    del ref
    import gc

    gc.collect()
    _flush_refs()
    deadline = time.time() + 5
    while time.time() < deadline:
        if _global.node.gcs.objects.get(oid.binary()) is None:
            break
        time.sleep(0.05)
    assert _global.node.gcs.objects.get(oid.binary()) is None


def test_dep_pinned_while_task_in_flight(ray4):
    # The driver drops its ref the instant the dependent task is
    # submitted; the task-dependency pin must keep the object alive
    # until the consumer has run.
    @ray_tpu.remote
    def consume(a):
        time.sleep(0.5)
        return float(np.sum(a))

    arr = np.random.rand(BIG)
    ref = ray_tpu.put(arr)
    _flush_refs()
    out = consume.remote(ref)
    del ref
    import gc

    gc.collect()
    _flush_refs()
    assert abs(ray_tpu.get(out, timeout=30) - arr.sum()) < 1e-6


def test_nested_refs_pin_children(ray4):
    # A stored value embedding refs keeps the children alive even after
    # the driver drops them (borrowing: refs inside objects).
    inner = ray_tpu.put(np.arange(BIG, dtype=np.float64))
    outer = ray_tpu.put({"data": inner})
    _flush_refs()
    inner_oid = inner.id()
    del inner
    import gc

    gc.collect()
    _flush_refs()
    time.sleep(0.3)
    assert _global.node.gcs.objects.get(inner_oid.binary()) is not None
    got = ray_tpu.get(outer)
    inner_val = ray_tpu.get(got["data"])
    assert inner_val[BIG - 1] == BIG - 1
    # Dropping the outer (and the borrowed handle) releases the chain.
    del outer, got, inner_val
    gc.collect()
    _flush_refs()
    deadline = time.time() + 5
    while time.time() < deadline:
        if _global.node.gcs.objects.get(inner_oid.binary()) is None:
            break
        time.sleep(0.05)
    assert _global.node.gcs.objects.get(inner_oid.binary()) is None


def test_reconstruction_after_data_eviction(ray4):
    # Simulate memory-pressure eviction: the store's copy vanishes while
    # the directory still says READY; get() must resubmit the producing
    # task from lineage and return the value.
    @ray_tpu.remote
    def produce(seed):
        return np.random.default_rng(seed).random(BIG)

    ref = produce.remote(42)
    first = ray_tpu.get(ref).copy()
    # Evict: drop the sealed bytes everywhere (directory entry kept).
    gcs = _global.node.gcs
    entry = _entry(ref)
    assert entry is not None and entry.segment is not None
    from ray_tpu._private.ids import ObjectID

    gcs._store.delete(ref.id())
    client = global_client()
    client.store.delete(ref.id())
    assert not client.store.contains(ref.id())
    # Reconstruct through lineage.
    second = ray_tpu.get(ref, timeout=60)
    assert np.allclose(second, first)


def test_reconstruction_when_node_dies_with_only_copy():
    from ray_tpu.cluster_utils import DaemonCluster

    cluster = DaemonCluster(head_node_args={"num_cpus": 2, "tcp_port": 0})
    try:
        # Two interchangeable daemons: the task can run on either, so
        # reconstruction has somewhere to go after one dies.
        proc_a = cluster.add_node(num_cpus=2, resources={"spot": 1.0}, label="a")
        proc_b = cluster.add_node(num_cpus=2, resources={"spot": 1.0}, label="b")

        @ray_tpu.remote
        def produce(seed):
            return np.random.default_rng(seed).random(BIG)

        ref = produce.options(resources={"spot": 0.01}, max_retries=3).remote(7)
        expected = np.random.default_rng(7).random(BIG)
        # Seal on one daemon but do NOT pull it anywhere else yet.
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
        assert ready
        entry = _global.node.gcs.objects[ref.id().binary()]
        assert entry.segment is not None
        holder_label = {
            n["node_id"]: n["label"] for n in ray_tpu.nodes()
        }[entry.node_id.binary()]
        victim = proc_a if holder_label == "a" else proc_b
        cluster.kill_node(victim)
        # Wait for the GCS to declare the node (and the object) lost.
        deadline = time.time() + 15
        while time.time() < deadline:
            if _global.node.gcs.objects[ref.id().binary()].status == "LOST":
                break
            time.sleep(0.2)
        # The only copy died with the node: get() must reconstruct by
        # re-running the producing task on the surviving daemon.
        got = ray_tpu.get(ref, timeout=60)
        assert np.allclose(got, expected)
    finally:
        cluster.shutdown()


def test_fast_dropped_result_is_reclaimed(ray_start):
    """A task result whose ref lives for less than one ref-flush window
    must still be freed server-side (owner return-refs are advertised
    at submission, so the drop's remove always goes out)."""
    import time

    from ray_tpu._private.worker import _global

    @ray_tpu.remote
    def quick():
        return list(range(1000))

    oids = []
    for _ in range(5):
        ref = quick.remote()
        assert len(ray_tpu.get(ref)) == 1000
        oids.append(ref.id().binary())
        del ref  # dropped well inside the 100ms flush window
    gcs = _global.node.gcs
    deadline = time.time() + 10
    while time.time() < deadline:
        live = [o for o in oids if gcs.objects.get(o) is not None]
        if not live:
            break
        time.sleep(0.2)
    assert not live, f"{len(live)} fast-dropped results leaked"


def test_drop_racing_delayed_task_done_is_reclaimed():
    """The owner's ref-drop can reach the directory BEFORE the worker's
    batched task_done creates the entry (leased path: return refs are
    advertised client-side only, and under load the 4ms done-batch can
    land after the 100ms ref flush). The early-drop ledger
    (gcs._early_drops) must reclaim the result at seal time — observed
    leaking 1-in-5 under a loaded full-suite run before the fix."""
    import time

    from ray_tpu._private.worker import _global

    ray_tpu.init(
        num_cpus=2,
        # Delay every done-batch 150ms at the GCS: the driver's ref
        # flush (100ms) now reliably wins the race the wild run hit
        # intermittently.
        _system_config={
            "testing_rpc_delay_us": "task_done_batch=150000:150000"
        },
    )
    try:
        @ray_tpu.remote
        def quick():
            return list(range(500))

        # Warm a leased worker so subsequent calls ride the direct path.
        ray_tpu.get(quick.remote())
        oids = []
        for _ in range(5):
            ref = quick.remote()
            assert len(ray_tpu.get(ref)) == 500
            oids.append(ref.id().binary())
            del ref
        gcs = _global.node.gcs
        deadline = time.time() + 15
        while time.time() < deadline:
            live = [o for o in oids if gcs.objects.get(o) is not None]
            if not live:
                break
            time.sleep(0.2)
        assert not live, (
            f"{len(live)} results leaked past the early-drop ledger"
        )
    finally:
        ray_tpu.shutdown()
