"""Resource-aware streaming execution + new datasources.

Reference behavior being matched: data/_internal/execution/
resource_manager.py (reservation-based per-operator memory budgets —
outstanding BYTES bounded, not just task counts) and the image / SQL /
webdataset datasources.
"""
import os
import sqlite3
import tarfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


# ------------------------------------------------------ memory budget

ROW_BYTES = 8 * 1024  # each row is an 8 KiB blob
ROWS_PER_BLOCK = 16  # -> ~128 KiB blocks


def _big_pipeline(executor_kwargs):
    """range -> map to fat rows -> map identity, executed manually so
    the test can observe the resource manager."""
    from ray_tpu.data._executor import StreamingExecutor
    from ray_tpu.data._plan import optimize

    ds = (
        rd.range(24 * ROWS_PER_BLOCK, parallelism=24)
        .map_batches(
            lambda b: {"blob": [b"x" * ROW_BYTES for _ in b["id"]]},
            batch_size=None,
        )
    )
    ex = StreamingExecutor(**executor_kwargs)
    out = list(ex.execute(optimize(ds._plan)))
    total_rows = sum(m.num_rows for _, m in out)
    return total_rows, ex.resource_manager


def test_flat_cap_balloons_but_budget_bounds_peak(cluster):
    budget = 6 * ROWS_PER_BLOCK * ROW_BYTES  # ~6 blocks worth
    # Without a budget the executor keeps max_in_flight tasks' worth of
    # blocks outstanding — well beyond the budget.
    rows, rm_free = _big_pipeline({"max_in_flight": 16})
    assert rows == 24 * ROWS_PER_BLOCK
    assert rm_free.peak_bytes > budget * 1.5, rm_free.peak_bytes

    # With the reservation allocator the peak stays within budget plus
    # one task of overshoot (the progress guarantee).
    rows, rm = _big_pipeline(
        {"max_in_flight": 16, "memory_budget_bytes": budget}
    )
    assert rows == 24 * ROWS_PER_BLOCK
    one_block = ROWS_PER_BLOCK * ROW_BYTES
    assert rm.peak_bytes <= budget + 2 * one_block, (
        rm.peak_bytes, budget,
    )


def test_budget_pipeline_correctness(cluster):
    # Budget so tight only the progress guarantee advances: results
    # must still be complete and ordered.
    ds = rd.range(200, parallelism=10).map(lambda r: {"v": r["id"] * 2})
    os.environ["RAY_TPU_DATA_MEMORY_BUDGET"] = "1"
    try:
        out = [r["v"] for r in ds.iter_rows()]
    finally:
        del os.environ["RAY_TPU_DATA_MEMORY_BUDGET"]
    assert out == [i * 2 for i in range(200)]


# -------------------------------------------------------- datasources

def test_read_images_roundtrip(cluster, tmp_path):
    from PIL import Image

    for i in range(4):
        arr = np.full((8, 6, 3), i * 10, dtype=np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
    ds = rd.read_images(str(tmp_path), size=(4, 3), mode="RGB")
    rows = sorted(ds.take_all(), key=lambda r: r["path"])
    assert len(rows) == 4
    for i, row in enumerate(rows):
        img = np.asarray(row["image"])
        assert img.shape == (4, 3, 3)
        assert img.flat[0] == i * 10
        assert row["path"].endswith(f"img_{i}.png")


def test_read_sql_roundtrip(cluster, tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE kv (k INTEGER, v TEXT)")
    conn.executemany(
        "INSERT INTO kv VALUES (?, ?)", [(i, f"row{i}") for i in range(20)]
    )
    conn.commit()
    conn.close()

    ds = rd.read_sql(
        "SELECT k, v FROM kv ORDER BY k",
        lambda: sqlite3.connect(db),
    )
    rows = ds.take_all()
    assert [r["k"] for r in rows] == list(range(20))
    assert rows[7]["v"] == "row7"

    # Sharded: LIMIT/OFFSET split across tasks, same content.
    sharded = rd.read_sql(
        "SELECT k, v FROM kv ORDER BY k",
        lambda: sqlite3.connect(db),
        shard_rows=6,
        parallelism=4,
    )
    assert sorted(r["k"] for r in sharded.take_all()) == list(range(20))


def test_read_webdataset_roundtrip(cluster, tmp_path):
    shard = tmp_path / "shard-0000.tar"
    with tarfile.open(shard, "w") as tf:
        for i in range(3):
            for ext, payload in (
                ("jpg", b"JPEG" + bytes([i])),
                ("cls", str(i).encode()),
            ):
                import io

                info = tarfile.TarInfo(name=f"{i:04d}.{ext}")
                info.size = len(payload)
                tf.addfile(info, io.BytesIO(payload))
    ds = rd.read_webdataset(str(shard))
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert [r["__key__"] for r in rows] == ["0000", "0001", "0002"]
    assert rows[1]["jpg"] == b"JPEG\x01"
    assert rows[2]["cls"] == b"2"
    # Decoding composes through map(), as in the reference default.
    decoded = ds.map(lambda r: {"label": int(r["cls"].decode())})
    assert sorted(x["label"] for x in decoded.take_all()) == [0, 1, 2]


def test_read_sql_sharding_covers_whole_table(cluster, tmp_path):
    """Strided paging: rows beyond parallelism * shard_rows must not be
    dropped (regression)."""
    db = str(tmp_path / "big.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE n (k INTEGER)")
    conn.executemany("INSERT INTO n VALUES (?)", [(i,) for i in range(1000)])
    conn.commit()
    conn.close()
    ds = rd.read_sql(
        "SELECT k FROM n ORDER BY k",
        lambda: sqlite3.connect(db),
        shard_rows=50,
        parallelism=4,  # 4 * 50 << 1000
    )
    assert sorted(r["k"] for r in ds.take_all()) == list(range(1000))


def test_read_webdataset_union_of_keys(cluster, tmp_path):
    """Extensions missing from the FIRST sample must still become
    columns (regression: first-row schema dropped later keys)."""
    import io

    shard = tmp_path / "mixed.tar"
    with tarfile.open(shard, "w") as tf:
        for name, payload in (
            ("0000.jpg", b"a"),          # first sample: jpg only
            ("0001.jpg", b"b"),
            ("0001.cls", b"7"),          # cls appears later
        ):
            info = tarfile.TarInfo(name=name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))
    rows = sorted(
        rd.read_webdataset(str(shard)).take_all(),
        key=lambda r: r["__key__"],
    )
    assert rows[0]["cls"] is None
    assert rows[1]["cls"] == b"7"


def test_read_images_mixed_sizes_without_resize(cluster, tmp_path):
    """One file per read task: mixed shapes read fine without size=
    (regression: grouped tasks crashed concatenating fixed-shape
    tensor columns)."""
    from PIL import Image

    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(tmp_path / "a.png")
    Image.fromarray(np.ones((8, 2, 3), np.uint8)).save(tmp_path / "B.PNG")
    rows = rd.read_images(str(tmp_path), parallelism=1).take_all()
    shapes = sorted(np.asarray(r["image"]).shape for r in rows)
    assert shapes == [(4, 4, 3), (8, 2, 3)]  # uppercase .PNG included
