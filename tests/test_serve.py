"""Serve: deployments, handles, routing, autoscaling, HTTP, batching.

Models the reference's serve test coverage (python/ray/serve/tests/).
"""
import asyncio
import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_session():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    serve.start(proxy=False)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture
def serve_session_http():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    serve.start(serve.HTTPOptions(host="127.0.0.1", port=18099))
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment(serve_session):
    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), name="fn_app", route_prefix=None)
    assert handle.remote(21).result(timeout_s=10) == 42
    serve.delete("fn_app")


def test_class_deployment_and_methods(serve_session):
    @serve.deployment
    class Greeter:
        def __init__(self, greeting):
            self.greeting = greeting

        def __call__(self, name):
            return f"{self.greeting}, {name}!"

        def shout(self, name):
            return f"{self.greeting.upper()} {name.upper()}"

    handle = serve.run(Greeter.bind("hello"), name="greet", route_prefix=None)
    assert handle.remote("world").result(timeout_s=10) == "hello, world!"
    assert handle.shout.remote("world").result(timeout_s=10) == "HELLO WORLD"
    serve.delete("greet")


def test_composition(serve_session):
    @serve.deployment
    class Adder:
        def __init__(self, amount):
            self.amount = amount

        def __call__(self, x):
            return x + self.amount

    @serve.deployment
    class Combiner:
        def __init__(self, a, b):
            self.a = a
            self.b = b

        async def __call__(self, x):
            return await self.a.remote(x) + await self.b.remote(x)

    app = Combiner.bind(Adder.options(name="A1").bind(1), Adder.options(name="A2").bind(2))
    handle = serve.run(app, name="comp", route_prefix=None)
    # (x+1) + (x+2) = 2x+3
    assert handle.remote(10).result(timeout_s=10) == 23
    serve.delete("comp")


def test_multiple_replicas_spread(serve_session):
    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __call__(self, _):
            return serve.get_replica_context().replica_id

    handle = serve.run(WhoAmI.bind(), name="spread", route_prefix=None)
    ids = {handle.remote(i).result(timeout_s=10) for i in range(30)}
    assert len(ids) >= 2, f"expected requests on >=2 replicas, saw {ids}"
    serve.delete("spread")


def test_status_and_redeploy_reconfigure(serve_session):
    @serve.deployment(user_config={"factor": 2})
    class Scaler:
        def __init__(self):
            self.factor = 1

        def reconfigure(self, cfg):
            self.factor = cfg["factor"]

        def __call__(self, x):
            return x * self.factor

    handle = serve.run(Scaler.bind(), name="cfg", route_prefix=None)
    assert handle.remote(10).result(timeout_s=10) == 20
    statuses = serve.status()
    assert statuses["cfg"].status.value == "RUNNING"
    assert statuses["cfg"].deployments["Scaler"].num_replicas == 1

    # Redeploy with a new user_config: reconfigured in place.
    handle = serve.run(
        Scaler.options(user_config={"factor": 5}).bind(), name="cfg",
        route_prefix=None,
    )
    deadline = time.time() + 15
    while time.time() < deadline:
        if handle.remote(10).result(timeout_s=10) == 50:
            break
        time.sleep(0.1)
    assert handle.remote(10).result(timeout_s=10) == 50
    serve.delete("cfg")


def test_autoscaling_up_and_down(serve_session):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1,
            upscale_delay_s=0.2,
            downscale_delay_s=1.0,
            metrics_interval_s=0.1,
            look_back_period_s=1.0,
        ),
        max_ongoing_requests=2,
    )
    class Slow:
        async def __call__(self, _):
            await asyncio.sleep(0.4)
            return serve.get_replica_context().replica_id

    handle = serve.run(Slow.bind(), name="auto", route_prefix=None)
    # Flood with concurrent requests to force upscale.
    responses = [handle.remote(i) for i in range(40)]
    ids = {r.result(timeout_s=60) for r in responses}
    assert len(ids) >= 2, f"expected autoscale to >=2 replicas, saw {len(ids)}"
    # Idle: scale back down to min_replicas.
    deadline = time.time() + 30
    while time.time() < deadline:
        info = serve.status()["auto"].deployments["Slow"]
        if info.num_replicas == 1:
            break
        time.sleep(0.25)
    assert serve.status()["auto"].deployments["Slow"].num_replicas == 1
    serve.delete("auto")


def test_http_proxy(serve_session_http):
    @serve.deployment
    class Echo:
        def __call__(self, request):
            if request.path.endswith("/sum"):
                data = request.json()
                return {"sum": sum(data["values"])}
            return "hello http"

    serve.run(Echo.bind(), name="web", route_prefix="/")
    base = "http://127.0.0.1:18099"
    with urllib.request.urlopen(f"{base}/") as resp:
        assert resp.read().decode() == "hello http"
    req = urllib.request.Request(
        f"{base}/sum", data=json.dumps({"values": [1, 2, 3]}).encode(),
        method="POST",
    )
    with urllib.request.urlopen(req) as resp:
        assert json.loads(resp.read()) == {"sum": 6}
    with urllib.request.urlopen(f"{base}/-/routes") as resp:
        assert json.loads(resp.read()) == {"/": "web"}
    serve.delete("web")


def test_batching(serve_session):
    @serve.deployment
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        async def handle_batch(self, xs):
            self.batch_sizes.append(len(xs))
            return [x * 10 for x in xs]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def seen_batches(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), name="batch", route_prefix=None)
    responses = [handle.remote(i) for i in range(16)]
    assert [r.result(timeout_s=20) for r in responses] == [i * 10 for i in range(16)]
    sizes = handle.seen_batches.remote().result(timeout_s=10)
    assert max(sizes) > 1, f"batching never coalesced: {sizes}"
    serve.delete("batch")


def test_multiplexed_models(serve_session):
    @serve.deployment
    class MuxModel:
        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            return {"id": model_id, "loaded_at": time.time()}

        async def __call__(self, _):
            model_id = serve.get_multiplexed_model_id()
            model = await self.get_model(model_id)
            return model["id"]

    handle = serve.run(MuxModel.bind(), name="mux", route_prefix=None)
    assert (
        handle.options(multiplexed_model_id="m1").remote(None).result(timeout_s=10)
        == "m1"
    )
    assert (
        handle.options(multiplexed_model_id="m2").remote(None).result(timeout_s=10)
        == "m2"
    )
    serve.delete("mux")


def test_failing_deployment_reports_deploy_failed(serve_session):
    """A crash-looping constructor surfaces DEPLOY_FAILED instead of
    hanging serve.run until timeout."""

    @serve.deployment
    class Broken:
        def __init__(self):
            raise RuntimeError("boom at init")

        def __call__(self, x):
            return x

    with pytest.raises(RuntimeError, match="Deploy failed"):
        serve.run(Broken.bind(), name="broken", route_prefix=None,
                  timeout_s=60)
    serve.delete("broken")


def test_replica_recovery_after_kill(serve_session):
    @serve.deployment(health_check_period_s=0.2)
    class Sturdy:
        def __call__(self, x):
            return x + 1

        def pid(self):
            import os

            return os.getpid()

    handle = serve.run(Sturdy.bind(), name="sturdy", route_prefix=None)
    assert handle.remote(1).result(timeout_s=10) == 2
    pid = handle.pid.remote().result(timeout_s=10)
    # Kill the replica's worker process out from under Serve.
    import signal
    import os

    os.kill(pid, signal.SIGKILL)
    # The controller's health checks replace the replica; requests keep
    # succeeding (routed around the dead replica, retried).
    deadline = time.time() + 40
    ok = False
    while time.time() < deadline:
        try:
            if handle.remote(5).result(timeout_s=10) == 6:
                new_pid = handle.pid.remote().result(timeout_s=10)
                if new_pid != pid:
                    ok = True
                    break
        except Exception:
            time.sleep(0.2)
    assert ok, "replica was not replaced after SIGKILL"
    serve.delete("sturdy")
