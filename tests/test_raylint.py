"""raylint: per-rule fixture tests + marker grammar + baseline flow.

Each rule gets a seeded-violation fixture (must fire) and a clean twin
(must not): the lint's own regression net. The final tests run the
real engine over the real tree and assert the repo itself lints clean
against its baseline — the CI contract.
"""
import json
import os
import subprocess
import sys

import pytest

from tools.raylint import (
    RULES,
    diff_baseline,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)
from tools.raylint.markers import parse_markers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def names(violations, rule=None):
    return [
        v.rule for v in violations if rule is None or v.rule == rule
    ]


# ------------------------------------------------------------ thread-domain


SEEDED_THREAD_DOMAIN = '''
# raylint: guarded-attrs=holders,owner_released
class Directory:
    def on_dispatch(self, entry, cid):
        entry.holders.add(cid)          # VIOLATION: unmarked function
        entry.owner_released = True     # VIOLATION
'''

CLEAN_THREAD_DOMAIN = '''
# raylint: guarded-attrs=holders,owner_released
class Directory:
    def __init__(self):
        self.holders = set()            # construction is legal

    # raylint: applier-only
    def apply(self, entry, cid):
        entry.holders.add(cid)
        entry.owner_released = True

    def read_only(self, entry):
        return len(entry.holders)       # reads are free
'''


def test_thread_domain_seeded():
    vs = lint_source(SEEDED_THREAD_DOMAIN, only=["thread-domain"])
    assert len(vs) == 2
    assert all(v.rule == "thread-domain" for v in vs)


def test_thread_domain_clean_twin():
    assert not lint_source(CLEAN_THREAD_DOMAIN, only=["thread-domain"])


def test_thread_domain_dispatch_calls_applier():
    src = '''
# raylint: guarded-attrs=holders
class D:
    # raylint: applier-only
    def _apply(self, e):
        e.holders.clear()
    # raylint: dispatch-only
    def handler(self, e):
        self._apply(e)
'''
    vs = lint_source(src, only=["thread-domain"])
    assert len(vs) == 1
    assert "calls applier-only" in vs[0].message


def test_thread_domain_nested_thread_target_not_dispatch():
    # A def nested inside a dispatch handler is usually a thread
    # target: calls it makes do NOT run on the dispatch thread and
    # must not be attributed to it (mirrors no-blocking-on-dispatch).
    src = '''
# raylint: guarded-attrs=holders
import threading
class D:
    # raylint: applier-only
    def _apply(self, e):
        e.holders.clear()
    # raylint: dispatch-only
    def handler(self, e):
        def _bg():
            self._apply(e)
        threading.Thread(target=_bg, daemon=True).start()
'''
    assert not lint_source(src, only=["thread-domain"])


def test_thread_domain_scoped_per_module():
    # No guarded-attrs declaration => rule is inert (gcs.py mutates
    # holder state legally under its own lock).
    src = "class D:\n    def f(self, e):\n        e.holders.add(1)\n"
    assert not lint_source(src, only=["thread-domain"])


# -------------------------------------------------- no-blocking-on-dispatch


SEEDED_BLOCKING = '''
# raylint: dispatch-handlers=_h_*
import time
class G:
    def _h_tick(self, state, msg):
        self._inner(msg)
    def _inner(self, msg):
        time.sleep(0.5)                # VIOLATION (transitive)
        data = open("/tmp/f").read()   # VIOLATION
        return data
'''

CLEAN_BLOCKING = '''
# raylint: dispatch-handlers=_h_*
import threading, time
class G:
    def _h_tick(self, state, msg):
        self._enqueue(msg)
        threading.Thread(target=self._bg, daemon=True).start()
    def _enqueue(self, msg):
        self.queue.append(msg)
    def _bg(self):
        time.sleep(0.5)  # its own thread: never CALLED from a handler
'''


def test_no_blocking_seeded():
    vs = lint_source(SEEDED_BLOCKING, only=["no-blocking-on-dispatch"])
    assert len(vs) == 2
    assert "reachable from dispatch handler 'G._h_tick'" in vs[0].message


def test_no_blocking_clean_twin():
    assert not lint_source(
        CLEAN_BLOCKING, only=["no-blocking-on-dispatch"]
    )


def test_no_blocking_explicit_marker_and_socket():
    src = '''
class Conn:
    # raylint: dispatch-only
    def deliver(self, sock):
        return sock.recv(4096)
'''
    vs = lint_source(src, only=["no-blocking-on-dispatch"])
    assert len(vs) == 1 and ".recv()" in vs[0].message


# ------------------------------------------------------- fixed-sleep-retry


SEEDED_SLEEP = '''
import time
def fetch(conn):
    for attempt in range(5):
        try:
            return conn.pull()
        except OSError:
            time.sleep(0.5)            # VIOLATION: fixed retry delay
'''

CLEAN_SLEEP_BACKOFF = '''
import time
from ray_tpu._private.chaos import Backoff
def fetch(conn):
    bo = Backoff(base_s=0.5)
    for attempt in range(5):
        try:
            return conn.pull()
        except OSError:
            time.sleep(bo.next_delay())  # on the one retry policy
'''

CLEAN_SLEEP_POLL = '''
import time
def monitor(self):
    while not self.shutdown:
        time.sleep(0.2)                # poll cadence, not a retry
        try:
            self.tick()
        except Exception:
            self.stats["errors"] = self.stats.get("errors", 0) + 1
'''


def test_fixed_sleep_seeded():
    vs = lint_source(SEEDED_SLEEP, only=["fixed-sleep-retry"])
    assert len(vs) == 1
    assert "chaos.Backoff" in vs[0].message


def test_fixed_sleep_clean_backoff_twin():
    assert not lint_source(CLEAN_SLEEP_BACKOFF, only=["fixed-sleep-retry"])


def test_fixed_sleep_poll_cadence_not_flagged():
    assert not lint_source(CLEAN_SLEEP_POLL, only=["fixed-sleep-retry"])


# ---------------------------------------------------- raw-send-on-gcs-path


SEEDED_RAW_SEND = '''
def report_done(self, spec):
    self.conn.send({"type": "task_done", "spec": spec})   # VIOLATION
'''

SEEDED_RAW_SEND_VIA_VAR = '''
def flush(self, client):
    msg = {"type": "ref_flush", "client": b"x"}
    client.conn.send(msg)                                  # VIOLATION
'''

CLEAN_RAW_SEND = '''
def report_done(self, spec):
    self.send_reliable({"type": "task_done", "spec": spec})

def lease(self):
    self.conn.send({"type": "return_lease"})   # not a reliable class
'''


def test_raw_send_seeded():
    vs = lint_source(SEEDED_RAW_SEND, only=["raw-send-on-gcs-path"])
    assert len(vs) == 1 and "task_done" in vs[0].message


def test_raw_send_resolves_local_dict():
    vs = lint_source(
        SEEDED_RAW_SEND_VIA_VAR, only=["raw-send-on-gcs-path"]
    )
    assert len(vs) == 1 and "ref_flush" in vs[0].message


def test_raw_send_clean_twin():
    assert not lint_source(CLEAN_RAW_SEND, only=["raw-send-on-gcs-path"])


def test_raw_send_suppression_with_reason():
    src = '''
def flush(self, client):
    # raylint: disable=raw-send-on-gcs-path -- at-least-once layer itself
    client.conn.send({"type": "ref_flush"})
'''
    assert not lint_source(src, only=["raw-send-on-gcs-path"])


# ---------------------------------------------------------- swallowed-fault


SEEDED_SWALLOW = '''
def pull(self):
    try:
        self.fetch()
    except Exception:
        pass                           # VIOLATION: silent swallow
'''

CLEAN_SWALLOW = '''
def pull(self):
    try:
        self.fetch()
    except Exception:
        self.stats["errors"] += 1      # counted, never silent

def seal(self):
    try:
        self.fetch()
    except Exception as e:
        self.reply(error=str(e))       # converted, not swallowed

def strict(self):
    try:
        self.fetch()
    except ValueError:
        pass                           # narrow except: out of scope
'''


def test_swallowed_fault_seeded():
    vs = lint_source(SEEDED_SWALLOW, only=["swallowed-fault"])
    assert len(vs) == 1


def test_swallowed_fault_clean_twin():
    assert not lint_source(CLEAN_SWALLOW, only=["swallowed-fault"])


def test_swallowed_fault_bare_except_and_record():
    src = '''
def f(self):
    try:
        self.g()
    except:
        pass
'''
    assert len(lint_source(src, only=["swallowed-fault"])) == 1
    src_ok = src.replace("pass", "_events.record('chaos', 'x', 'FAULT')")
    assert not lint_source(src_ok, only=["swallowed-fault"])


# ----------------------------------------------------------- event-taxonomy


def test_event_taxonomy_seeded():
    src = '''
from . import events as _events
def f():
    _events.record(_events.TASK, "tid", "TOTALLY_NOT_AN_EVENT", None)
'''
    vs = lint_source(src, only=["event-taxonomy"])
    assert len(vs) == 1
    assert "TOTALLY_NOT_AN_EVENT" in vs[0].message


def test_event_taxonomy_clean_twin():
    src = '''
from . import events as _events
def f():
    _events.record(_events.TASK, "tid", "SUBMITTED", None)
    _events.record(_events.REFS, "x", "SHARD_APPLY", {"ops": 1})
'''
    assert not lint_source(src, only=["event-taxonomy"])


def test_event_taxonomy_unknown_category():
    src = '''
def f(rec):
    rec.record("nonsense_category", "x", "SUBMITTED", None)
'''
    vs = lint_source(src, only=["event-taxonomy"])
    assert len(vs) == 1 and "category" in vs[0].message


def test_event_taxonomy_stitch_literals():
    src = '''
# raylint: check-event-literals
def stitch(ev):
    if ev["event"] == "NOT_REGISTERED_ROW":
        return 1
    if ev["event"] in ("SHARD_APPLY", "PULL_DONE"):
        return 2
'''
    vs = lint_source(src, only=["event-taxonomy"])
    assert len(vs) == 1
    assert "NOT_REGISTERED_ROW" in vs[0].message


def test_registry_covers_runtime_constants():
    """events.py's transition/span tables and state.py's stitch names
    must stay registered (the cross-check that keeps the registry the
    single source of truth)."""
    from ray_tpu._private import event_names, events

    for t in events.TASK_TRANSITIONS:
        assert event_names.is_registered(t), t
    for span in events._SPAN_KEYS:
        assert event_names.is_registered(span), span
    assert set(event_names.CATEGORIES) == {
        events.TASK, events.WORKER, events.LEASE, events.OBJECT,
        events.TRANSFER, events.SCHED, events.REFS, events.CHAOS,
        events.HEAD,
    }
    # The witness's finding event is registered under chaos.
    assert "LOCK_ORDER" in event_names.EVENTS_BY_CATEGORY["chaos"]


# ------------------------------------------------------------------ markers


def test_marker_grammar():
    mks = parse_markers(
        "# raylint: guarded-attrs=a,b\n"
        "x = 1  # raylint: disable=swallowed-fault -- known-benign\n"
        "# raylint: dispatch-only\n"
    )
    assert mks[0].directive == "guarded-attrs"
    assert mks[0].values == ["a", "b"]
    assert mks[0].own_line
    assert mks[1].directive == "disable"
    assert mks[1].values == ["swallowed-fault"]
    assert mks[1].reason == "known-benign"
    assert not mks[1].own_line
    assert mks[2].directive == "dispatch-only"


def test_bare_suppression_is_a_violation():
    src = '''
def f(self):
    try:
        self.g()
    except Exception:  # raylint: disable=swallowed-fault
        pass
'''
    vs = lint_source(src)
    assert names(vs) == ["bare-suppression"]
    with_reason = src.replace(
        "disable=swallowed-fault", "disable=swallowed-fault -- why not"
    )
    assert not lint_source(with_reason)


def test_function_scope_suppression():
    src = '''
# raylint: disable=swallowed-fault -- wrapper swallows by contract
def f(self):
    try:
        self.g()
    except Exception:
        pass
'''
    assert not lint_source(src)


# ----------------------------------------------------------------- baseline


def test_baseline_roundtrip(tmp_path):
    vs = lint_source(SEEDED_SWALLOW, path="m.py", only=["swallowed-fault"])
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), vs)
    data = json.loads(bl.read_text())
    assert data["version"] == 1
    assert len(data["violations"]) == 1
    # Same violations against the baseline: nothing new.
    new, fixed = diff_baseline(vs, load_baseline(str(bl)))
    assert not new and not fixed
    # A second identical swallow in the same function IS new (count).
    doubled = SEEDED_SWALLOW + (
        "\ndef g(self):\n    try:\n        self.fetch()\n"
        "    except Exception:\n        pass\n"
    )
    vs2 = lint_source(doubled, path="m.py", only=["swallowed-fault"])
    new, _ = diff_baseline(vs2, load_baseline(str(bl)))
    assert len(new) == 1
    # Fixing the original reports its fingerprint as stale.
    _, fixed = diff_baseline([], load_baseline(str(bl)))
    assert len(fixed) == 1


def test_fingerprint_stable_across_line_moves():
    a = lint_source(SEEDED_SWALLOW, path="m.py")
    b = lint_source("\n\n\n" + SEEDED_SWALLOW, path="m.py")
    assert [v.fingerprint for v in a] == [v.fingerprint for v in b]


# ------------------------------------------------------------- repo contract


def test_rule_catalogue_complete():
    assert set(RULES) >= {
        "thread-domain", "no-blocking-on-dispatch", "fixed-sleep-retry",
        "raw-send-on-gcs-path", "swallowed-fault", "event-taxonomy",
    }


def test_repo_lints_clean_against_baseline():
    """The CI gate, in-process: zero non-baselined violations."""
    violations, errors = lint_paths([os.path.join(REPO, "ray_tpu")], REPO)
    assert not errors
    baseline = load_baseline(
        os.path.join(REPO, "tools", "raylint", "baseline.json")
    )
    new, _fixed = diff_baseline(violations, baseline)
    assert not new, "\n".join(v.render() for v in new)


def test_cli_end_to_end():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.raylint"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "new" in proc.stdout


def test_cli_refuses_partial_baseline_write():
    """--write-baseline on a narrowed run would wipe the full-scope
    debt; the CLI must refuse rather than corrupt the baseline."""
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.raylint",
            "ray_tpu/_private/state.py", "--write-baseline",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "refusing" in proc.stderr
