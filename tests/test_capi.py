"""C ABI client (native/rtpu_client.c): a pure-C caller drives a live
actor over the direct socket — frame codec, HMAC handshake, pickle
writer/reader all independently implemented in C, so this also
cross-validates the fastpath wire format end to end.

Reference contrast: the reference's cpp/ worker API hosts actors and
tasks in C++; ray_tpu's compute path is jax/Python by design, so the C
surface targets the embed case (a C/C++ service calling a deployed
actor). See native/rtpu_client.h.
"""
import os
import subprocess
import time

import pytest

import ray_tpu

BUILD = os.path.join(
    os.path.dirname(__file__), "..", "ray_tpu", "_private", "_native"
)
BIN = os.path.join(BUILD, "rtpu_client_test")


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def client_bin():
    if not os.path.exists(BIN):
        subprocess.run(
            ["make", os.path.relpath(BIN, "native")],
            cwd=os.path.join(os.path.dirname(__file__), "..", "native"),
            check=True,
            capture_output=True,
        )
    return BIN


@ray_tpu.remote
class Target:
    def ping(self):
        return "pong"

    def add(self, a, b):
        return a + b

    def add1(self, a):
        return a + 1

    def fmul(self, x):
        return x * 2.0

    def echo_len(self, b):
        assert isinstance(b, bytes)
        return len(b)

    def greet(self, name):
        return f"hello {name}"

    def boom(self):
        raise ValueError("kaboom")


def _direct_info(handle):
    """(direct_addr, aid_hex, authkey_hex) for a live actor."""
    from ray_tpu._private.worker import global_client

    client = global_client()
    aid = handle._actor_id.binary()
    deadline = time.time() + 30
    while time.time() < deadline:
        reply = client.request({"type": "get_actor_direct", "actor_id": aid})
        if reply.get("addr"):
            return reply["addr"], aid.hex(), client._authkey.hex()
        time.sleep(0.1)
    raise TimeoutError("actor direct addr not available")


def test_c_client_calls_live_actor(cluster, client_bin):
    t = Target.remote()
    assert ray_tpu.get(t.ping.remote()) == "pong"  # ensure ALIVE
    addr, aid_hex, key_hex = _direct_info(t)

    out = subprocess.run(
        [client_bin, addr, key_hex, aid_hex],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert out.returncode == 0, out.stderr
    lines = out.stdout.strip().splitlines()
    assert "ping str pong" in lines
    assert "add int 42" in lines
    assert "add1 int 1234567890123456790" in lines
    assert "fmul float 3" in lines
    assert "echo_len int 300" in lines
    assert "greet str hello wörld" in lines
    assert "boom rc -3" in lines  # RTPU_ERR_REMOTE, conn survives
    assert "ping2 str pong" in lines
    assert lines[-1] == "ok"

    # The Python side still talks to the same actor afterwards.
    assert ray_tpu.get(t.add.remote(1, 2)) == 3
