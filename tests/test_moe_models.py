"""MoE (expert parallelism) + GPT model family tests on the CPU mesh.

Runs under the conftest's 8-virtual-device CPU backend.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_moe():
    from ray_tpu.models.mixtral import CONFIGS, MixtralForCausalLM

    cfg = CONFIGS["mixtral-tiny"]
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, remat=False)
    model = MixtralForCausalLM(cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    return cfg, model, ids, params


def test_moe_forward_finite(tiny_moe):
    cfg, model, ids, params = tiny_moe
    logits = model.apply(params, ids)
    assert logits.shape == (4, 32, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_moe_dispatch_matches_naive_gather(tiny_moe):
    """The dense dispatch/combine einsums must equal a per-token gather
    reference (same experts, same gates, no capacity drops)."""
    import dataclasses

    from ray_tpu.models.mixtral import MoELayer

    cfg, _, _, _ = tiny_moe
    # Huge capacity so nothing is dropped in the comparison.
    cfg = dataclasses.replace(cfg, capacity_factor=10.0)
    layer = MoELayer(cfg)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 16, cfg.hidden_size), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)
    out = layer.apply(params, x)

    # Naive reference: per-token top-k gather through each expert's FFN.
    p = params["params"]
    router_w = np.asarray(p["router"]["kernel"], np.float64)
    wg = np.asarray(p["w_gate"], np.float64)
    wu = np.asarray(p["w_up"], np.float64)
    wd = np.asarray(p["w_down"], np.float64)
    xs = np.asarray(x, np.float64)
    B, T, D = xs.shape
    logits = xs @ router_w
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros_like(xs)
    for b in range(B):
        for t in range(T):
            topk = np.argsort(-probs[b, t])[: cfg.num_experts_per_tok]
            gates = probs[b, t, topk]
            gates = gates / gates.sum()
            acc = np.zeros(D)
            for gate, e in zip(gates, topk):
                h = xs[b, t] @ wg[e]
                u = xs[b, t] @ wu[e]
                silu = h / (1 + np.exp(-h))
                acc += gate * ((silu * u) @ wd[e])
            want[b, t] = acc
    np.testing.assert_allclose(np.asarray(out), want, atol=2e-3, rtol=2e-3)


def test_moe_capacity_drops_tokens(tiny_moe):
    """With capacity 0-ish, combine weights vanish: output ≈ 0."""
    import dataclasses

    from ray_tpu.models.mixtral import MoELayer

    cfg, _, _, _ = tiny_moe
    cfg = dataclasses.replace(
        cfg, capacity_factor=1e-9, moe_dispatch="capacity"
    )
    layer = MoELayer(cfg)
    x = jnp.asarray(np.random.RandomState(2).randn(2, 16, cfg.hidden_size),
                    jnp.float32)
    params = layer.init(jax.random.PRNGKey(2), x)
    out = layer.apply(params, x)
    # Capacity C=max(1, ...)=1: only the first token per expert survives.
    per_token = np.abs(np.asarray(out)).sum(-1)
    assert (per_token[:, -1] == 0).all() or per_token[:, -1].max() < 1e-6


def test_moe_train_step_on_expert_mesh(tiny_moe):
    """Full train step with an expert-parallel mesh axis: GSPMD compiles
    the dispatch all-to-all; loss is finite and params update."""
    import optax

    from ray_tpu.models.mixtral import moe_lm_loss
    from ray_tpu.parallel import MeshSpec, shard_params

    import dataclasses

    from ray_tpu.models.mixtral import MixtralForCausalLM

    cfg, _, ids, params = tiny_moe
    # Expert parallelism uses the capacity dispatch (explicit [E,...]
    # expert axis for the GSPMD all-to-all); param structure is
    # identical across dispatch modes, so the fixture params reuse.
    model = MixtralForCausalLM(
        dataclasses.replace(cfg, moe_dispatch="capacity")
    )
    mesh = MeshSpec(data=2, expert=4).build()
    targets = jnp.roll(ids, -1, axis=1)
    with jax.set_mesh(mesh):
        params_s = shard_params(params, mesh)
        tx = optax.adam(1e-3)
        opt_state = tx.init(params_s)

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(
                lambda p: moe_lm_loss(model, p, ids, targets)
            )(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        p1, opt_state, loss1 = step(params_s, opt_state)
        p2, _, loss2 = step(p1, opt_state)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss1)  # aux+LM loss decreasing on same batch
    # Expert weights actually sharded over the expert axis.
    w = p1["params"]["layers_0"]["moe"]["w_gate"]
    spec = w.sharding.spec
    assert spec[0] == "expert", f"expert axis not sharded: {spec}"


def test_moe_aux_loss_balances(tiny_moe):
    """Router aux loss = E * sum_e(frac_tokens_e * frac_probs_e); for a
    near-uniform router at init, frac_tokens sums to K and frac_probs
    to 1, so the expected value is ~K (= num_experts_per_tok)."""
    cfg, model, ids, params = tiny_moe
    K = cfg.num_experts_per_tok
    _, state = model.apply(params, ids, mutable=["intermediates"])
    leaves = jax.tree_util.tree_leaves(state["intermediates"])
    assert leaves, "router_aux_loss not sown"
    for aux in leaves:
        assert 0.5 * K < float(aux) < 2.0 * K


def test_gpt_forward_and_grads():
    import dataclasses

    from ray_tpu.models.gpt import CONFIGS, GPTForCausalLM
    from ray_tpu.models.llama import causal_lm_loss

    cfg = dataclasses.replace(CONFIGS["gpt2-tiny"], dtype=jnp.float32,
                              remat=False)
    model = GPTForCausalLM(cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 32, cfg.vocab_size)
    loss, grads = jax.value_and_grad(
        lambda p: causal_lm_loss(model.apply(p, ids), jnp.roll(ids, -1, 1))
    )(params)
    assert np.isfinite(float(loss))
    gnorm = sum(
        float(jnp.abs(g).sum()) for g in jax.tree_util.tree_leaves(grads)
    )
    assert gnorm > 0


def test_ragged_and_capacity_dispatch_agree(tiny_moe):
    """With ample capacity (no drops) the two dispatch backends are the
    same mathematical function — identical params, matching outputs."""
    import dataclasses

    from ray_tpu.models.mixtral import MoELayer

    cfg, _, _, _ = tiny_moe
    x = jnp.asarray(
        np.random.RandomState(3).randn(2, 16, cfg.hidden_size), jnp.float32
    )
    ragged = MoELayer(dataclasses.replace(cfg, moe_dispatch="ragged"))
    cap = MoELayer(
        dataclasses.replace(cfg, moe_dispatch="capacity", capacity_factor=8.0)
    )
    params = ragged.init(jax.random.PRNGKey(4), x)
    out_r = ragged.apply(params, x)
    out_c = cap.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(out_r), np.asarray(out_c), rtol=2e-4, atol=2e-4
    )


def test_gmm_dispatch_agrees_with_ragged(tiny_moe, monkeypatch):
    """The pallas grouped-matmul backend (interpret mode on CPU) is the
    same mathematical function as the exact ragged dispatch — outputs
    AND gradients."""
    import dataclasses

    from ray_tpu.models.mixtral import MoELayer

    monkeypatch.setenv("RAY_TPU_PALLAS_INTERPRET", "1")
    cfg, _, _, _ = tiny_moe
    x = jnp.asarray(
        np.random.RandomState(5).randn(2, 16, cfg.hidden_size), jnp.float32
    )
    ragged = MoELayer(dataclasses.replace(cfg, moe_dispatch="ragged"))
    gmm_l = MoELayer(dataclasses.replace(cfg, moe_dispatch="gmm"))
    params = ragged.init(jax.random.PRNGKey(4), x)
    out_r = ragged.apply(params, x)
    out_g = gmm_l.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(out_r), np.asarray(out_g), rtol=2e-4, atol=2e-4
    )

    def loss(layer):
        def f(p, x):
            return (layer.apply(p, x) ** 2).sum()

        return jax.grad(f, argnums=(0, 1))(params, x)

    gp_r, gx_r = loss(ragged)
    gp_g, gx_g = loss(gmm_l)
    np.testing.assert_allclose(
        np.asarray(gx_r), np.asarray(gx_g), rtol=5e-3, atol=5e-3
    )
    flat_r = jax.tree_util.tree_leaves(gp_r)
    flat_g = jax.tree_util.tree_leaves(gp_g)
    for a, b in zip(flat_r, flat_g):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3
        )


def test_moe_dispatch_auto_resolution(tiny_moe, monkeypatch, tmp_path):
    """"auto" resolves via a measured probe, caches to disk, and forces
    capacity under an expert-sharded mesh."""
    import dataclasses

    from ray_tpu.models import mixtral as mx

    cfg, _, _, _ = tiny_moe
    auto_cfg = dataclasses.replace(cfg, moe_dispatch="auto")

    # Env override wins without probing.
    monkeypatch.setenv("RAY_TPU_MOE_DISPATCH", "ragged")
    mx._RESOLVED.clear()
    assert mx.resolve_moe_dispatch(auto_cfg) == "ragged"
    monkeypatch.delenv("RAY_TPU_MOE_DISPATCH")

    # Expert-sharded mesh forces the EP-capable capacity layout.
    from ray_tpu.parallel import MeshSpec

    mesh = MeshSpec(data=2, expert=4).build()
    mx._RESOLVED.clear()
    assert mx.resolve_moe_dispatch(auto_cfg, mesh=mesh) == "capacity"

    # Measured probe on this backend: must return a working backend and
    # persist it (gmm needs interpret mode to be probe-able on CPU).
    monkeypatch.setenv("HOME", str(tmp_path))
    monkeypatch.setenv("RAY_TPU_PALLAS_INTERPRET", "1")
    mx._RESOLVED.clear()
    winner = mx.resolve_moe_dispatch(auto_cfg, tokens=64, steps=1)
    assert winner in ("capacity", "gmm")
    cache = tmp_path / ".cache" / "ray_tpu" / "moe_dispatch.json"
    assert cache.exists()
    # Cached: a fresh in-process resolution short-circuits to the same.
    mx._RESOLVED.clear()
    assert mx.resolve_moe_dispatch(auto_cfg) == winner
