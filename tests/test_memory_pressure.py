"""Memory-pressure ladder: object spilling to disk + OOM worker killing.

Reference behavior: src/ray/raylet/local_object_manager.h:41-110 (spill
under pressure, restore on get), src/ray/common/memory_monitor.h:52 and
worker_killing_policy_retriable_fifo.h (kill newest retriable task
first; non-retriable fail with OutOfMemoryError).
"""
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import OutOfMemoryError


def _native_pool_available() -> bool:
    from ray_tpu._private.native_store import native_available

    return native_available()


@pytest.mark.skipif(
    not _native_pool_available(),
    reason="spilling manages the native pool arena; no native store here",
)
def test_spilling_keeps_live_objects_readable(tmp_path):
    """2x the pool size of live-ref'd objects: every get still returns
    (cold objects spill to disk and reads fall back to the file)."""
    pool_bytes = 32 << 20
    spill_dir = str(tmp_path / "spill")
    ray_tpu.init(
        num_cpus=2,
        ignore_reinit_error=True,
        _system_config={
            "object_store_memory_bytes": pool_bytes,
            "object_spilling_directory": spill_dir,
            "object_spilling_threshold": 0.5,
        },
    )
    try:
        from ray_tpu._private.worker import global_client

        client = global_client()
        each = 2 << 20  # 2 MiB per object
        n = (2 * pool_bytes) // each  # 2x pool size, all live refs
        refs = []
        for i in range(n):
            refs.append(ray_tpu.put(np.full(each // 4, i, dtype=np.int32)))
            # Deterministic: drive the spill rung directly instead of
            # sleep-polling the 0.2s monitor cadence (the old
            # time.sleep(0.02) waits made this test a flake magnet).
            if i % 4 == 3:
                client.request({"type": "spill_tick"})
        client.request({"type": "spill_tick"})
        spilled = os.listdir(spill_dir) if os.path.isdir(spill_dir) else []
        assert spilled, "no objects were spilled at 2x pool occupancy"
        # Every object — spilled or resident — still reads correctly.
        for i, ref in enumerate(refs):
            arr = ray_tpu.get(ref)
            assert arr[0] == i and arr[-1] == i
    finally:
        ray_tpu.shutdown()


def test_oom_kills_nonretriable_with_oom_error(tmp_path):
    usage_file = tmp_path / "usage"
    usage_file.write_text("0.10")
    ray_tpu.init(
        num_cpus=2,
        ignore_reinit_error=True,
        _system_config={
            "testing_memory_usage_file": str(usage_file),
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 100,
        },
    )
    try:
        @ray_tpu.remote(max_retries=0)
        def hog():
            time.sleep(60)
            return "survived"

        # A function's first-ever call ships its blob through the GCS
        # route, so the GCS schedules (and can OOM-target) the worker.
        ref = hog.remote()
        time.sleep(1.0)  # task running
        usage_file.write_text("0.97")  # breach the threshold
        with pytest.raises(OutOfMemoryError):
            ray_tpu.get(ref, timeout=30)
        usage_file.write_text("0.10")
    finally:
        ray_tpu.shutdown()


def test_oom_group_by_owner_fairness_two_jobs(tmp_path):
    """Kill-ladder fairness tier (reference:
    worker_killing_policy_group_by_owner.h): under memory pressure with
    job A running a 3-task burst (submitted from inside a worker — its
    own owner/client id) and job B running one task (the driver), the
    victim comes from job A's burst. Job B's single task must complete
    without ever being killed."""
    usage_file = tmp_path / "usage"
    usage_file.write_text("0.10")
    ray_tpu.init(
        num_cpus=8,
        ignore_reinit_error=True,
        _system_config={
            "testing_memory_usage_file": str(usage_file),
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 300,
        },
    )
    try:
        flag_a = str(tmp_path / "job_a_attempts")
        flag_b = str(tmp_path / "job_b_attempts")

        @ray_tpu.remote(max_retries=3)
        def hog(path, dep, hold_s):
            with open(path, "a") as f:
                f.write("attempt\n")
            t0 = time.time()
            while time.time() - t0 < hold_s:
                time.sleep(0.05)
            return "done"

        @ray_tpu.remote(max_retries=0)
        def spawner(path, dep):
            # Job A: this worker process is the submitting client for
            # three hogs (a dep ref keeps them on the GCS route, where
            # the monitor can see and target them).
            d2 = ray_tpu.put(b"y")
            refs = [hog.remote(path, d2, 6.0) for _ in range(3)]
            return ray_tpu.get(refs, timeout=90)

        dep = ray_tpu.put(b"x")
        b_ref = hog.remote(flag_b, dep, 5.0)  # job B: one task
        s_ref = spawner.remote(flag_a, dep)
        # Wait until all four hogs are running.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            a_n = (
                len(open(flag_a).readlines())
                if os.path.exists(flag_a)
                else 0
            )
            b_n = (
                len(open(flag_b).readlines())
                if os.path.exists(flag_b)
                else 0
            )
            if a_n >= 3 and b_n >= 1:
                break
            time.sleep(0.1)
        assert a_n >= 3 and b_n >= 1, "hogs never started"
        time.sleep(0.3)
        usage_file.write_text("0.97")  # one-ish monitor tick of pressure
        time.sleep(0.45)
        usage_file.write_text("0.10")
        # Both jobs complete; the burst (job A) absorbed the kill(s).
        assert ray_tpu.get(b_ref, timeout=60) == "done"
        assert ray_tpu.get(s_ref, timeout=120) == ["done"] * 3
        with open(flag_a) as f:
            a_attempts = len(f.readlines())
        with open(flag_b) as f:
            b_attempts = len(f.readlines())
        assert b_attempts == 1, (
            f"job B's single task was killed ({b_attempts} attempts) "
            "while job A ran a 3-task burst"
        )
        assert a_attempts >= 4, (
            "no job-A task was killed — the pressure tick never fired?"
        )
    finally:
        ray_tpu.shutdown()


def test_oom_prefers_retriable_and_resubmits(tmp_path):
    usage_file = tmp_path / "usage"
    usage_file.write_text("0.10")
    ray_tpu.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={
            "testing_memory_usage_file": str(usage_file),
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 100,
        },
    )
    try:
        flag = str(tmp_path / "attempt")

        @ray_tpu.remote(max_retries=2)
        def retriable(flag_path):
            # First attempt parks (gets OOM-killed); the resubmitted
            # attempt returns immediately.
            if not os.path.exists(flag_path):
                with open(flag_path, "w") as f:
                    f.write("1")
                time.sleep(60)
            return "second attempt"

        ref = retriable.remote(flag)
        deadline = time.monotonic() + 15
        while not os.path.exists(flag) and time.monotonic() < deadline:
            time.sleep(0.1)
        assert os.path.exists(flag), "task never started"
        time.sleep(0.3)
        usage_file.write_text("0.97")
        time.sleep(0.5)
        usage_file.write_text("0.10")  # recover so the retry survives
        assert ray_tpu.get(ref, timeout=30) == "second attempt"
    finally:
        ray_tpu.shutdown()


@pytest.mark.skipif(
    not _native_pool_available(),
    reason="spilling manages the native pool arena; no native store here",
)
def test_truncated_spill_file_never_returns_garbage(tmp_path):
    """Regression (ISSUE 10): a hand-truncated spill file must NEVER
    restore as silently wrong bytes. A put object (no lineage) resolves
    ObjectLostError — the directory drops the bad file and answers LOST
    — while a task-produced object reconstructs through lineage on the
    next get (correct bytes, not an error)."""
    from ray_tpu._private.object_store import spill_path
    from ray_tpu._private.worker import _global, global_client
    from ray_tpu.exceptions import ObjectLostError

    pool_bytes = 8 << 20
    spill_dir = str(tmp_path / "spill")
    ray_tpu.init(
        num_cpus=2,
        ignore_reinit_error=True,
        _system_config={
            "object_store_memory_bytes": pool_bytes,
            "object_spilling_directory": spill_dir,
            "object_spilling_threshold": 0.3,
        },
    )
    try:
        client = global_client()
        gcs = _global.node.gcs

        def truncate(ref):
            path = spill_path(spill_dir, ref.id())
            with open(path, "r+b") as f:
                f.truncate(os.path.getsize(path) // 2)
            return path

        def spilled_of(refs):
            return [
                r for r in refs
                if (e := gcs.objects.get(r.id().binary())) is not None
                and e.spilled_path is not None
            ]

        # -- put object (no lineage): corrupt spill resolves LOST.
        refs = [
            ray_tpu.put(np.full(256 * 1024, i, dtype=np.int32))
            for i in range(8)
        ]
        client.request({"type": "spill_tick"})
        spilled = spilled_of(refs)
        assert spilled, "nothing spilled at 4x the threshold"
        victim = spilled[0]
        path = truncate(victim)
        # The driver holds no local copy (puts went straight to pool and
        # the pool copy was freed by the spill) — the get must detect
        # the corruption and fail LOST, never return truncated bytes.
        with pytest.raises(ObjectLostError):
            ray_tpu.get(victim, timeout=30)
        # The head validates the report (and unlinks the bad file) on a
        # background thread — poll briefly for the drop to land.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and os.path.exists(path):
            time.sleep(0.05)
        assert not os.path.exists(path), "corrupt spill file not dropped"
        entry = gcs.objects.get(victim.id().binary())
        assert entry is None or entry.spilled_path is None
        # Untouched spilled objects still restore bit-exact.
        for r in spilled[1:]:
            i = refs.index(r)
            arr = ray_tpu.get(r, timeout=30)
            assert arr[0] == i and arr[-1] == i
        ray_tpu.free(refs)

        # -- task result (lineage): corrupt spill reconstructs.
        @ray_tpu.remote(max_retries=3)
        def make(i):
            return np.full(256 * 1024, i, dtype=np.int32)

        made = [make.remote(i) for i in range(6)]
        vals = ray_tpu.get(made, timeout=60)
        assert all(int(v[0]) == i for i, v in enumerate(vals))
        del vals
        client.request({"type": "spill_tick"})
        spilled = spilled_of(made)
        if spilled:
            victim = spilled[0]
            i = made.index(victim)
            truncate(victim)
            try:
                client.store.delete(victim.id())  # drop any local replica
            except Exception:  # noqa: BLE001
                pass
            arr = ray_tpu.get(victim, timeout=60)
            assert arr[0] == i and arr[-1] == i, \
                "reconstruction returned junk"
    finally:
        ray_tpu.shutdown()


@pytest.mark.skipif(
    not _native_pool_available(),
    reason="put backpressure gates on the native pool arena",
)
def test_put_backpressure_waits_for_spill(tmp_path):
    """A put against a full pool blocks (bounded) while the spill rung
    frees space, instead of immediately overflowing — and completes
    once the ladder has run."""
    import threading

    from ray_tpu._private.worker import global_client

    pool_bytes = 16 << 20
    spill_dir = str(tmp_path / "spill")
    ray_tpu.init(
        num_cpus=1,
        ignore_reinit_error=True,
        _system_config={
            "object_store_memory_bytes": pool_bytes,
            "object_spilling_directory": spill_dir,
            "object_spilling_threshold": 0.8,
            "put_backpressure_timeout_s": 8.0,
        },
    )
    try:
        client = global_client()
        # Fill the pool (threshold high so the monitor stays quiet).
        refs = [
            ray_tpu.put(np.zeros(512 * 1024, dtype=np.int32))
            for i in range(7)
        ]
        # Spill ticks a moment later free space; the blocked put must
        # complete within the backpressure window (not fall to an
        # unbounded segment the instant the pool is full).
        ticker_stop = threading.Event()

        def tick():
            while not ticker_stop.wait(0.3):
                client.request({"type": "spill_tick"})

        t = threading.Thread(target=tick, daemon=True)
        t.start()
        try:
            late = ray_tpu.put(np.full(512 * 1024, 7, dtype=np.int32))
            arr = ray_tpu.get(late, timeout=30)
            assert arr[0] == 7 and arr[-1] == 7
        finally:
            ticker_stop.set()
            t.join(5)
        for r in refs:
            assert ray_tpu.get(r, timeout=30)[0] == 0
    finally:
        ray_tpu.shutdown()
