"""Memory-pressure ladder: object spilling to disk + OOM worker killing.

Reference behavior: src/ray/raylet/local_object_manager.h:41-110 (spill
under pressure, restore on get), src/ray/common/memory_monitor.h:52 and
worker_killing_policy_retriable_fifo.h (kill newest retriable task
first; non-retriable fail with OutOfMemoryError).
"""
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import OutOfMemoryError


def _native_pool_available() -> bool:
    from ray_tpu._private.native_store import native_available

    return native_available()


@pytest.mark.skipif(
    not _native_pool_available(),
    reason="spilling manages the native pool arena; no native store here",
)
def test_spilling_keeps_live_objects_readable(tmp_path):
    """2x the pool size of live-ref'd objects: every get still returns
    (cold objects spill to disk and reads fall back to the file)."""
    pool_bytes = 32 << 20
    spill_dir = str(tmp_path / "spill")
    ray_tpu.init(
        num_cpus=2,
        ignore_reinit_error=True,
        _system_config={
            "object_store_memory_bytes": pool_bytes,
            "object_spilling_directory": spill_dir,
            "object_spilling_threshold": 0.5,
        },
    )
    try:
        each = 2 << 20  # 2 MiB per object
        n = (2 * pool_bytes) // each  # 2x pool size, all live refs
        refs = []
        for i in range(n):
            refs.append(ray_tpu.put(np.full(each // 4, i, dtype=np.int32)))
            time.sleep(0.02)  # give the spill monitor ticks to run
        deadline = time.monotonic() + 20
        spilled = []
        while time.monotonic() < deadline:
            spilled = os.listdir(spill_dir) if os.path.isdir(spill_dir) else []
            if spilled:
                break
            time.sleep(0.2)
        assert spilled, "no objects were spilled at 2x pool occupancy"
        # Every object — spilled or resident — still reads correctly.
        for i, ref in enumerate(refs):
            arr = ray_tpu.get(ref)
            assert arr[0] == i and arr[-1] == i
    finally:
        ray_tpu.shutdown()


def test_oom_kills_nonretriable_with_oom_error(tmp_path):
    usage_file = tmp_path / "usage"
    usage_file.write_text("0.10")
    ray_tpu.init(
        num_cpus=2,
        ignore_reinit_error=True,
        _system_config={
            "testing_memory_usage_file": str(usage_file),
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 100,
        },
    )
    try:
        @ray_tpu.remote(max_retries=0)
        def hog():
            time.sleep(60)
            return "survived"

        # A function's first-ever call ships its blob through the GCS
        # route, so the GCS schedules (and can OOM-target) the worker.
        ref = hog.remote()
        time.sleep(1.0)  # task running
        usage_file.write_text("0.97")  # breach the threshold
        with pytest.raises(OutOfMemoryError):
            ray_tpu.get(ref, timeout=30)
        usage_file.write_text("0.10")
    finally:
        ray_tpu.shutdown()


def test_oom_group_by_owner_fairness_two_jobs(tmp_path):
    """Kill-ladder fairness tier (reference:
    worker_killing_policy_group_by_owner.h): under memory pressure with
    job A running a 3-task burst (submitted from inside a worker — its
    own owner/client id) and job B running one task (the driver), the
    victim comes from job A's burst. Job B's single task must complete
    without ever being killed."""
    usage_file = tmp_path / "usage"
    usage_file.write_text("0.10")
    ray_tpu.init(
        num_cpus=8,
        ignore_reinit_error=True,
        _system_config={
            "testing_memory_usage_file": str(usage_file),
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 300,
        },
    )
    try:
        flag_a = str(tmp_path / "job_a_attempts")
        flag_b = str(tmp_path / "job_b_attempts")

        @ray_tpu.remote(max_retries=3)
        def hog(path, dep, hold_s):
            with open(path, "a") as f:
                f.write("attempt\n")
            t0 = time.time()
            while time.time() - t0 < hold_s:
                time.sleep(0.05)
            return "done"

        @ray_tpu.remote(max_retries=0)
        def spawner(path, dep):
            # Job A: this worker process is the submitting client for
            # three hogs (a dep ref keeps them on the GCS route, where
            # the monitor can see and target them).
            d2 = ray_tpu.put(b"y")
            refs = [hog.remote(path, d2, 6.0) for _ in range(3)]
            return ray_tpu.get(refs, timeout=90)

        dep = ray_tpu.put(b"x")
        b_ref = hog.remote(flag_b, dep, 5.0)  # job B: one task
        s_ref = spawner.remote(flag_a, dep)
        # Wait until all four hogs are running.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            a_n = (
                len(open(flag_a).readlines())
                if os.path.exists(flag_a)
                else 0
            )
            b_n = (
                len(open(flag_b).readlines())
                if os.path.exists(flag_b)
                else 0
            )
            if a_n >= 3 and b_n >= 1:
                break
            time.sleep(0.1)
        assert a_n >= 3 and b_n >= 1, "hogs never started"
        time.sleep(0.3)
        usage_file.write_text("0.97")  # one-ish monitor tick of pressure
        time.sleep(0.45)
        usage_file.write_text("0.10")
        # Both jobs complete; the burst (job A) absorbed the kill(s).
        assert ray_tpu.get(b_ref, timeout=60) == "done"
        assert ray_tpu.get(s_ref, timeout=120) == ["done"] * 3
        with open(flag_a) as f:
            a_attempts = len(f.readlines())
        with open(flag_b) as f:
            b_attempts = len(f.readlines())
        assert b_attempts == 1, (
            f"job B's single task was killed ({b_attempts} attempts) "
            "while job A ran a 3-task burst"
        )
        assert a_attempts >= 4, (
            "no job-A task was killed — the pressure tick never fired?"
        )
    finally:
        ray_tpu.shutdown()


def test_oom_prefers_retriable_and_resubmits(tmp_path):
    usage_file = tmp_path / "usage"
    usage_file.write_text("0.10")
    ray_tpu.init(
        num_cpus=4,
        ignore_reinit_error=True,
        _system_config={
            "testing_memory_usage_file": str(usage_file),
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 100,
        },
    )
    try:
        flag = str(tmp_path / "attempt")

        @ray_tpu.remote(max_retries=2)
        def retriable(flag_path):
            # First attempt parks (gets OOM-killed); the resubmitted
            # attempt returns immediately.
            if not os.path.exists(flag_path):
                with open(flag_path, "w") as f:
                    f.write("1")
                time.sleep(60)
            return "second attempt"

        ref = retriable.remote(flag)
        deadline = time.monotonic() + 15
        while not os.path.exists(flag) and time.monotonic() < deadline:
            time.sleep(0.1)
        assert os.path.exists(flag), "task never started"
        time.sleep(0.3)
        usage_file.write_text("0.97")
        time.sleep(0.5)
        usage_file.write_text("0.10")  # recover so the retry survives
        assert ray_tpu.get(ref, timeout=30) == "second attempt"
    finally:
        ray_tpu.shutdown()
