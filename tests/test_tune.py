"""ray_tpu.tune tests (reference strategy: tune/tests with mock
trainables and deterministic search spaces)."""
import os

import pytest

import ray_tpu
from ray_tpu import tune


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _quadratic(config):
    # max of -(x-3)^2 at x=3
    for i in range(5):
        tune.report({"score": -((config["x"] - 3.0) ** 2) - 0.01 * (5 - i)})


def test_grid_search(cluster, tmp_path):
    results = tune.Tuner(
        _quadratic,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=ray_tpu.train.RunConfig(storage_path=str(tmp_path),
                                           name="grid"),
    ).fit()
    assert len(results) == 4
    best = results.get_best_result()
    assert best.config["x"] == 3.0
    assert abs(best.metrics["score"]) < 0.1


def test_random_search_num_samples(cluster, tmp_path):
    results = tune.Tuner(
        _quadratic,
        param_space={"x": tune.uniform(0, 6)},
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=6),
        run_config=ray_tpu.train.RunConfig(storage_path=str(tmp_path),
                                           name="rand"),
    ).fit()
    assert len(results) == 6
    assert not results.errors


def test_trainable_class_and_checkpointing(cluster, tmp_path):
    class Counter(tune.Trainable):
        def setup(self, config):
            self.total = 0
            self.inc = config["inc"]

        def step(self):
            self.total += self.inc
            return {"total": self.total}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "state.txt"), "w") as f:
                f.write(str(self.total))
            return d

        def load_checkpoint(self, d):
            with open(os.path.join(d, "state.txt")) as f:
                self.total = int(f.read())

    rc = ray_tpu.train.RunConfig(storage_path=str(tmp_path), name="cls",
                                 stop={"training_iteration": 4})
    results = tune.Tuner(
        Counter,
        param_space={"inc": tune.grid_search([1, 10])},
        tune_config=tune.TuneConfig(metric="total", mode="max"),
        run_config=rc,
    ).fit()
    assert len(results) == 2
    best = results.get_best_result()
    assert best.metrics["total"] == 40
    assert best.checkpoint is not None
    with open(os.path.join(best.checkpoint.path, "state.txt")) as f:
        assert f.read() == "40"


def test_asha_stops_bad_trials(cluster, tmp_path):
    def trainable(config):
        for i in range(1, 17):
            tune.report({"acc": config["q"] * i})

    # Strong trials first + sequential execution so rung cutoffs are
    # established before weak trials arrive (async ASHA never stops the
    # first arrival at a rung).
    sched = tune.ASHAScheduler(grace_period=2, reduction_factor=2, max_t=16)
    results = tune.Tuner(
        trainable,
        param_space={"q": tune.grid_search([1.0, 0.5, 0.2, 0.1])},
        tune_config=tune.TuneConfig(metric="acc", mode="max",
                                    scheduler=sched,
                                    max_concurrent_trials=1),
        run_config=ray_tpu.train.RunConfig(storage_path=str(tmp_path),
                                           name="asha"),
    ).fit()
    iters = sorted(
        len(r.metrics_history) for r in results.results
    )
    # at least one trial early-stopped, and the best survived longer
    assert iters[0] < 16
    best = results.get_best_result()
    assert best.metrics["acc"] == pytest.approx(16.0)


def test_pbt_exploits(cluster, tmp_path):
    def trainable(config):
        ckpt = tune.get_checkpoint()
        start = 0
        if ckpt:
            with open(os.path.join(ckpt.path, "v.txt")) as f:
                start = int(f.read())
        import tempfile

        for i in range(start + 1, 21):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "v.txt"), "w") as f:
                f.write(str(i))
            tune.report(
                {"perf": config["lr"] * i, "training_iteration": i},
                checkpoint=ray_tpu.train.Checkpoint(d),
            )

    sched = tune.PopulationBasedTraining(
        perturbation_interval=5,
        hyperparam_mutations={"lr": [0.1, 1.0, 10.0]},
        seed=0,
    )
    results = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.1, 10.0])},
        tune_config=tune.TuneConfig(metric="perf", mode="max", scheduler=sched,
                                    max_concurrent_trials=2),
        run_config=ray_tpu.train.RunConfig(storage_path=str(tmp_path),
                                           name="pbt"),
    ).fit()
    assert len(results) == 2
    # the weak trial should have been exploited to a strong lr at least once
    assert all(r.metrics["perf"] > 2.0 for r in results.results)


def test_actor_loss_restarts_trial(cluster, tmp_path):
    """A trial whose ACTOR dies (preemption/OOM/registration starvation
    — not user code raising) restarts from its latest checkpoint on the
    infra budget instead of erroring: the round-4 flake was spurious
    actor loss under host load surfacing as trial ERRORs."""
    import tempfile
    import time

    from ray_tpu.tune.tune_controller import RUNNING, TuneController

    def slow(config):
        ckpt = tune.get_checkpoint()
        start = 0
        if ckpt:
            with open(os.path.join(ckpt.path, "i.txt")) as f:
                start = int(f.read())
        for i in range(start + 1, 6):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "i.txt"), "w") as f:
                f.write(str(i))
            tune.report({"i": i}, checkpoint=ray_tpu.train.Checkpoint(d))
            time.sleep(0.2)

    controller = TuneController(
        slow, param_space={}, metric="i", mode="max",
        experiment_dir=str(tmp_path / "infra"),
    )
    # run until the trial is mid-flight with at least one report in
    while not any(
        t.status == RUNNING and t.metrics_history for t in controller.trials
    ):
        assert controller.step()
    trial = controller.trials[0]
    # kill the actor out from under the controller (what the memory
    # monitor / a preemption does)
    ray_tpu.kill(controller._actors[trial.trial_id])
    while controller.step():
        pass
    assert trial.status == "TERMINATED", trial.error
    assert trial.num_infra_failures >= 1
    assert trial.num_failures == 0  # infra loss is not a user failure
    assert trial.last_result["i"] == 5  # resumed and finished


def test_failed_trial_reports_error(cluster, tmp_path):
    def bad(config):
        tune.report({"x": 1})
        raise ValueError("boom")

    results = tune.Tuner(
        bad,
        param_space={},
        tune_config=tune.TuneConfig(metric="x", mode="max"),
        run_config=ray_tpu.train.RunConfig(storage_path=str(tmp_path),
                                           name="fail"),
    ).fit()
    assert len(results.errors) == 1


def test_experiment_state_saved(cluster, tmp_path):
    results = tune.Tuner(
        _quadratic,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=ray_tpu.train.RunConfig(storage_path=str(tmp_path),
                                           name="state"),
    ).fit()
    state_file = tmp_path / "state" / "experiment_state.json"
    assert state_file.exists()
    import json

    state = json.loads(state_file.read_text())
    assert len(state["trials"]) == 2
    assert all(t["status"] == "TERMINATED" for t in state["trials"])


def test_tune_run_functional(cluster, tmp_path):
    results = tune.run(
        _quadratic,
        config={"x": tune.grid_search([2.0, 3.0])},
        metric="score",
        mode="max",
        storage_path=str(tmp_path),
        name="func",
    )
    assert len(results) == 2
