"""Runtime environments + accelerator manager.

Models the reference's python/ray/tests/test_runtime_env*.py and
accelerator manager unit tests.
"""
import os

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_env_vars_task(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_RE_FLAG": "hello"}})
    def read_env():
        return os.environ.get("MY_RE_FLAG")

    assert ray_tpu.get(read_env.remote()) == "hello"

    @ray_tpu.remote
    def read_plain():
        return os.environ.get("MY_RE_FLAG")

    # Restored after the task: pooled workers don't leak the env.
    assert ray_tpu.get(read_plain.remote()) is None


def test_env_vars_actor_lifetime(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_FLAG": "on"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_FLAG")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote()) == "on"
    assert ray_tpu.get(a.read.remote()) == "on"  # persists across calls


def test_working_dir_ships_code(cluster, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "helper_mod.py").write_text("def value():\n    return 'shipped'\n")
    (pkg / "data.txt").write_text("file-content")

    @ray_tpu.remote(runtime_env={"working_dir": str(pkg)})
    def use_pkg():
        import helper_mod  # importable from the shipped dir

        with open("data.txt") as f:  # cwd is the shipped dir
            data = f.read()
        return helper_mod.value(), data

    assert ray_tpu.get(use_pkg.remote()) == ("shipped", "file-content")


def test_py_modules(cluster, tmp_path):
    mod = tmp_path / "extra_mod_dir"
    mod.mkdir()
    (mod / "extra_util.py").write_text("X = 41\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
    def use_module():
        import extra_util

        return extra_util.X + 1

    assert ray_tpu.get(use_module.remote()) == 42


def test_invalid_runtime_env_key(cluster):
    with pytest.raises(ValueError, match="Unsupported runtime_env"):

        @ray_tpu.remote(runtime_env={"no_such_plugin": ["x"]})
        def f():
            return 1

        f.remote()


# ------------------------------------------------------------ accelerators
def test_tpu_manager_detection_env_override(monkeypatch):
    from ray_tpu._private.accelerators import TPUAcceleratorManager as M

    monkeypatch.setenv("RAY_TPU_NUM_CHIPS", "4")
    assert M.get_current_node_num_accelerators() == 4


def test_tpu_manager_type_and_head_resources(monkeypatch):
    from ray_tpu._private.accelerators import TPUAcceleratorManager as M

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    monkeypatch.setenv("TPU_NAME", "mypod")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    assert M.get_current_node_accelerator_type() == "v5e"
    extra = M.get_current_node_additional_resources()
    assert extra == {"TPU-pod-mypod": 1.0, "TPU-v5e-head": 1.0}
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    assert "TPU-v5e-head" not in M.get_current_node_additional_resources()


def test_tpu_visible_chips_bounds():
    from ray_tpu._private.accelerators import TPUAcceleratorManager as M

    env = {}
    M.set_visible_accelerator_ids(env, ["0", "1"])
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "1,2,1"


# ----------------------------------------------------- plugins (pip etc.)
def _make_wheel(tmp_path, name="tinypkg", version="1.0", body="VALUE = 42\n"):
    """Hand-rolled wheel (a zip with dist-info) — installable offline."""
    import base64
    import hashlib
    import zipfile

    whl = tmp_path / f"{name}-{version}-py3-none-any.whl"
    dist = f"{name}-{version}.dist-info"
    files = {
        f"{name}/__init__.py": body,
        f"{dist}/METADATA": (
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n"
        ),
        f"{dist}/WHEEL": (
            "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
            "Tag: py3-none-any\n"
        ),
    }
    records = []
    with zipfile.ZipFile(whl, "w") as zf:
        for path, content in files.items():
            data = content.encode()
            zf.writestr(path, data)
            digest = base64.urlsafe_b64encode(
                hashlib.sha256(data).digest()
            ).rstrip(b"=").decode()
            records.append(f"{path},sha256={digest},{len(data)}")
        records.append(f"{dist}/RECORD,,")
        zf.writestr(f"{dist}/RECORD", "\n".join(records) + "\n")
    return str(whl)


def test_pip_plugin_venv_isolation(cluster, tmp_path):
    """A pip runtime_env installs into a cached venv whose packages are
    importable ONLY inside tasks carrying that env (reference:
    _private/runtime_env/pip.py)."""
    wheel = _make_wheel(tmp_path)

    @ray_tpu.remote
    def with_pkg():
        import tinypkg

        return tinypkg.VALUE

    @ray_tpu.remote
    def without_pkg():
        try:
            import tinypkg  # noqa: F401

            return "leaked"
        except ImportError:
            return "isolated"

    env = {"pip": [wheel]}
    assert ray_tpu.get(
        with_pkg.options(runtime_env=env).remote(), timeout=120
    ) == 42
    assert ray_tpu.get(without_pkg.remote(), timeout=60) == "isolated"


def test_pip_plugin_bad_requirement_fails_loudly(cluster):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ray_tpu.exceptions.RayTaskError) as ei:
        ray_tpu.get(
            f.options(
                runtime_env={"pip": ["/nonexistent/nowhere-9.9.whl"]}
            ).remote(),
            timeout=120,
        )
    assert "pip" in str(ei.value)


def test_container_plugin_gated(cluster):
    @ray_tpu.remote
    def f():
        return 1

    # No docker/podman on this host: rejected at validation time.
    with pytest.raises(ValueError):
        f.options(runtime_env={"container": {"image": "x"}}).remote()
