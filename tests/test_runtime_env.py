"""Runtime environments + accelerator manager.

Models the reference's python/ray/tests/test_runtime_env*.py and
accelerator manager unit tests.
"""
import os

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_env_vars_task(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_RE_FLAG": "hello"}})
    def read_env():
        return os.environ.get("MY_RE_FLAG")

    assert ray_tpu.get(read_env.remote()) == "hello"

    @ray_tpu.remote
    def read_plain():
        return os.environ.get("MY_RE_FLAG")

    # Restored after the task: pooled workers don't leak the env.
    assert ray_tpu.get(read_plain.remote()) is None


def test_env_vars_actor_lifetime(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_FLAG": "on"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_FLAG")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote()) == "on"
    assert ray_tpu.get(a.read.remote()) == "on"  # persists across calls


def test_working_dir_ships_code(cluster, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "helper_mod.py").write_text("def value():\n    return 'shipped'\n")
    (pkg / "data.txt").write_text("file-content")

    @ray_tpu.remote(runtime_env={"working_dir": str(pkg)})
    def use_pkg():
        import helper_mod  # importable from the shipped dir

        with open("data.txt") as f:  # cwd is the shipped dir
            data = f.read()
        return helper_mod.value(), data

    assert ray_tpu.get(use_pkg.remote()) == ("shipped", "file-content")


def test_py_modules(cluster, tmp_path):
    mod = tmp_path / "extra_mod_dir"
    mod.mkdir()
    (mod / "extra_util.py").write_text("X = 41\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
    def use_module():
        import extra_util

        return extra_util.X + 1

    assert ray_tpu.get(use_module.remote()) == 42


def test_invalid_runtime_env_key(cluster):
    with pytest.raises(ValueError, match="Unsupported runtime_env"):

        @ray_tpu.remote(runtime_env={"pip": ["torch"]})
        def f():
            return 1

        f.remote()


# ------------------------------------------------------------ accelerators
def test_tpu_manager_detection_env_override(monkeypatch):
    from ray_tpu._private.accelerators import TPUAcceleratorManager as M

    monkeypatch.setenv("RAY_TPU_NUM_CHIPS", "4")
    assert M.get_current_node_num_accelerators() == 4


def test_tpu_manager_type_and_head_resources(monkeypatch):
    from ray_tpu._private.accelerators import TPUAcceleratorManager as M

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    monkeypatch.setenv("TPU_NAME", "mypod")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    assert M.get_current_node_accelerator_type() == "v5e"
    extra = M.get_current_node_additional_resources()
    assert extra == {"TPU-pod-mypod": 1.0, "TPU-v5e-head": 1.0}
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    assert "TPU-v5e-head" not in M.get_current_node_additional_resources()


def test_tpu_visible_chips_bounds():
    from ray_tpu._private.accelerators import TPUAcceleratorManager as M

    env = {}
    M.set_visible_accelerator_ids(env, ["0", "1"])
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "1,2,1"
