"""Owner-sharded object plane: tracker edges, sharded directory, and
the no-refcount-work-on-the-dispatch-loop acceptance criterion.

Reference behaviors modeled: reference_count.h (owner-side authority,
borrow edges, flap suppression), ownership_based_object_directory.h
(per-shard lock domains + flush queues).
"""
import gc
import threading
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import RayActorError
from ray_tpu._private.ids import WorkerID
from ray_tpu._private.object_plane import directory as objdir
from ray_tpu._private.object_plane.directory import ShardedObjectDirectory
from ray_tpu._private.object_plane.owner_refs import OwnerRefTracker
from ray_tpu._private.worker import _global, global_client


class _FakeConn:
    closed = False

    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)


class _FakeClient:
    def __init__(self, wid=None):
        self.worker_id = wid or WorkerID.from_random()
        self.conn = _FakeConn()
        self._lineage = {}
        self.pruned = []

    def _wait_prune(self, oids):
        self.pruned.extend(oids)


OWNER = b"o" * 16
OTHER = b"b" * 16


# --------------------------------------------------------------- tracker


def test_flap_within_flush_window_sends_nothing():
    """1->0->1 within one flush window: the net state is unchanged, so
    the flush must emit no edge at all (owned, borrowed, or fallback)."""
    c = _FakeClient()
    t = OwnerRefTracker(c)
    self_id = c.worker_id.binary()
    for oid, owner in (
        (b"owned111", self_id), (b"borrowed", OWNER), (b"fallback", b"")
    ):
        t.incr(oid, owner)
        t.decr(oid)
        t.incr(oid, owner)
    t.flush(c)
    # owned: alive + owner-side -> nothing; borrowed/fallback: alive ->
    # one advertisement each, but NO retraction of any kind.
    for msg in c.conn.sent:
        assert not msg.get("release") and not msg.get("bdel"), msg
        assert not msg.get("remove"), msg


def test_drop_within_window_unadvertised_sends_nothing():
    """A ref held and dropped inside one window, never advertised,
    must send NOTHING — a bare retraction would race ahead of the
    still-batched advertisement and free a live object."""
    c = _FakeClient()
    t = OwnerRefTracker(c)
    for oid, owner in (
        (b"owned111", c.worker_id.binary()),
        (b"borrowed", OWNER),
        (b"fallback", b""),
    ):
        t.incr(oid, owner)
        t.decr(oid)
    t.flush(c)
    assert c.conn.sent == []


def test_owned_advertised_drop_sends_release():
    c = _FakeClient()
    t = OwnerRefTracker(c)
    oid = b"owned111"
    t.incr(oid, c.worker_id.binary())
    t.mark_advertised(oid)
    t.decr(oid)
    t.flush(c)
    (msg,) = c.conn.sent
    assert msg["type"] == "ref_flush"
    assert msg["release"] == [oid]
    # The release is an edge, not a level: flushing again sends nothing.
    c.conn.sent.clear()
    t.flush(c)
    assert c.conn.sent == []


def test_borrow_holds_release_until_borrowers_drain():
    c = _FakeClient()
    t = OwnerRefTracker(c)
    oid = b"owned111"
    t.incr(oid, c.worker_id.binary())
    t.mark_advertised(oid)
    t.apply_borrow_update(OTHER, [oid], [])
    t.decr(oid)
    t.flush(c)
    assert c.conn.sent == []  # borrower alive: no release
    t.apply_borrow_update(OTHER, [], [oid])
    t.flush(c)
    (msg,) = c.conn.sent
    assert msg["release"] == [oid]


def test_borrower_death_sweep_releases():
    c = _FakeClient()
    t = OwnerRefTracker(c)
    oid = b"owned111"
    t.incr(oid, c.worker_id.binary())
    t.mark_advertised(oid)
    t.apply_borrow_update(OTHER, [oid], [])
    t.decr(oid)
    t.flush(c)
    assert c.conn.sent == []
    t.sweep_borrower(OTHER)
    t.flush(c)
    assert c.conn.sent and c.conn.sent[0]["release"] == [oid]


def test_borrowed_refs_route_to_owner():
    """Borrowed instances send badd/bdel grouped with their owner —
    never a head holder add — and bdel only after its badd."""
    c = _FakeClient()
    t = OwnerRefTracker(c)
    oid = b"borrowed"
    t.incr(oid, OWNER)
    t.flush(c)
    (msg,) = c.conn.sent
    assert msg["badd"] == [(OWNER, oid)]
    assert "add" not in msg
    c.conn.sent.clear()
    t.decr(oid)
    t.flush(c)
    (msg,) = c.conn.sent
    assert msg["bdel"] == [(OWNER, oid)]


# ------------------------------------------------------------- directory


class _Entry:
    def __init__(self):
        self.status = "READY"
        self.waiters = []
        self.task_pins = 0
        self.child_pins = 0
        self.holders = set()
        self.had_holder = False
        self.owner = None
        self.owner_released = False


def test_sharded_directory_facade_and_apply():
    freed = []
    d = ShardedObjectDirectory(
        _Entry, num_shards=4, free_callback=freed.extend
    )
    oids = [bytes([i]) * 8 for i in range(32)]
    for oid in oids:
        e = d.setdefault(oid, _Entry())
        e.owner = OWNER
    assert len(d) == 32
    assert sorted(d.keys()) == sorted(oids)
    assert d.get(oids[0]) is d[oids[0]]
    # Ops spread across shards and apply off-thread.
    d.enqueue([("release", oid, OWNER) for oid in oids])
    assert d.flush(timeout=5)
    deadline = time.time() + 5
    while len(freed) < 32 and time.time() < deadline:
        time.sleep(0.01)
    assert sorted(freed) == sorted(oids)
    for oid in oids:
        assert d.get(oid).owner_released
    d.stop()


def test_directory_early_drop_ledger_sharded():
    d = ShardedObjectDirectory(_Entry, num_shards=4)
    oid = b"notyet11"
    d.enqueue([("release", oid, OWNER)])
    assert d.flush(timeout=5)
    assert d.take_early_drop(oid)
    assert not d.take_early_drop(oid)  # consumed
    # Bounded: overflow evicts oldest, never grows without limit.
    many = [i.to_bytes(8, "little") for i in range(objdir.EARLY_DROP_CAP * 8)]
    d.enqueue([("release", o, OWNER) for o in many])
    assert d.flush(timeout=30)
    per_shard = [len(s.early_drops) for s in d._shards]
    assert all(n <= objdir.EARLY_DROP_CAP for n in per_shard)
    d.stop()


def test_remove_before_add_suppressed_on_sharded_path():
    """A legacy remove for an entry the directory never saw lands in
    the early-drop ledger, not as a free of someone else's object."""
    d = ShardedObjectDirectory(_Entry, num_shards=2)
    freed = []
    d.free_callback = freed.extend
    e = d.setdefault(b"live1111", _Entry())
    e.owner = None
    e.had_holder = True
    e.holders.add(OTHER)
    d.enqueue([("remove", b"ghost111", OWNER)])
    assert d.flush(timeout=5)
    assert freed == []
    assert d.take_early_drop(b"ghost111")
    d.stop()


# ----------------------------------------------------- cluster behaviors


@pytest.fixture
def ray2():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _flush_refs():
    client = global_client()
    client._tracker.flush(client)


def test_no_refcount_mutation_on_dispatch_loop():
    """Acceptance criterion: with the dispatch threads instrumented, a
    put/task/get/drop workload performs ZERO per-object holder-set
    mutations on the head dispatch loop — everything applies on the
    shard appliers or owner-side."""
    objdir.GUARD = True
    try:
        ray_tpu.init(num_cpus=2, ignore_reinit_error=True)

        @ray_tpu.remote
        def produce(x):
            return [x] * 1000

        import numpy as np

        refs = [ray_tpu.put(np.zeros(300_000)) for _ in range(8)]
        outs = [produce.remote(i) for i in range(16)]
        assert len(ray_tpu.get(outs)) == 16
        for r in refs:
            assert ray_tpu.get(r).shape == (300_000,)
        _flush_refs()
        del refs, outs
        gc.collect()
        _flush_refs()
        gcs = _global.node.gcs
        # The releases travel conn -> shard queue -> applier: poll.
        deadline = time.time() + 10
        while time.time() < deadline:
            if gcs.objects.stats["applied_ops"] > 0:
                break
            time.sleep(0.05)
        assert gcs.objects.flush(timeout=10)
        stats = gcs.objects.stats
        assert stats["applied_ops"] > 0  # the plane did real work
        assert stats["dispatch_mutations"] == 0, stats
    finally:
        objdir.GUARD = False
        ray_tpu.shutdown()


def test_owned_object_refcounts_stay_off_the_wire(ray2):
    """Instance churn on owned objects sends nothing: only the final
    release edge reaches the head."""
    import numpy as np

    client = global_client()
    ref = ray_tpu.put(np.zeros(300_000))
    _flush_refs()
    base = dict(client._tracker.stats)
    # Churn: many instance create/drop cycles while the object lives.
    for _ in range(50):
        r2 = ray_tpu.ObjectRef(ref.id(), client.worker_id.binary())
        del r2
    gc.collect()
    _flush_refs()
    after = dict(client._tracker.stats)
    assert after["releases"] == base["releases"]
    assert after["fallback_adds"] == base["fallback_adds"]
    oid = ref.id()
    del ref
    gc.collect()
    _flush_refs()
    after2 = dict(client._tracker.stats)
    assert after2["releases"] == base["releases"] + 1
    gcs = _global.node.gcs
    deadline = time.time() + 5
    while time.time() < deadline:
        if gcs.objects.get(oid.binary()) is None:
            break
        time.sleep(0.05)
    assert gcs.objects.get(oid.binary()) is None


def test_task_retained_borrow_keeps_foreign_object_alive(ray2):
    """An actor that stores a ref nested in its args borrows it: the
    driver dropping its own handle must not free the object (the borrow
    edge relayed to the owner holds it)."""
    import numpy as np

    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.ref = None

        def keep(self, refs):
            self.ref = refs[0]  # nested ref: arrives as a ref
            return True

        def read(self):
            return float(ray_tpu.get(self.ref).sum())

    k = Keeper.remote()
    arr = np.ones(300_000)
    ref = ray_tpu.put(arr)
    assert ray_tpu.get(k.keep.remote([ref]), timeout=30)
    _flush_refs()
    # Give the worker's borrow flush + head relay a couple windows.
    time.sleep(0.4)
    oid = ref.id()
    del ref
    gc.collect()
    _flush_refs()
    time.sleep(0.5)
    gcs = _global.node.gcs
    assert gcs.objects.get(oid.binary()) is not None, (
        "borrowed object freed while the actor still holds it"
    )
    assert abs(ray_tpu.get(k.read.remote(), timeout=30) - 300_000.0) < 1e-6
    ray_tpu.kill(k)


def test_owner_death_promotes_to_head_fallback():
    """Owner dies -> its entries promote to head-fallback; unborrowed
    ones free, borrowed ones survive on the holder shadow."""
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        import numpy as np

        @ray_tpu.remote
        class Owner:
            def make(self):
                # The ref is owned by THIS worker process.
                self.ref = ray_tpu.put(np.zeros(300_000))
                return [self.ref]  # nested: returned as a ref

        o = Owner.remote()
        [ref] = ray_tpu.get(o.make.remote(), timeout=30)
        oid = ref.id()
        assert ray_tpu.get(ref).shape == (300_000,)
        _flush_refs()
        gcs = _global.node.gcs
        entry = gcs.objects.get(oid.binary())
        assert entry is not None and entry.owner is not None
        ray_tpu.kill(o)
        deadline = time.time() + 10
        while time.time() < deadline:
            e = gcs.objects.get(oid.binary())
            if e is not None and e.owner is None:
                break
            time.sleep(0.05)
        e = gcs.objects.get(oid.binary())
        # Promoted (owner None). The driver's borrow shadow may or may
        # not have registered before the owner died; if the entry
        # survived, it must still be readable from the local copy.
        if e is not None:
            assert e.owner is None
        del ref
        gc.collect()
        _flush_refs()
        deadline = time.time() + 10
        while time.time() < deadline:
            if gcs.objects.get(oid.binary()) is None:
                break
            time.sleep(0.05)
    finally:
        ray_tpu.shutdown()


def test_drop_racing_delayed_task_done_reclaims_on_sharded_path():
    """Port of the early-drop-ledger regression to the object plane:
    the owner's release can reach the shard applier BEFORE the leased
    worker's batched task_done creates the entry; the per-shard ledger
    must reclaim the result at seal."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "testing_rpc_delay_us": "task_done_batch=150000:150000"
        },
    )
    try:
        @ray_tpu.remote
        def quick():
            return list(range(500))

        ray_tpu.get(quick.remote())  # warm a leased worker
        time.sleep(0.3)  # let the warmup's own ref flush drain
        oids = []
        for _ in range(5):
            ref = quick.remote()
            assert len(ray_tpu.get(ref)) == 500
            oids.append(ref.id().binary())
            del ref
            gc.collect()
            # Flush NOW: the release reaches the shard applier while
            # the worker's task_done_batch is still stalled in the
            # injected 150ms dispatch delay — the ledger must catch it.
            _flush_refs()
        gcs = _global.node.gcs
        deadline = time.time() + 15
        live = oids
        while time.time() < deadline:
            live = [o for o in oids if gcs.objects.get(o) is not None]
            if not live:
                break
            time.sleep(0.2)
        assert not live, (
            f"{len(live)} results leaked past the sharded early-drop ledger"
        )
        assert gcs.objects.stats["early_drops"] > 0
    finally:
        ray_tpu.shutdown()


def test_owner_death_with_unflushed_ref_flush_batch():
    """Owner-death edge (chaos engine, deterministic): the driver's
    badd for an actor-owned object is DROPPED at the head (first two
    ref_flush deliveries), the owner dies before the retransmit lands,
    and the promoted entry must survive on the owner-death grace window
    until the retransmitted borrow edge arrives — then free normally
    once the borrow drops. Without the grace + at-least-once flush the
    head frees a live borrowed object."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "chaos_spec": "ref_flush=drop:1.0@2",
            "chaos_seed": 33,
            "owner_death_grace_s": 6.0,
        },
    )
    try:
        import numpy as np

        @ray_tpu.remote
        class Owner:
            def make(self):
                self.ref = ray_tpu.put(np.zeros(300_000))
                return [self.ref]

        o = Owner.remote()
        [ref] = ray_tpu.get(o.make.remote(), timeout=30)
        oid = ref.id()
        _flush_refs()  # the badd batch — dropped at the head
        ray_tpu.kill(o)  # owner dies with the borrow edge un-landed
        gcs = _global.node.gcs
        deadline = time.time() + 10
        while time.time() < deadline:
            e = gcs.objects.get(oid.binary())
            if e is not None and e.owner is None:
                break
            time.sleep(0.05)
        e = gcs.objects.get(oid.binary())
        assert e is not None, (
            "promoted entry freed during the grace window with the "
            "borrow edge still in flight"
        )
        # The retransmitted badd lands within a couple of retransmit
        # periods — well inside the grace window — as a holder shadow.
        deadline = time.time() + 8
        while time.time() < deadline:
            e = gcs.objects.get(oid.binary())
            if e is not None and e.holders:
                break
            time.sleep(0.1)
        assert e is not None and e.holders, "borrow edge never landed"
        # Borrowed data still readable after the owner's death.
        assert ray_tpu.get(ref, timeout=30).shape == (300_000,)
        del ref
        gc.collect()
        _flush_refs()
        deadline = time.time() + 15
        while time.time() < deadline:
            if gcs.objects.get(oid.binary()) is None:
                break
            time.sleep(0.1)
        assert gcs.objects.get(oid.binary()) is None, (
            "promoted entry leaked after its last borrow dropped"
        )
    finally:
        ray_tpu.shutdown()
        from ray_tpu._private import chaos as _chaos

        _chaos.install("", 0)


def test_borrower_dies_during_head_owner_relay():
    """The head→owner borrow relay reordered past the borrower's death
    (chaos reorder rule at the owner's deliver side): the owner must
    ignore the stale add — a borrow edge for a dead process would hold
    the object forever."""
    ray_tpu.init(
        num_cpus=2,
        _system_config={
            "chaos_spec": "borrow_update=reorder:1.0@1?role=driver",
            "chaos_seed": 44,
        },
    )
    try:
        import numpy as np

        @ray_tpu.remote
        class Keeper:
            def keep(self, refs):
                self.refs = refs
                return True

        k = Keeper.remote()
        ref = ray_tpu.put(np.ones(300_000))  # driver owns X
        oid = ref.id()
        assert ray_tpu.get(k.keep.remote([ref]), timeout=30)
        # The relay's add for this borrow is held in the reorder slot;
        # killing the borrower makes borrower_died overtake it.
        ray_tpu.kill(k)
        time.sleep(1.0)  # let the sweep + (stale) relay both land
        del ref
        gc.collect()
        _flush_refs()
        gcs = _global.node.gcs
        deadline = time.time() + 15
        while time.time() < deadline:
            if gcs.objects.get(oid.binary()) is None:
                break
            time.sleep(0.1)
        client = global_client()
        assert gcs.objects.get(oid.binary()) is None, (
            "stale borrow edge for a dead borrower held the object",
            client._tracker.stats,
        )
    finally:
        ray_tpu.shutdown()
        from ray_tpu._private import chaos as _chaos

        _chaos.install("", 0)


def test_flap_across_owner_restart(monkeypatch):
    """1→0→1 instance flap on a borrowed ref across its owner's death
    and restart, with the owner killed at a deterministic chaos kill
    point ('between SEAL and REF_FLUSH': right after reporting
    Owner.make done, before its ref flush). The flapped ref must stay
    readable on the promoted entry and free exactly once at the end."""
    # Worker kill points activate from the environment (spawned worker
    # processes read RAY_TPU_chaos_* at import).
    monkeypatch.setenv(
        # Actor-method specs are named by bare method name.
        "RAY_TPU_chaos_spec", "kill:worker.post_exec.make=1"
    )
    monkeypatch.setenv("RAY_TPU_chaos_seed", "55")
    ray_tpu.init(num_cpus=2)
    try:
        import numpy as np

        @ray_tpu.remote(max_restarts=1)
        class Owner:
            def make(self):
                self.ref = ray_tpu.put(np.zeros(300_000))
                return [self.ref]

            def ping(self):
                return "pong"

        o = Owner.remote()
        [ref] = ray_tpu.get(o.make.remote(), timeout=60)
        oid = ref.id()
        owner_b = ref._owner
        gcs = _global.node.gcs
        # The chaos kill point took the owner down right after the
        # reply; wait for promotion (owner=None).
        deadline = time.time() + 20
        while time.time() < deadline:
            e = gcs.objects.get(oid.binary())
            if e is not None and e.owner is None:
                break
            time.sleep(0.1)
        e = gcs.objects.get(oid.binary())
        assert e is not None and e.owner is None, "owner never promoted"
        # Flap 1→0→1 within one flush window across the restart.
        del ref
        ref = ray_tpu.ObjectRef(oid, owner_b)
        gc.collect()
        _flush_refs()
        time.sleep(0.3)
        assert gcs.objects.get(oid.binary()) is not None, (
            "flapped borrow freed a live promoted object"
        )
        assert ray_tpu.get(ref, timeout=30).shape == (300_000,)
        # The actor itself restarted and is usable.
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                assert ray_tpu.get(o.ping.remote(), timeout=10) == "pong"
                break
            except RayActorError:
                time.sleep(0.2)
        else:
            pytest.fail("owner actor did not restart")
        # Final drop frees exactly once.
        del ref
        gc.collect()
        _flush_refs()
        deadline = time.time() + 15
        while time.time() < deadline:
            if gcs.objects.get(oid.binary()) is None:
                break
            time.sleep(0.1)
        assert gcs.objects.get(oid.binary()) is None
    finally:
        ray_tpu.shutdown()
        from ray_tpu._private import chaos as _chaos

        _chaos.install("", 0)


def test_stream_items_freed_after_consumption(ray2):
    """Stream items are OWNERLESS (sealed head-side, no lineage): their
    refs must ride the head-fallback holder path so dropping them frees
    the entries — owned-but-never-advertised classification would leak
    every consumed item."""

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(5):
            yield [i] * 2000  # non-inline-trivial payloads

    oids = []
    for r in gen.remote():
        assert len(ray_tpu.get(r)) == 2000
        oids.append(r.id().binary())
        del r
    _flush_refs()
    gcs = _global.node.gcs
    deadline = time.time() + 10
    live = oids
    while time.time() < deadline:
        live = [o for o in oids if gcs.objects.get(o) is not None]
        if not live:
            break
        time.sleep(0.2)
    assert not live, f"{len(live)} consumed stream items leaked"


def test_ref_flush_emits_flight_recorder_events(ray2):
    """Satellite: the plane's edges are visible to `ray_tpu events` —
    refcount flush and shard enqueue/apply land in the aggregator."""
    import numpy as np

    ref = ray_tpu.put(np.zeros(300_000))
    _flush_refs()
    del ref
    gc.collect()
    _flush_refs()
    from ray_tpu.util.state import list_cluster_events

    want = {"REF_FLUSH", "SHARD_ENQUEUE", "SHARD_APPLY"}
    deadline = time.time() + 10
    kinds = set()
    while time.time() < deadline:
        # Query per event name: the global ring survives init/shutdown,
        # so a capped combined listing can be dominated by a previous
        # session's leftovers.
        kinds = {
            k
            for k in want
            if list_cluster_events(category="refs", event=k, limit=10)
        }
        if want <= kinds:
            break
        time.sleep(0.2)
    assert want <= kinds, (kinds, _global.node.gcs.objects.stats)


# ---------------------------------------------------- pull admission
# Reference: pull_manager.h — get > wait > task-args priority classes
# under a bounded in-flight byte budget; completed/failed/cancelled
# pulls release budget and activate the next queued request.


class _BlockingFetcher:
    """Stands in for ObjectFetcher: pulls park on an event so tests
    control exactly when budget releases."""

    def __init__(self):
        self.release = threading.Event()
        self.order = []
        self.fail = set()

    def pull(self, oid, address, timeout=None, resolve=None):
        self.order.append(oid.binary())
        self.release.wait(timeout)
        return oid.binary() not in self.fail


def _mk_oids(n):
    from ray_tpu._private.ids import ObjectID

    return [ObjectID(bytes([i + 1]) * 16) for i in range(n)]


def _pull_in_thread(mgr, oid, size, prio, results, timeout=15):
    from ray_tpu._private.object_plane import pull_manager as pm

    def run():
        results[oid.binary()] = mgr.pull(
            oid, "addr", size=size, priority=prio, timeout=timeout
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_pull_admission_get_beats_queued_task_args():
    """A queued get activates ahead of an earlier-queued task-arg pull
    when budget frees (priority order, FIFO only within a class)."""
    from ray_tpu._private.object_plane import pull_manager as pm

    f = _BlockingFetcher()
    mgr = pm.PullManager(f, budget_bytes=100)
    a, b, c = _mk_oids(3)
    results = {}
    threads = [_pull_in_thread(mgr, a, 100, pm.PULL_GET, results)]
    deadline = time.time() + 5
    while not f.order and time.time() < deadline:
        time.sleep(0.01)
    assert f.order == [a.binary()]
    # task-arg queues FIRST, then a get — each needs the whole budget.
    threads.append(_pull_in_thread(mgr, b, 100, pm.PULL_TASK_ARGS, results))
    time.sleep(0.05)
    threads.append(_pull_in_thread(mgr, c, 100, pm.PULL_GET, results))
    time.sleep(0.05)
    s = mgr.stats()
    assert s["queued_get"] == 1 and s["queued_task_args"] == 1
    assert s["in_flight_bytes"] == 100
    f.release.set()
    for t in threads:
        t.join(10)
    assert f.order[1] == c.binary(), "get did not activate before task-args"
    assert f.order[2] == b.binary()
    assert all(results.values())
    assert mgr.stats()["in_flight_bytes"] == 0


def test_pull_budget_released_on_failure():
    """A failed pull must release its budget share and activate the
    next queued request — a lost object must not brick the plane."""
    from ray_tpu._private.object_plane import pull_manager as pm

    f = _BlockingFetcher()
    mgr = pm.PullManager(f, budget_bytes=100)
    a, b = _mk_oids(2)
    f.fail.add(a.binary())
    results = {}
    t1 = _pull_in_thread(mgr, a, 100, pm.PULL_GET, results)
    time.sleep(0.05)
    t2 = _pull_in_thread(mgr, b, 100, pm.PULL_GET, results)
    time.sleep(0.05)
    assert len(f.order) == 1  # b is queued behind the full budget
    f.release.set()
    t1.join(10)
    t2.join(10)
    assert results[a.binary()] is False
    assert results[b.binary()] is True
    assert mgr.stats()["in_flight_bytes"] == 0


def test_pull_cancel_on_ref_drop_frees_budget():
    """Cancelling a queued pull (ref-drop) removes it from the queue
    without it ever fetching; its budget share never activates."""
    from ray_tpu._private.object_plane import pull_manager as pm

    f = _BlockingFetcher()
    mgr = pm.PullManager(f, budget_bytes=100)
    a, b = _mk_oids(2)
    results = {}
    t1 = _pull_in_thread(mgr, a, 100, pm.PULL_GET, results)
    time.sleep(0.05)
    t2 = _pull_in_thread(mgr, b, 80, pm.PULL_TASK_ARGS, results, timeout=30)
    time.sleep(0.05)
    assert mgr.stats()["queued_task_args"] == 1
    assert mgr.cancel(b.binary()) == 1
    t2.join(5)
    assert results[b.binary()] is False
    assert mgr.stats()["queued_task_args"] == 0
    f.release.set()
    t1.join(10)
    assert f.order == [a.binary()]  # b never fetched
    assert mgr.stats()["in_flight_bytes"] == 0


def test_pull_fifo_within_class_and_oversize_solo():
    """FIFO within one class; an object bigger than the whole budget
    still runs (alone) — liveness over strictness."""
    from ray_tpu._private.object_plane import pull_manager as pm

    f = _BlockingFetcher()
    f.release.set()  # no blocking: drain in admission order
    mgr = pm.PullManager(f, budget_bytes=100)
    big = _mk_oids(1)[0]
    assert mgr.pull(big, "addr", size=10_000, priority=pm.PULL_GET,
                    timeout=5)
    assert f.order == [big.binary()]
    assert mgr.stats()["in_flight_bytes"] == 0

    f2 = _BlockingFetcher()
    mgr2 = pm.PullManager(f2, budget_bytes=100)
    oids = _mk_oids(4)
    results = {}
    threads = [_pull_in_thread(mgr2, oids[0], 100, pm.PULL_GET, results)]
    time.sleep(0.05)
    for o in oids[1:]:
        threads.append(
            _pull_in_thread(mgr2, o, 100, pm.PULL_TASK_ARGS, results)
        )
        time.sleep(0.02)
    f2.release.set()
    for t in threads:
        t.join(10)
    assert f2.order[1:] == [o.binary() for o in oids[1:]], "FIFO violated"


def test_pull_dedup_follower_rides_leader():
    """Concurrent pulls of ONE object cross the wire once: the second
    request follows the active leader without charging budget."""
    from ray_tpu._private.object_plane import pull_manager as pm

    class _Store:
        def contains(self, oid):
            return True

    f = _BlockingFetcher()
    mgr = pm.PullManager(f, store=_Store(), budget_bytes=100)
    (a,) = _mk_oids(1)
    results = {}
    t1 = _pull_in_thread(mgr, a, 100, pm.PULL_GET, results)
    deadline = time.time() + 5
    while not f.order and time.time() < deadline:
        time.sleep(0.01)

    follower_done = []

    def follow():
        follower_done.append(
            mgr.pull(a, "addr", size=100, priority=pm.PULL_GET, timeout=10)
        )

    t2 = threading.Thread(target=follow, daemon=True)
    t2.start()
    time.sleep(0.1)
    assert mgr.stats()["in_flight_bytes"] == 100  # charged once
    f.release.set()
    t1.join(10)
    t2.join(10)
    assert f.order == [a.binary()]  # one wire fetch
    assert follower_done == [True]


def test_pull_task_arg_class_context():
    """The worker runtime scopes arg-resolution pulls to the task-args
    class via the thread-local context."""
    from ray_tpu._private.object_plane import pull_manager as pm

    assert pm.current_pull_class() == pm.PULL_GET
    with pm.pull_class(pm.PULL_TASK_ARGS):
        assert pm.current_pull_class() == pm.PULL_TASK_ARGS
        with pm.pull_class(pm.PULL_WAIT):
            assert pm.current_pull_class() == pm.PULL_WAIT
        assert pm.current_pull_class() == pm.PULL_TASK_ARGS
    assert pm.current_pull_class() == pm.PULL_GET
