"""RLlib: modules, connectors, buffers, PPO/DQN/IMPALA learning,
fault tolerance, checkpointing, Tune integration.

Models the reference's rllib test strategy (SURVEY.md §4: learning
tests on CartPole with reward thresholds, actor-manager fault
tolerance).
"""
import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


# ------------------------------------------------------------ unit pieces
def test_replay_buffer_uniform():
    from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer

    buf = ReplayBuffer(capacity=100, seed=0)
    buf.add_batch({"obs": np.arange(150, dtype=np.float32).reshape(150, 1),
                   "rewards": np.arange(150, dtype=np.float32)})
    assert len(buf) == 100
    s = buf.sample(32)
    assert s["obs"].shape == (32, 1)
    # Ring buffer: oldest 50 evicted.
    assert s["rewards"].min() >= 50

def test_replay_buffer_prioritized():
    from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=64, seed=0, alpha=1.0)
    buf.add_batch({"x": np.arange(64, dtype=np.float32)})
    # Give item 7 overwhelming priority; it should dominate samples.
    buf.update_priorities(np.arange(64), np.full(64, 1e-3))
    buf.update_priorities([7], [100.0])
    s = buf.sample(256)
    frac = float(np.mean(s["x"] == 7.0))
    assert frac > 0.8, f"priority sampling broken: frac={frac}"
    assert "weights" in s and s["weights"].shape == (256,)


def test_episode_and_batch_connector():
    from ray_tpu.rllib.connectors.connector_v2 import EpisodesToBatch
    from ray_tpu.rllib.env.episode import SingleAgentEpisode

    ep = SingleAgentEpisode(initial_observation=np.zeros(3))
    for t in range(5):
        ep.add_env_step(np.full(3, t + 1.0), t % 2, 1.0,
                        terminated=(t == 4),
                        extra_model_outputs={"action_logp": -0.5})
    ep.finalize()
    batch = EpisodesToBatch()(episodes=[ep])
    assert batch["obs"].shape == (5, 3)
    assert batch["next_obs"].shape == (5, 3)
    assert batch["terminateds"][-1] == 1.0 and batch["terminateds"][0] == 0.0
    assert np.allclose(batch["action_logp"], -0.5)


def test_gae_matches_reference_impl():
    """GAE against a hand-rolled numpy reference on a tiny episode."""
    from ray_tpu.rllib.connectors.connector_v2 import (
        GeneralAdvantageEstimation,
    )
    from ray_tpu.rllib.env.episode import SingleAgentEpisode

    ep = SingleAgentEpisode(initial_observation=np.zeros(1))
    rewards = [1.0, 0.5, 2.0]
    for t, r in enumerate(rewards):
        ep.add_env_step(np.zeros(1), 0, r, terminated=(t == 2))
    ep.finalize()
    values = np.array([0.1, 0.2, 0.3, 0.4], np.float32)
    gae = GeneralAdvantageEstimation(
        gamma=0.9, lambda_=0.8, values_fn=lambda obs_list: [values]
    )
    batch = gae(batch={}, episodes=[ep])
    # Manual: terminal bootstrap=0.
    adv = np.zeros(3)
    g = 0.0
    last_v = 0.0
    for t in (2, 1, 0):
        nv = last_v if t == 2 else values[t + 1]
        delta = rewards[t] + 0.9 * nv - values[t]
        g = delta + 0.9 * 0.8 * g
        adv[t] = g
    assert np.allclose(batch["advantages"], adv, atol=1e-5)
    assert np.allclose(batch["value_targets"], adv + values[:3], atol=1e-5)


def test_vtrace_reduces_to_gae_like_on_policy():
    """On-policy (rho=1) V-trace vs discounted-return sanity check."""
    import jax

    from ray_tpu.rllib.algorithms.impala import IMPALALearner, IMPALAConfig
    from ray_tpu.rllib.core.rl_module import RLModuleSpec, DiscretePolicyModule
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    spec = RLModuleSpec(
        module_class=DiscretePolicyModule,
        observation_space=env.observation_space,
        action_space=env.action_space,
    )
    cfg = IMPALAConfig().training(rollout_fragment_length=10)
    learner = IMPALALearner(module_spec=spec, config=cfg.learner_config())
    learner.build()
    from ray_tpu.rllib.env.episode import SingleAgentEpisode

    ep = SingleAgentEpisode(initial_observation=env.reset(seed=0)[0])
    obs = ep.observations[0]
    for t in range(10):
        a = t % 2
        nobs, r, term, trunc, _ = env.step(a)
        ep.add_env_step(nobs, a, r, terminated=term, truncated=True if t == 9 else trunc,
                        extra_model_outputs={"action_logp": 0.0})
        if term:
            break
    ep.finalize()
    batch = learner.build_batch([ep])
    loss, metrics = learner.compute_loss(
        learner.params,
        {k: jax.numpy.asarray(v) for k, v in batch.items()},
        jax.random.PRNGKey(0),
    )
    assert np.isfinite(float(loss))
    assert float(metrics["mean_rho"]) > 0.0


# --------------------------------------------------------------- learning
def test_ppo_cartpole_learns(cluster):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8)
        .training(train_batch_size=2000, minibatch_size=128, num_epochs=8,
                  lr=5e-4)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(20):
        r = algo.train()
        best = max(best, r["episode_return_mean"])
        if best >= 80.0:
            break
    algo.stop()
    assert best >= 80.0, f"PPO failed to learn CartPole: best={best}"


def test_ppo_remote_env_runners(cluster):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4)
        .training(train_batch_size=1000, minibatch_size=128, num_epochs=4)
        .debugging(seed=0)
        .build()
    )
    r1 = algo.train()
    r2 = algo.train()
    assert r2["num_env_steps_sampled_lifetime"] >= 2000
    assert r2["env_runners"]["num_healthy_workers"] == 2
    algo.stop()


def test_dqn_cartpole_learns(cluster):
    from ray_tpu.rllib.algorithms.dqn import DQNConfig

    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4)
        .training(prioritized_replay=True, epsilon_timesteps=4000,
                  num_steps_sampled_before_learning_starts=500,
                  lr=1e-3, target_network_update_freq=200)
        .debugging(seed=1)
        .build()
    )
    best = 0.0
    for _ in range(60):
        r = algo.train()
        best = max(best, r["episode_return_mean"])
        if best >= 60.0:
            break
    algo.stop()
    assert best >= 60.0, f"DQN failed to learn CartPole: best={best}"


def test_impala_async_pipeline(cluster):
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4)
        .training(lr=5e-4, entropy_coeff=0.005)
        .debugging(seed=0)
        .build()
    )
    first = None
    best = 0.0
    for _ in range(150):
        r = algo.train()
        m = r["episode_return_mean"]
        if not np.isnan(m):
            first = m if first is None else first
            best = max(best, m)
        if best >= 50.0:
            break
    algo.stop()
    assert best >= 50.0, f"IMPALA not improving: first={first} best={best}"


def test_env_runner_fault_tolerance(cluster):
    """Kill a remote env runner mid-training; the actor manager replaces
    it and sampling continues (reference FaultTolerantActorManager)."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
        .training(train_batch_size=400, minibatch_size=64, num_epochs=2)
        .build()
    )
    algo.train()
    mgr = algo.env_runner_group._manager
    ray_tpu.kill(mgr.actor(0))
    r = algo.train()  # triggers restart path
    r = algo.train()
    assert r["env_runners"]["num_healthy_workers"] == 2
    assert algo.env_runner_group.num_restarts >= 1
    algo.stop()


def test_algorithm_checkpoint_restore(cluster, tmp_path):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    def build():
        return (
            PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=4)
            .training(train_batch_size=400, minibatch_size=64, num_epochs=2)
            .debugging(seed=0)
            .build()
        )

    algo = build()
    algo.train()
    algo.save_checkpoint(str(tmp_path))
    w1 = algo.learner_group.get_weights()
    it1 = algo._iteration
    algo.stop()

    algo2 = build()
    algo2.load_checkpoint(str(tmp_path))
    w2 = algo2.learner_group.get_weights()
    import jax

    leaves1 = jax.tree_util.tree_leaves(w1)
    leaves2 = jax.tree_util.tree_leaves(w2)
    assert all(np.allclose(a, b) for a, b in zip(leaves1, leaves2))
    assert algo2._iteration == it1
    algo2.stop()


def test_multi_learner_gradient_sync(cluster):
    """num_learners=2: out-of-graph gradient allreduce keeps learner
    replicas in lockstep (the DCN multi-host path)."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4)
        .training(train_batch_size=400, minibatch_size=None, num_epochs=1)
        .learners(num_learners=2)
        .debugging(seed=0)
        .build()
    )
    algo.train()
    import jax

    w0 = ray_tpu.get(algo.learner_group._actors[0].get_weights.remote())
    w1 = ray_tpu.get(algo.learner_group._actors[1].get_weights.remote())
    for a, b in zip(jax.tree_util.tree_leaves(w0), jax.tree_util.tree_leaves(w1)):
        assert np.allclose(a, b, atol=1e-5)
    algo.stop()


def test_tune_integration(cluster, tmp_path):
    """Algorithms are Tune trainables (reference: Algorithm extends
    Trainable; here via the class API)."""
    from ray_tpu import tune
    from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4)
        .training(train_batch_size=400, minibatch_size=64, num_epochs=2)
    )
    results = tune.Tuner(
        PPO,
        param_space={
            "__algorithm_config__": cfg,
            "lr": tune.grid_search([1e-4, 5e-4]),
        },
        tune_config=tune.TuneConfig(metric="episode_return_mean", mode="max"),
        run_config=ray_tpu.train.RunConfig(
            storage_path=str(tmp_path), name="rl", stop={"training_iteration": 2}
        ),
    ).fit()
    assert len(results) == 2
    assert all(r.error is None for r in results.results)
