"""Ecosystem adapters: multiprocessing.Pool, joblib backend, tqdm_ray
(reference: python/ray/util/multiprocessing/pool.py, util/joblib/,
experimental/tqdm_ray.py)."""
import time

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import Pool


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


def test_pool_map_and_star(cluster):
    with Pool(4) as p:
        assert p.map(_sq, range(20)) == [x * x for x in range(20)]
        assert p.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]
        assert p.apply(_add, (5, 6)) == 11


def test_pool_async_and_imap(cluster):
    with Pool(3) as p:
        ar = p.map_async(_sq, range(10))
        assert ar.get(timeout=60) == [x * x for x in range(10)]
        assert list(p.imap(_sq, range(8), chunksize=3)) == [
            x * x for x in range(8)
        ]
        assert sorted(p.imap_unordered(_sq, range(8))) == sorted(
            x * x for x in range(8)
        )


def test_pool_workers_share_processes(cluster):
    """Pool actors are sub-core: a wide pool must not boot one
    interpreter per slot (they pack onto shared hosts)."""
    import os

    with Pool(8) as p:
        pids = set(p.map(lambda _: os.getpid(), range(32)))
        assert len(pids) < 8


def test_pool_apply_async_callback(cluster):
    got = []
    with Pool(2) as p:
        ar = p.apply_async(_sq, (7,), callback=got.append)
        assert ar.get(timeout=60) == 49
        deadline = time.time() + 10
        while not got and time.time() < deadline:
            time.sleep(0.05)
        assert got == [49]


def test_pool_close_join_tears_down_actors(cluster):
    """close()+join() (the documented multiprocessing shutdown) must
    drain in-flight work and release the actor fleet — not leak
    sub-core reservations for the driver's lifetime."""
    from ray_tpu.util.state import list_actors

    p = Pool(3)
    ar = p.map_async(_sq, range(9))
    p.close()
    p.join()
    assert ar.get(timeout=60) == [x * x for x in range(9)]
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [
            a for a in list_actors()
            if a["class_name"].startswith("_PoolWorker")
            and a["state"] == "ALIVE"
        ]
        if not alive:
            break
        time.sleep(0.2)
    assert not alive


def test_joblib_backend(cluster):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray_tpu", n_jobs=3):
        out = joblib.Parallel()(joblib.delayed(_sq)(i) for i in range(12))
    assert out == [i * i for i in range(12)]


def test_tqdm_ray_worker_bars_reach_driver(cluster):
    from ray_tpu.experimental import tqdm_ray

    @ray_tpu.remote
    def work(n):
        bar = tqdm_ray.tqdm(desc="crunch", total=n)
        for _ in range(n):
            bar.update(1)
        # leave the bar open so the driver registry retains it
        return n

    assert ray_tpu.get(work.remote(5)) == 5
    deadline = time.time() + 30
    while time.time() < deadline:
        bars = tqdm_ray.bars()
        if any(
            b["desc"] == "crunch" and b["n"] == 5 for b in bars.values()
        ):
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"driver never saw the bar: {tqdm_ray.bars()}")


def test_tqdm_ray_close_removes_bar(cluster):
    from ray_tpu.experimental import tqdm_ray

    bar = tqdm_ray.tqdm(desc="local", total=3)
    bar.update(2)
    assert any(b["desc"] == "local" for b in tqdm_ray.bars().values())
    bar.close()
    assert not any(b["desc"] == "local" for b in tqdm_ray.bars().values())
