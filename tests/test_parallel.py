"""Mesh/sharding layer + ring attention vs dense oracle on the 8-device
CPU mesh (the reference tests multi-node on one box the same way —
cluster_utils; here virtual XLA devices stand in for chips)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.ops import attention_reference, flash_attention, ring_attention
from ray_tpu.ops.ring_attention import ring_self_attention
from ray_tpu.parallel import MeshSpec, logical_sharding
from ray_tpu.parallel.mesh import logical_to_spec


def test_mesh_spec_build():
    spec = MeshSpec(data=2, seq=2, tensor=2)
    mesh = spec.build()
    assert mesh.shape == {"data": 2, "fsdp": 1, "seq": 2, "tensor": 2, "expert": 1}


def test_mesh_spec_too_many_devices():
    with pytest.raises(ValueError):
        MeshSpec(data=16).build()


def test_logical_to_spec():
    assert logical_to_spec(("batch", "seq", "embed")) == P(("data", "fsdp"), "seq", "fsdp") or True
    # embed after batch: fsdp already used by batch -> embed replicates
    spec = logical_to_spec(("batch", "seq", "embed"))
    assert spec[0] == ("data", "fsdp")
    assert spec[1] == "seq"
    assert spec[2] is None  # fsdp consumed by batch


def test_logical_sharding_placement():
    mesh = MeshSpec(data=4, tensor=2).build()
    x = jnp.zeros((8, 16))
    sharded = jax.device_put(x, logical_sharding(mesh, ("batch", "mlp")))
    assert sharded.sharding.spec[1] == "tensor"


def test_flash_matches_reference_cpu():
    # On CPU flash_attention falls back to the reference path; exercise the
    # dispatch and GQA handling.
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (2, 8, 64, 32))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 64, 32))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 2, 64, 32))
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = MeshSpec(seq=4).build()
    b, h, t, d = 2, 4, 128, 16
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, t, d), jnp.float32)
        for i in range(3)
    )
    out = ring_self_attention(q, k, v, mesh, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grads_match_dense():
    mesh = MeshSpec(seq=4).build()
    b, h, t, d = 1, 2, 64, 8
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, t, d), jnp.float32)
        for i in range(3)
    )

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-5)


def test_ring_attention_gqa():
    mesh = MeshSpec(seq=2).build()
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 64, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 64, 16))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 64, 16))
    out = ring_self_attention(q, k, v, mesh, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_pallas_interpret_matches_reference():
    # Run the actual pallas kernel in interpreter mode on CPU.
    from ray_tpu.ops import attention as A

    q = jax.random.normal(jax.random.PRNGKey(0), (4, 96, 32), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (4, 96, 32), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (4, 96, 32), jnp.float32)
    import jax.experimental.pallas as pl  # noqa: F401
    from jax.experimental.pallas import tpu as pltpu

    with pltpu.force_tpu_interpret_mode():
        o, lse = A._flash_fwd_pallas(
            q, k, v, causal=True, sm_scale=0.25, block_q=32, block_k=32
        )
    # Treat the leading dim as heads of a single batch element.
    ref = attention_reference(q[None], k[None], v[None], causal=True, sm_scale=0.25)[0]
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)


def test_flash_pallas_backward_matches_reference_grads():
    """dq/dk/dv from the pallas backward kernels vs autodiff through the
    XLA reference (interpret mode on CPU; the same kernels run compiled
    on the chip)."""
    from jax.experimental.pallas import tpu as pltpu

    from ray_tpu.ops import attention as A

    q = jax.random.normal(jax.random.PRNGKey(3), (2, 96, 32), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(4), (2, 96, 32), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(5), (2, 96, 32), jnp.float32)
    do = jax.random.normal(jax.random.PRNGKey(6), (2, 96, 32), jnp.float32)

    def ref_out(q, k, v):
        o = attention_reference(
            q[None], k[None], v[None], causal=True, sm_scale=0.25
        )[0]
        return jnp.sum(o * do)

    dq_ref, dk_ref, dv_ref = jax.grad(ref_out, argnums=(0, 1, 2))(q, k, v)

    with pltpu.force_tpu_interpret_mode():
        o, lse = A._flash_fwd_pallas(
            q, k, v, causal=True, sm_scale=0.25, block_q=32, block_k=32
        )
        dq, dk, dv = A._flash_bwd_pallas(
            q, k, v, o, lse, do, causal=True, sm_scale=0.25,
            block_q=32, block_k=32,
        )
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_ref), atol=2e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_ref), atol=2e-4)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_ref), atol=2e-4)
