"""LockWitness: the runtime lock-order race detector (TSan-lite).

Covers the acceptance triad: an intentionally inverted lock pair is
caught, a consistent ordering stays clean, and reentrant RLock
acquisition produces no false positive — plus the Condition/Event
integration the runtime leans on and the witnessed-under-load check.
"""
import os
import threading

import pytest

from ray_tpu._private import lock_witness as lw


@pytest.fixture
def witness(monkeypatch):
    """Fresh witness per test; uninstall + reset afterwards so other
    tests see pristine threading factories. The session sidecar file
    is detached for the duration — these tests trip inversions ON
    PURPOSE, and under race-smoke those must not land in the sidecar
    the sessionfinish gate scans."""
    monkeypatch.delenv(lw.FILE_ENV, raising=False)
    was_installed = lw.installed()
    lw.clear()
    lw.install()
    yield lw
    if not was_installed:
        lw.uninstall()
    lw.clear()


def _make_locks(witness):
    # Distinct creation lines => distinct witness sites.
    a = threading.Lock()
    b = threading.Lock()
    return a, b


def test_inverted_pair_detected(witness):
    a, b = _make_locks(witness)
    with a:
        with b:
            pass
    assert not witness.violations(), "consistent order must be clean"
    # Reverse order: the inversion fires at acquire time, in whatever
    # thread performs it (no deadlock needed — a IS free here).
    with b:
        with a:
            pass
    vs = witness.violations()
    assert len(vs) == 1
    v = vs[0]
    assert v.first != v.second
    assert "lock-order inversion" in v.render()
    assert "this acquisition" in v.render()
    with pytest.raises(AssertionError):
        witness.assert_clean()


def test_inverted_pair_across_threads(witness):
    a, b = _make_locks(witness)

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    t = threading.Thread(target=order_ab)
    t.start()
    t.join()
    t = threading.Thread(target=order_ba)
    t.start()
    t.join()
    assert len(witness.violations()) == 1


def test_consistent_order_clean(witness):
    a, b = _make_locks(witness)
    for _ in range(50):
        with a:
            with b:
                pass
    witness.assert_clean()
    rep = witness.witness_report()
    assert rep["violations"] == 0
    assert rep["edges"] >= 1


def test_transitive_cycle_detected(witness):
    a = threading.Lock()
    b = threading.Lock()
    c = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    witness.assert_clean()
    with c:
        with a:  # closes a->b->c->a
            pass
    vs = witness.violations()
    assert len(vs) == 1
    assert len(vs[0].path) == 3  # c -> ... -> a chain witnessed


def test_reentrant_rlock_no_false_positive(witness):
    r = threading.RLock()
    other = threading.Lock()
    with r:
        with r:  # reentrant: no self-edge, no violation
            with other:
                pass
        with r:
            pass
    witness.assert_clean()


def test_rlock_inversion_still_detected(witness):
    r = threading.RLock()
    lk = threading.Lock()
    with r:
        with lk:
            pass
    with lk:
        with r:
            pass
    assert len(witness.violations()) == 1


def test_same_site_siblings_ignored(witness):
    # Per-shard pattern: N locks born on ONE line share a site; order
    # between siblings is not witnessable (documented limitation) and
    # must not self-cycle.
    locks = [threading.Lock() for _ in range(4)]
    with locks[0]:
        with locks[1]:
            pass
    with locks[2]:
        with locks[3]:
            pass
    with locks[3]:
        with locks[2]:
            pass
    witness.assert_clean()


def test_condition_and_event_integration(witness):
    """Condition(RLock) waits/notifies and Event set/wait work
    unchanged under the witness (the _release_save protocol)."""
    cond = threading.Condition(threading.RLock())
    evt = threading.Event()
    state = {"go": False, "seen": False}

    def waiter():
        with cond:
            while not state["go"]:
                cond.wait(timeout=5)
            state["seen"] = True
        evt.set()

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        state["go"] = True
        cond.notify_all()
    assert evt.wait(timeout=5)
    t.join(timeout=5)
    assert state["seen"]
    witness.assert_clean()


def test_nonblocking_acquire_failure_adds_nothing(witness):
    a = threading.Lock()
    b = threading.Lock()
    # Establish a -> b.
    with a:
        assert b.acquire(blocking=False)
        b.release()

    results = {}

    def try_inverted():
        # b held here; a is held by the main thread, so the
        # try-acquire FAILS — a failed acquire must record no b->a
        # edge (the inversion never happened).
        with b:
            results["got_a"] = a.acquire(blocking=False)

    with a:
        t = threading.Thread(target=try_inverted)
        t.start()
        t.join(timeout=5)
    assert results["got_a"] is False
    witness.assert_clean()


def test_violation_reported_once_per_pair(witness):
    a, b = _make_locks(witness)
    with a:
        with b:
            pass
    for _ in range(5):
        with b:
            with a:
                pass
    assert len(witness.violations()) == 1


def test_cross_thread_release_no_phantom(witness):
    """Lock handoff (acquired in one thread, released by another)
    must not leave a phantom entry on the acquirer's held stack —
    the phantom would seed false held-before edges from a lock the
    thread no longer holds and fail race-smoke on code with no real
    ordering bug."""
    h = threading.Lock()
    x = threading.Lock()
    y = threading.Lock()
    h.acquire()
    t = threading.Thread(target=h.release)  # handoff release
    t.start()
    t.join(timeout=5)
    # h is no longer held here: x-then-y must record x->y only, with
    # no h->x edge from the stale stack entry.
    with x:
        with y:
            pass

    def x_then_h():
        with x:
            with h:
                pass

    t = threading.Thread(target=x_then_h)
    t.start()
    t.join(timeout=5)
    # A phantom h would have made x->h close a fake h->x->h cycle.
    witness.assert_clean()


def test_same_basename_distinct_dirs_distinct_sites(witness, tmp_path):
    """Locks created in different files sharing a basename AND line
    number must be distinct graph nodes — merging them fabricates an
    inversion between locks that never interact (or masks a real
    one)."""
    import importlib.util

    src = "import threading\nL = threading.Lock()\n"
    mods = []
    for d in ("a", "b"):
        pkg = tmp_path / d
        pkg.mkdir()
        f = pkg / "samename.py"
        f.write_text(src)
        spec = importlib.util.spec_from_file_location(
            f"_lw_samename_{d}", f
        )
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        mods.append(m)
    la, lb = mods[0].L, mods[1].L
    assert la._site != lb._site
    q = threading.Lock()
    # q->la in one order, lb->q in the other: only a basename-keyed
    # witness would see these as one node and report a cycle.
    with q:
        with la:
            pass
    with lb:
        with q:
            pass
    witness.assert_clean()


def test_queue_under_witness(witness):
    """queue.Queue (Condition-heavy) round-trips across threads."""
    import queue

    q = queue.Queue()

    def produce():
        for i in range(100):
            q.put(i)

    t = threading.Thread(target=produce)
    t.start()
    got = [q.get(timeout=5) for _ in range(100)]
    t.join(timeout=5)
    assert got == list(range(100))
    witness.assert_clean()


def test_at_fork_reinit_clears_held_entry(witness):
    """CPython's at-fork hooks acquire module locks in the parent and
    _at_fork_reinit() them in the child instead of releasing — the
    witness must treat the reinit as the release, or the child keeps
    phantom held entries that fabricate inversions (seen live with
    logging._lock vs concurrent.futures' shutdown lock at exit)."""
    for make in (threading.Lock, threading.RLock):
        a = make()
        x = threading.Lock()
        a.acquire()
        a._at_fork_reinit()  # child-side stand-in for release()
        # a is no longer held: taking x must not record an a->x edge.
        with x:
            pass

        def x_then_a(a=a, x=x):
            with x:
                with a:  # only real edge; must not close a fake cycle
                    pass

        t = threading.Thread(target=x_then_a)
        t.start()
        t.join(timeout=5)
    witness.assert_clean()


def test_violation_written_to_sidecar_file(witness, tmp_path,
                                           monkeypatch):
    """With FILE_ENV set, a violation is appended to the sidecar —
    the channel that lets a race-smoke driver fail on inversions
    witnessed in other processes."""
    side = tmp_path / "witness.log"
    monkeypatch.setenv(lw.FILE_ENV, str(side))
    a, b = _make_locks(witness)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    text = side.read_text()
    assert "lock-order inversion" in text
    assert f"[pid {os.getpid()}]" in text


def test_subprocess_violation_reaches_sidecar(tmp_path):
    """The daemon path end-to-end: a CHILD process self-installs off
    the inherited env, trips an inversion, and its finding lands in
    the shared sidecar file — this is what closes the 'inversion in a
    spawned head/raylet/worker passes CI' hole."""
    import subprocess
    import sys

    side = tmp_path / "witness.log"
    env = dict(os.environ)
    env[lw.ENV_VAR] = "1"
    env[lw.FILE_ENV] = str(side)
    code = (
        "from ray_tpu._private import lock_witness as lw\n"
        "assert lw.maybe_install()\n"
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "with a:\n"
        "    with b:\n"
        "        pass\n"
        "with b:\n"
        "    with a:\n"
        "        pass\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env,
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    text = side.read_text()
    assert "lock-order inversion" in text
    # The pid recorded is NOT ours: the finding crossed processes.
    assert f"[pid {os.getpid()}]" not in text


def test_uninstall_restores_factories():
    # Preserve the session's installed state: under race-smoke the
    # witness is armed session-wide and must STAY armed after this
    # test (a stray uninstall would silently disable the inversion
    # check for every suite that follows).
    was_installed = lw.installed()
    lw.clear()
    lw.install()
    try:
        assert threading.Lock is lw.WitnessLock
    finally:
        lw.uninstall()
    assert threading.Lock is lw._REAL_LOCK
    assert threading.RLock is lw._REAL_RLOCK
    lk = threading.Lock()
    assert not isinstance(lk, lw.WitnessLock)
    if was_installed:
        lw.install()


def test_witnessed_runtime_locks_smoke(witness):
    """The real object-plane structures run under the witness: a
    sharded directory + owner tracker exercise their lock stacks
    (shard locks, GCS-free callback, tracker lock) without a
    violation — the in-process slice of what race-smoke soaks."""

    class _Entry:
        def __init__(self):
            self.holders = set()
            self.status = "READY"
            self.waiters = []
            self.task_pins = 0
            self.child_pins = 0
            self.owner = None
            self.owner_released = False
            self.had_holder = False

    from ray_tpu._private.object_plane.directory import (
        ShardedObjectDirectory,
    )

    freed = []
    d = ShardedObjectDirectory(
        _Entry, num_shards=4, free_callback=freed.extend
    )
    try:
        oids = [bytes([i]) * 8 for i in range(32)]
        for oid in oids:
            d[oid] = _Entry()
        d.enqueue([("badd", oid, b"client-1") for oid in oids])
        d.enqueue([("release", oid, b"owner-1") for oid in oids])
        d.enqueue([("bdel", oid, b"client-1") for oid in oids])
        assert d.flush(timeout=10)
        assert sorted(freed) == sorted(oids)
    finally:
        d.stop()
    witness.assert_clean()
