"""DAG API, compiled graphs (channels), and durable workflows.

Models the reference's python/ray/dag and python/ray/workflow tests.
"""
import time

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def mul(a, b):
    return a * b


# ------------------------------------------------------------------- DAG
def test_function_dag_execute(cluster):
    with InputNode() as inp:
        dag = add.bind(mul.bind(inp, 2), mul.bind(inp, 3))
    ref = dag.execute(10)
    assert ray_tpu.get(ref) == 50  # 10*2 + 10*3


def test_dag_diamond_runs_once(cluster):
    @ray_tpu.remote
    def tag(x):
        import os, time as t

        return (x, os.getpid(), t.time())

    with InputNode() as inp:
        shared = tag.bind(inp)
        dag = add.bind(
            mul.bind(shared, 1),
            mul.bind(shared, 1),
        )
    # shared node executes once: its tuple result is used twice; mul on
    # tuples fails, so project first.
    @ray_tpu.remote
    def first(t):
        return t[0]

    with InputNode() as inp:
        shared = tag.bind(inp)
        a = first.bind(shared)
        dag = add.bind(a, a)
    assert ray_tpu.get(dag.execute(21)) == 42


# --------------------------------------------------------- compiled graphs
def test_compiled_dag_linear_chain(cluster):
    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def apply(self, x):
            return x + self.k

    s1, s2 = Stage.remote(1), Stage.remote(10)
    with InputNode() as inp:
        dag = s2.apply.bind(s1.apply.bind(inp))
    compiled = dag.experimental_compile()
    for i in range(20):
        assert compiled.execute(i) == i + 11
    compiled.teardown()
    # Actors still usable for normal calls after teardown.
    assert ray_tpu.get(s1.apply.remote(5)) == 6


def test_compiled_dag_faster_than_rpc(cluster):
    """The point of compiling: channel round-trips beat per-call task
    submission (reference: ~10x; assert >=2x to stay robust in CI)."""
    @ray_tpu.remote
    class Echo:
        def apply(self, x):
            return x

    a = Echo.remote()
    ray_tpu.get(a.apply.remote(0))  # warm up worker
    N = 200
    t0 = time.perf_counter()
    for i in range(N):
        ray_tpu.get(a.apply.remote(i))
    rpc_s = time.perf_counter() - t0

    with InputNode() as inp:
        dag = a.apply.bind(inp)
    compiled = dag.experimental_compile()
    compiled.execute(0)  # warm
    t0 = time.perf_counter()
    for i in range(N):
        compiled.execute(i)
    chan_s = time.perf_counter() - t0
    compiled.teardown()
    assert chan_s * 2 < rpc_s, (
        f"compiled {chan_s*1e6/N:.0f}us/call vs rpc {rpc_s*1e6/N:.0f}us/call"
    )


def test_compiled_dag_error_propagation(cluster):
    @ray_tpu.remote
    class Boom:
        def apply(self, x):
            if x == 13:
                raise ValueError("unlucky")
            return x

    a = Boom.remote()
    with InputNode() as inp:
        compiled = a.apply.bind(inp).experimental_compile()
    assert compiled.execute(1) == 1
    with pytest.raises(ValueError, match="unlucky"):
        compiled.execute(13)
    # Loop survives an error.
    assert compiled.execute(2) == 2
    compiled.teardown()


def test_compiled_dag_submit_collect_fifo(cluster):
    """submit/collect split: results come back in submit order and an
    error in one microbatch doesn't derail the ones behind it."""
    @ray_tpu.remote
    class Working:
        def apply(self, x):
            if x == 3:
                raise ValueError("three")
            return x * 10

    a = Working.remote()
    with InputNode() as inp:
        compiled = a.apply.bind(inp).experimental_compile()
    for i in range(3):
        compiled.submit(i)
    assert compiled.collect() == 0
    assert compiled.collect() == 10
    compiled.submit(3)
    compiled.submit(4)
    assert compiled.collect() == 20
    with pytest.raises(ValueError, match="three"):
        compiled.collect()
    assert compiled.collect() == 40
    with pytest.raises(RuntimeError, match="matching submit"):
        compiled.collect()
    compiled.teardown()


def test_compiled_dag_two_stage_pipeline_overlaps(cluster):
    """Pipeline parallelism on the compiled-DAG substrate (SURVEY §2.3):
    with two stages resident in different actor processes, microbatch
    i+1 runs stage 1 while microbatch i runs stage 2. Stage time is
    sleep-dominated (emulating device dispatch on a 1-core CI host), so
    wall clock shows the schedule: sequential = 2*M*T, pipelined ~
    (M+1)*T. Assert >=1.4x (theory 1.78x at M=8)."""
    import threading

    T = 0.08

    @ray_tpu.remote
    class Stage:
        def apply(self, x):
            time.sleep(T)
            return x + 1

    s1, s2 = Stage.remote(), Stage.remote()
    ray_tpu.get([s1.apply.remote(0), s2.apply.remote(0)])  # warm boot
    M = 8

    # Sequential oracle: each microbatch traverses both stages alone.
    t0 = time.perf_counter()
    for i in range(M):
        ray_tpu.get(s2.apply.remote(ray_tpu.get(s1.apply.remote(i))))
    seq_s = time.perf_counter() - t0

    with InputNode() as inp:
        compiled = s2.apply.bind(s1.apply.bind(inp)).experimental_compile()
    compiled.execute(0)  # warm the resident loops
    # Feeder thread keeps the pipe full (submit blocks on the bounded
    # single-slot channels — that's the backpressure, not a bug).
    t0 = time.perf_counter()
    feeder = threading.Thread(
        target=lambda: [compiled.submit(i) for i in range(M)]
    )
    feeder.start()
    results = [compiled.collect() for _ in range(M)]
    feeder.join()
    pipe_s = time.perf_counter() - t0
    compiled.teardown()

    assert results == [i + 2 for i in range(M)]
    assert pipe_s * 1.4 < seq_s, (
        f"pipelined {pipe_s:.3f}s vs sequential {seq_s:.3f}s "
        f"(speedup {seq_s / pipe_s:.2f}x)"
    )


# -------------------------------------------------------------- workflows
def test_workflow_run_and_output(cluster, tmp_path):
    workflow.init(str(tmp_path))
    dag = add.bind(mul.bind(3, 4), 5)
    out = workflow.run(dag, workflow_id="w1")
    assert out == 17
    assert workflow.get_status("w1") == "SUCCESSFUL"
    assert workflow.get_output("w1") == 17
    assert {"workflow_id": "w1", "status": "SUCCESSFUL"} in workflow.list_all()


def test_workflow_resume_skips_completed(cluster, tmp_path):
    workflow.init(str(tmp_path))
    marker = tmp_path / "count.txt"
    marker.write_text("0")

    @ray_tpu.remote
    def counted(x):
        n = int(marker.read_text()) + 1
        marker.write_text(str(n))
        return x + 100

    @ray_tpu.remote
    def fail_once(x):
        if not (marker.parent / "healed").exists():
            raise RuntimeError("transient")
        return x * 2

    dag = fail_once.bind(counted.bind(1))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w2")
    assert workflow.get_status("w2") == "FAILED"
    assert marker.read_text() == "1"  # first task DID run + persist

    (marker.parent / "healed").write_text("y")
    out = workflow.resume("w2")
    assert out == 202
    # counted was NOT re-executed on resume (exactly-once).
    assert marker.read_text() == "1"
    assert workflow.get_status("w2") == "SUCCESSFUL"


def test_workflow_run_async(cluster, tmp_path):
    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def slow(x):
        time.sleep(0.3)
        return x + 1

    fut = workflow.run_async(slow.bind(41), workflow_id="w3")
    assert fut.result(timeout=30) == 42


def test_workflow_delete(cluster, tmp_path):
    workflow.init(str(tmp_path))
    workflow.run(add.bind(1, 2), workflow_id="w4")
    workflow.delete("w4")
    assert workflow.get_status("w4") is None


# ------------------------------------------------------- event triggers

def test_workflow_waits_for_posted_event(cluster, tmp_path):
    """wait_for_event blocks the DAG until post_event fires; the
    payload flows into downstream tasks (reference:
    workflow/event_listener.py semantics)."""
    import time as _time

    from ray_tpu import workflow

    workflow.init(str(tmp_path / "wf_events"))

    @ray_tpu.remote
    def consume(evt):
        return f"paid={evt['paid']} amount={evt['amount']}"

    node = consume.bind(workflow.wait_for_event("order/42", timeout_s=60))
    fut = workflow.run_async(node, workflow_id="order-42")
    _time.sleep(0.5)
    assert not fut.done()  # genuinely waiting, not racing through
    workflow.post_event("order/42", {"paid": True, "amount": 7})
    assert fut.result(timeout=60) == "paid=True amount=7"
    # Durable: the completed wait node persisted; resume re-delivers
    # without re-waiting.
    assert workflow.resume("order-42") == "paid=True amount=7"


def test_workflow_event_over_http(cluster, tmp_path):
    """The HTTP provider: POST to the dashboard fires the event."""
    import json as _json
    import urllib.request

    from ray_tpu import workflow
    from ray_tpu.dashboard import start_dashboard

    workflow.init(str(tmp_path / "wf_http"))
    url = start_dashboard(port=18281)

    @ray_tpu.remote
    def consume(evt):
        return evt["source"]

    fut = workflow.run_async(
        consume.bind(workflow.wait_for_event("deploy/done", timeout_s=60)),
        workflow_id="http-evt",
    )
    req = urllib.request.Request(
        f"{url}/api/workflow/events/deploy/done",
        method="POST",
        data=_json.dumps({"source": "ci-pipeline"}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        assert _json.loads(r.read())["ok"]
    assert fut.result(timeout=60) == "ci-pipeline"


def test_workflow_event_timeout(cluster, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.exceptions import RayTaskError  # noqa: F401

    workflow.init(str(tmp_path / "wf_timeout"))
    node = workflow.wait_for_event("never/fires", timeout_s=1.0)
    with pytest.raises(Exception, match="not posted within"):
        workflow.run(node, workflow_id="evt-timeout")
