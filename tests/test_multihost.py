"""Multi-host control + data plane: real node-daemon processes over TCP.

Reference behaviors modeled: raylet registration with the GCS
(src/ray/gcs/gcs_server — node membership), cross-node scheduling, and
chunked node-to-node object transfer
(src/ray/object_manager/object_manager.h:63,117). The daemons run as
separate processes on this machine with their own shm pools and
namespaces, so a cross-node `get` must ride the transfer plane exactly
as it would between two hosts.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import DaemonCluster

BIG = 1 << 20  # > max_inline_object_size: forces the shm/transfer path


@pytest.fixture
def daemon_cluster():
    cluster = DaemonCluster(head_node_args={"num_cpus": 1, "tcp_port": 0})
    yield cluster
    cluster.shutdown()


@ray_tpu.remote
def whereami():
    return os.environ.get("RAY_TPU_NODE_NS", "head")


@ray_tpu.remote
def make_big(seed):
    rng = np.random.default_rng(seed)
    return rng.random(BIG // 8)  # ~1 MiB of float64


@ray_tpu.remote
def consume(arr):
    return float(arr.sum())


def test_daemons_register_and_run_tasks(daemon_cluster):
    daemon_cluster.add_node(num_cpus=2, resources={"node_a": 1.0}, label="a")
    daemon_cluster.add_node(num_cpus=2, resources={"node_b": 1.0}, label="b")
    total = ray_tpu.cluster_resources()
    assert total.get("CPU", 0) >= 5.0
    assert total.get("node_a") == 1.0 and total.get("node_b") == 1.0

    # Tasks pinned to each daemon node run in that daemon's namespace
    # (i.e. in a worker spawned by that daemon, not by the head).
    ns_a = ray_tpu.get(
        whereami.options(resources={"node_a": 0.01}).remote(), timeout=60
    )
    ns_b = ray_tpu.get(
        whereami.options(resources={"node_b": 0.01}).remote(), timeout=60
    )
    assert ns_a not in ("head", ns_b)


def test_cross_node_object_transfer(daemon_cluster):
    daemon_cluster.add_node(num_cpus=2, resources={"node_a": 1.0})
    daemon_cluster.add_node(num_cpus=2, resources={"node_b": 1.0})

    # Seal a large object on node A, read it from the driver (pull #1)
    # and from node B (pull #2) — three distinct stores.
    ref = make_big.options(resources={"node_a": 0.01}).remote(7)
    expected = np.random.default_rng(7).random(BIG // 8)
    got = ray_tpu.get(ref, timeout=60)
    assert np.allclose(got, expected)

    total = ray_tpu.get(
        consume.options(resources={"node_b": 0.01}).remote(ref), timeout=60
    )
    assert abs(total - expected.sum()) < 1e-6


def test_driver_object_pulled_by_remote_node(daemon_cluster):
    daemon_cluster.add_node(num_cpus=2, resources={"node_a": 1.0})
    arr = np.random.default_rng(3).random(BIG // 8)
    ref = ray_tpu.put(arr)  # sealed into the head store
    total = ray_tpu.get(
        consume.options(resources={"node_a": 0.01}).remote(ref), timeout=60
    )
    assert abs(total - arr.sum()) < 1e-6


def test_scheduling_spills_to_free_node(daemon_cluster):
    # Head has 1 CPU; 8 concurrent 2-CPU tasks only fit on the daemon.
    daemon_cluster.add_node(num_cpus=4, resources={"node_a": 1.0})

    @ray_tpu.remote(num_cpus=2)
    def ns():
        return os.environ.get("RAY_TPU_NODE_NS", "head")

    spots = ray_tpu.get([ns.remote() for _ in range(8)], timeout=120)
    assert all(s != "head" for s in spots)


def test_node_death_detected(daemon_cluster):
    proc = daemon_cluster.add_node(num_cpus=2, resources={"node_a": 1.0})
    assert ray_tpu.cluster_resources().get("node_a") == 1.0

    @ray_tpu.remote
    def sleepy():
        from ray_tpu._private.worker import global_client

        global_client().kv_put(b"sleepy_started", b"1")
        time.sleep(60)

    ref = sleepy.options(resources={"node_a": 0.01}, max_retries=0).remote()
    # Only kill once the task is actually running on the daemon's worker —
    # killed-while-pending would (correctly) leave it queued as infeasible.
    from ray_tpu._private.worker import global_client

    deadline = time.time() + 30
    while time.time() < deadline:
        if global_client().kv_get(b"sleepy_started"):
            break
        time.sleep(0.1)
    else:
        raise TimeoutError("task never started on the daemon node")
    daemon_cluster.kill_node(proc)
    with pytest.raises(ray_tpu.exceptions.WorkerCrashedError):
        ray_tpu.get(ref, timeout=30)
    deadline = time.time() + 10
    while time.time() < deadline:
        if ray_tpu.cluster_resources().get("node_a", 0) == 0:
            break
        time.sleep(0.2)
    assert ray_tpu.cluster_resources().get("node_a", 0) == 0


def test_remote_driver_over_tcp(daemon_cluster):
    # A second driver process connects over host:port?authkey, submits
    # work, and round-trips a large object both directions.
    script = """
import sys
import numpy as np
import ray_tpu

address = sys.argv[1]
ray_tpu.init(address=address)

@ray_tpu.remote
def double(a):
    return a * 2

arr = np.arange(300_000, dtype=np.float64)
out = ray_tpu.get(double.remote(arr), timeout=60)
assert np.allclose(out, arr * 2)
print("REMOTE-DRIVER-OK")
"""
    addr = f"{daemon_cluster.head_address}?{daemon_cluster.authkey.hex()}"
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("RAY_TPU_POOL_NAME", "RAY_TPU_NODE_NS")
    }
    out = subprocess.run(
        [sys.executable, "-c", script, addr],
        capture_output=True,
        timeout=120,
        env=env,
    )
    assert b"REMOTE-DRIVER-OK" in out.stdout, out.stderr.decode(errors="replace")


def test_jax_distributed_train_across_daemon_nodes(daemon_cluster):
    # Two TrainWorker actors on two different daemon nodes form one
    # jax.distributed cluster (CPU backend): every host sees the global
    # device set and an in-graph psum crosses the process boundary
    # (SURVEY.md §2.3 train bootstrap; reference: torch-XLA backend
    # train/torch/xla/config.py:73 dist.init_process_group).
    from ray_tpu import train as rt_train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    daemon_cluster.add_node(num_cpus=2, resources={"slot": 1.0})
    daemon_cluster.add_node(num_cpus=2, resources={"slot": 1.0})

    def loop(config):
        import jax
        import jax.numpy as jnp

        n = jax.local_device_count()
        out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((n,))
        )
        rt_train.report(
            {
                "process_count": jax.process_count(),
                "global_devices": jax.device_count(),
                "global_sum": float(out[0]),
            }
        )

    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2,
            resources_per_worker={"slot": 1.0, "CPU": 1.0},
            use_jax_distributed=True,
        ),
        run_config=RunConfig(storage_path="/tmp/ray_tpu_mh_train"),
    ).fit()
    assert result.error is None, result.error
    assert result.metrics["process_count"] == 2
    assert result.metrics["global_sum"] == result.metrics["global_devices"]


def test_hung_node_declared_dead_by_heartbeat(daemon_cluster):
    # SIGSTOP the daemon: its TCP connection stays established but
    # heartbeats stop; the GCS health loop must declare the node dead
    # (reference: gcs_health_check_manager.h:39).
    import signal

    proc = daemon_cluster.add_node(num_cpus=2, resources={"node_a": 1.0})
    assert ray_tpu.cluster_resources().get("node_a") == 1.0
    os.kill(proc.pid, signal.SIGSTOP)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            if ray_tpu.cluster_resources().get("node_a", 0) == 0:
                break
            time.sleep(0.3)
        assert ray_tpu.cluster_resources().get("node_a", 0) == 0
    finally:
        os.kill(proc.pid, signal.SIGCONT)
        daemon_cluster.kill_node(proc)
