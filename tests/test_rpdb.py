"""Remote debugger (util/rpdb.py; reference: python/ray/util/rpdb.py):
set_trace() in a task parks it on a socket; a client attaches, inspects
live frame state, and `c` resumes the task."""
import time

import pytest

import ray_tpu
from ray_tpu.util import rpdb


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_set_trace_attach_inspect_continue(cluster):
    @ray_tpu.remote
    def buggy(x):
        secret = x * 7
        rpdb.set_trace()
        return secret

    ref = buggy.remote(6)

    # The session shows up in the registry while the task is parked.
    deadline = time.time() + 60
    live = []
    while time.time() < deadline:
        live = rpdb.sessions()
        if live:
            break
        time.sleep(0.2)
    assert live, "no rpdb session registered"
    _, addr = live[0]

    sock = rpdb.connect(addr)
    f = sock.makefile("rw", buffering=1)

    def read_until_prompt():
        out = []
        while True:
            ch = f.read(1)
            if not ch:
                break
            out.append(ch)
            s = "".join(out)
            if s.endswith("(rpdb) "):
                return s
        return "".join(out)

    banner = read_until_prompt()
    assert "buggy" in banner or "rpdb" in banner or "->" in banner
    f.write("p secret\n")
    out = read_until_prompt()
    assert "42" in out
    f.write("c\n")
    f.flush()
    sock.close()

    assert ray_tpu.get(ref, timeout=60) == 42
    # Session deregistered once attached.
    assert not rpdb.sessions()
