"""Placement groups + multi-node resource scheduling.

Models the reference's python/ray/tests/test_placement_group.py and the
Cluster-in-one-process harness (cluster_utils.py:135).
"""
import pytest

import ray_tpu
from ray_tpu.exceptions import PlacementGroupSchedulingError
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


def test_pg_create_ready(ray_start):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(10)
    assert pg.bundle_count == 2
    remove_placement_group(pg)


def test_pg_reserves_resources(ray_start):
    pg = placement_group([{"CPU": 3}], strategy="PACK")
    assert ray_tpu.available_resources().get("CPU", 0) == 1.0
    remove_placement_group(pg)
    assert ray_tpu.available_resources().get("CPU", 0) == 4.0


def test_pg_unschedulable(ray_start):
    with pytest.raises(PlacementGroupSchedulingError):
        placement_group([{"CPU": 100}], strategy="STRICT_PACK")


def test_task_in_pg(ray_start):
    pg = placement_group([{"CPU": 2}], strategy="PACK")

    @ray_tpu.remote(num_cpus=2)
    def f():
        return "in-bundle"

    strategy = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0
    )
    ref = f.options(scheduling_strategy=strategy).remote()
    assert ray_tpu.get(ref, timeout=30) == "in-bundle"
    remove_placement_group(pg)


def test_actor_in_pg(ray_start):
    pg = placement_group([{"CPU": 1}], strategy="PACK")

    @ray_tpu.remote(num_cpus=1)
    class A:
        def ping(self):
            return "pong"

    a = A.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        )
    ).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"


def test_pg_bundle_capacity_enforced(ray_start):
    pg = placement_group([{"CPU": 1}], strategy="PACK")

    @ray_tpu.remote(num_cpus=1)
    def hold():
        import time

        time.sleep(0.5)
        return 1

    strategy = PlacementGroupSchedulingStrategy(placement_group=pg)
    # Two 1-CPU tasks against a 1-CPU bundle must serialize.
    import time

    r1 = hold.options(scheduling_strategy=strategy).remote()
    r2 = hold.options(scheduling_strategy=strategy).remote()
    start = time.monotonic()
    ray_tpu.get([r1, r2], timeout=60)
    assert time.monotonic() - start >= 0.8


def test_strict_spread_fails_single_node(ray_start):
    # One node: STRICT_SPREAD of 2 bundles cannot be placed.
    with pytest.raises(PlacementGroupSchedulingError):
        placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
