"""Native control-plane hot path (native/fastpath.c).

Reference: the compiled submit/receive path (_raylet.pyx:3996) and
hand-rolled hot-RPC encodings (src/ray/protobuf/). The codec must
round-trip every hot frame shape bit-exactly against the pickle
fallback, reject truncated/corrupt input without crashing, and
interoperate per-message with pickle senders (magic-byte routing).
"""
import pickle

import pytest

from ray_tpu._private import fastpath

fp = fastpath.get()
pytestmark = pytest.mark.skipif(fp is None, reason="no native toolchain")

TID = bytes(range(16))
CALL = (1, 7, TID, b"f" * 16, None, b"args-blob", 2, None, None)
ACTOR_CALL = (1, 8, TID, None, "method_name", b"", 1, b"a" * 16, "io")
REPLY_OK = (2, 7, None, [(b"inline", None, 6, ()), (None, "seg_9", 4096, (b"c" * 16, b"d" * 16))])
REPLY_ERR = (2, 9, b"pickled-exc", [])
RDY = ("RDY", (b"o" * 16,))


@pytest.mark.parametrize(
    "frame", [CALL, ACTOR_CALL, REPLY_OK, REPLY_ERR, RDY],
    ids=["call", "actor_call", "reply_ok", "reply_err", "rdy"],
)
def test_roundtrip_exact(frame):
    enc = fp.encode(frame)
    assert isinstance(enc, bytes) and enc[0] == 0xF1
    out = fp.decode(enc)
    assert out == frame
    # Same structure pickle would deliver (types too, not just ==).
    assert repr(out) == repr(pickle.loads(pickle.dumps(frame, 5)))


def test_batch_mixed_elements():
    batch = ("B", [CALL, {"type": "task_done", "n": 1}, REPLY_OK, RDY])
    enc = fp.encode(batch)
    assert enc is not None
    assert fp.decode(enc) == batch


def test_unsupported_shapes_fall_back():
    assert fp.encode({"type": "hello"}) is None
    assert fp.encode((99, "unknown-op")) is None
    assert fp.encode(("X", [1])) is None
    # lists of ids in RDY (the head builds tuples, but be liberal)
    assert fp.decode(fp.encode(("RDY", [b"o" * 16]))) == ("RDY", (b"o" * 16,))


def test_truncated_and_corrupt_input():
    enc = fp.encode(CALL)
    for cut in (1, 2, 5, len(enc) - 1):
        with pytest.raises(ValueError):
            fp.decode(enc[:cut])
    with pytest.raises(ValueError):
        fp.decode(b"\x80\x05garbage")  # pickle magic, not ours
    with pytest.raises(ValueError):
        fp.decode(b"\xf1\x63")  # bad kind


def test_return_oids_match_python():
    from ray_tpu._private.ids import ObjectID

    tid = bytes(range(16))
    assert fp.return_oids(tid, 5) == [
        ObjectID.bytes_for_return(tid, i) for i in range(5)
    ]
    assert fp.return_oids(tid, 0) == []


def test_wait_partition_semantics():
    from ray_tpu._private.ids import ObjectID
    from ray_tpu.object_ref import ObjectRef

    refs = [ObjectRef(ObjectID(bytes([i]) * 16)) for i in range(6)]
    ready = {refs[1]._id._bytes, refs[3]._id._bytes, refs[5]._id._bytes}
    part = fp.wait_partition(refs, ready, 2)
    assert part is not None
    got, rest = part
    assert got == [refs[1], refs[3]]  # order preserved, capped at n
    assert rest == [refs[0], refs[2], refs[4], refs[5]]
    assert fp.wait_partition(refs, ready, 4) is None  # only 3 ready


def test_large_frame_roundtrip():
    big = (1, 2**31, TID, None, "m", b"x" * (1 << 20), 1, b"a" * 16, None)
    # req_id must fit u32; 2**31 does.
    assert fp.decode(fp.encode(big)) == big
