"""Graceful node drain (reference: node_manager.h:551 HandleDrainRaylet,
autoscaler DrainNode-before-terminate).

Drain semantics under test: no new placements on a draining node,
running work finishes before removal, the deadline forces removal, and
the autoscaler's idle scale-down path drains instead of yanking nodes.
"""
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def two_nodes():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, label="b")
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote
def _hold(sec: float):
    time.sleep(sec)
    return "done"


def _node(label):
    return next(n for n in ray_tpu.nodes() if n["label"] == label)


def _wait(pred, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return False


def test_drain_removes_quiet_node(two_nodes):
    b = _node("b")
    assert ray_tpu.drain_node(b["node_id"], reason="test") is True
    # Quiet node: removed promptly by the health loop's drain tick.
    assert _wait(
        lambda: all(
            n["node_id"] != b["node_id"] or not n["alive"]
            for n in ray_tpu.nodes()
        )
    ), "drained node was not removed"


def test_drain_waits_for_running_task(two_nodes):
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    b = _node("b")
    ref = _hold.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=b["node_id"], soft=False
        )
    ).remote(4.0)
    # Wait until the task holds b's CPU.
    assert _wait(
        lambda: _node("b")["available"].get("CPU", 2) < 2
    ), "task never started on b"
    t0 = time.time()
    assert ray_tpu.drain_node(
        b["node_id"], reason="test", deadline_s=30.0
    )
    # The running task completes normally (not killed).
    assert ray_tpu.get(ref, timeout=30) == "done"
    # ... and only then is the node removed.
    assert _wait(
        lambda: all(
            n["node_id"] != b["node_id"] or not n["alive"]
            for n in ray_tpu.nodes()
        )
    )
    assert time.time() - t0 >= 2.0, "node removed before its task finished"


def test_drain_rejects_new_placements(two_nodes):
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    b = _node("b")
    ray_tpu.drain_node(b["node_id"], reason="test", deadline_s=60.0)
    # Hard affinity to a draining node can never be satisfied.
    ref = _hold.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=b["node_id"], soft=False
        )
    ).remote(0.1)
    with pytest.raises(ray_tpu.exceptions.TaskUnschedulableError):
        ray_tpu.get(ref, timeout=15)


def test_drain_deadline_forces_removal(two_nodes):
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    b = _node("b")
    ref = _hold.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=b["node_id"], soft=False
        )
    ).remote(60.0)
    assert _wait(lambda: _node("b")["available"].get("CPU", 2) < 2)
    ray_tpu.drain_node(b["node_id"], reason="preempt", deadline_s=1.0)
    assert _wait(
        lambda: all(
            n["node_id"] != b["node_id"] or not n["alive"]
            for n in ray_tpu.nodes()
        ),
        timeout=20,
    ), "deadline did not force removal"
    # The interrupted task surfaces a worker-death error.
    with pytest.raises(
        (
            ray_tpu.exceptions.WorkerCrashedError,
            ray_tpu.exceptions.RayTaskError,
        )
    ):
        ray_tpu.get(ref, timeout=20)


def test_autoscaler_drains_idle_nodes():
    from ray_tpu.autoscaler import Autoscaler, FakeNodeProvider

    ray_tpu.init(num_cpus=1, ignore_reinit_error=True)
    try:
        provider = FakeNodeProvider()
        asc = Autoscaler(
            {"cpu": {"resources": {"CPU": 2.0}, "max_workers": 2}},
            provider,
            idle_timeout_s=1.0,
        )
        # Force demand: a task shape the 1-CPU head can't take.
        ref = _hold.options(num_cpus=2).remote(2.0)
        deadline = time.time() + 20
        while time.time() < deadline and asc.num_launches == 0:
            asc.update()
            time.sleep(0.2)
        assert asc.num_launches >= 1
        assert ray_tpu.get(ref, timeout=30) == "done"
        # Idle scale-down goes through drain, then releases the node.
        deadline = time.time() + 30
        while time.time() < deadline and asc.num_terminations == 0:
            asc.update()
            time.sleep(0.2)
        assert asc.num_terminations >= 1
        assert provider.non_terminated_nodes() == []
    finally:
        ray_tpu.shutdown()
