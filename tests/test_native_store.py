"""C++ pool object store: allocator, refcounts, eviction, integration.

Models the reference's plasma tests
(src/ray/object_manager/plasma/test/, python/ray/tests/test_plasma*).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from ray_tpu._private.native_store import PoolStore, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native store did not build"
)


@pytest.fixture
def pool():
    name = f"/rtpu_t_{os.getpid()}"
    p = PoolStore(name, create=True, pool_bytes=16 << 20, max_objects=512,
                  evict=True)
    yield p
    p.destroy()


def _oid(i: int) -> bytes:
    return i.to_bytes(16, "little")


def test_create_seal_get_release_delete(pool):
    v = pool.create(_oid(1), 100)
    v[:5] = b"hello"
    del v
    assert not pool.contains(_oid(1))  # unsealed: not visible
    assert pool.seal(_oid(1))
    assert pool.contains(_oid(1))
    g = pool.get(_oid(1))
    assert bytes(g[:5]) == b"hello" and len(g) == 100
    del g
    pool.release(_oid(1))
    pool.delete(_oid(1))
    assert not pool.contains(_oid(1))


def test_duplicate_create_rejected(pool):
    v = pool.create(_oid(2), 10)
    del v
    assert pool.create(_oid(2), 10) is None


def test_allocator_reuses_freed_space(pool):
    # Fill ~3/4 of a 16MB pool, free, refill — the allocator must
    # coalesce and reuse, not leak.
    for round_ in range(5):
        ids = []
        for i in range(12):
            oid = _oid(1000 + round_ * 100 + i)
            v = pool.create(oid, 1 << 20)
            assert v is not None, f"round {round_}, obj {i}: allocator leaked"
            del v
            pool.seal(oid)
            ids.append(oid)
        for oid in ids:
            pool.delete(oid)
    assert pool.stats()["bytes_in_use"] == 0


def test_lru_eviction_under_pressure(pool):
    ids = [_oid(3000 + i) for i in range(30)]
    for oid in ids:
        v = pool.create(oid, 1 << 20)
        assert v is not None  # eviction makes room
        del v
        pool.seal(oid)
    st = pool.stats()
    assert st["num_evictions"] > 0
    assert pool.contains(ids[-1])
    assert not pool.contains(ids[0])  # oldest evicted


def test_referenced_objects_survive_eviction(pool):
    first = _oid(4000)
    v = pool.create(first, 1 << 20)
    v[:6] = b"pinned"  # payloads are malloc-style: not zeroed
    del v
    pool.seal(first)
    held = pool.get(first)  # refcount 1 — pin it
    for i in range(30):
        oid = _oid(4001 + i)
        w = pool.create(oid, 1 << 20)
        if w is None:
            break
        del w
        pool.seal(oid)
    assert pool.contains(first), "pinned object must not be evicted"
    assert bytes(held[:6]) == b"pinned", "pinned payload was clobbered"
    del held
    pool.release(first)


def test_cross_process_visibility(pool):
    v = pool.create(_oid(5), 8)
    v[:] = b"crosproc"
    del v
    pool.seal(_oid(5))
    code = f"""
from ray_tpu._private.native_store import PoolStore
p = PoolStore({pool.name!r}, create=False)
v = p.get((5).to_bytes(16, "little"))
assert bytes(v) == b"crosproc", bytes(v)
del v
p.release((5).to_bytes(16, "little"))
p.close()
print("OK")
"""
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))},
    )
    assert r.returncode == 0 and "OK" in r.stdout, r.stderr[-500:]


def test_default_pool_full_fails_create_no_eviction():
    """Session pools default to evict=False: a full pool rejects the
    create (callers fall back to per-object segments) rather than
    silently evicting objects live ObjectRefs may still name."""
    name = f"/rtpu_noevict_{os.getpid()}"
    p = PoolStore(name, create=True, pool_bytes=4 << 20, max_objects=64)
    try:
        created = 0
        for i in range(10):
            v = p.create(_oid(i), 1 << 20)
            if v is None:
                break
            del v
            p.seal(_oid(i))
            created += 1
        assert 0 < created < 10
        assert p.stats()["num_evictions"] == 0
        for i in range(created):  # everything created is still there
            assert p.contains(_oid(i))
    finally:
        p.destroy()


def test_public_api_via_pool():
    """End-to-end: ray_tpu.put/get of a large array rides the pool."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        arr = np.random.rand(1024, 256)  # 2MB > inline threshold
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref)
        assert np.array_equal(arr, out)

        @ray_tpu.remote
        def consume(x):
            return float(x.sum())

        assert abs(ray_tpu.get(consume.remote(ref)) - arr.sum()) < 1e-6
    finally:
        ray_tpu.shutdown()


def test_deferred_delete_frees_on_last_release():
    # Delete-while-referenced must free the block when the last reader
    # releases, even with eviction disabled (the session-pool default) —
    # otherwise deleted-but-referenced objects leak arena space forever.
    name = f"/rtpu_dd_{os.getpid()}"
    pool = PoolStore(name, create=True, pool_bytes=4 << 20, max_objects=64,
                     evict=False)
    try:
        v = pool.create(_oid(7), 1 << 20)
        del v
        assert pool.seal(_oid(7))
        g = pool.get(_oid(7))  # rc = 1
        base = pool.stats()["bytes_in_use"]
        pool.delete(_oid(7))  # deferred: reader still holds a ref
        assert pool.stats()["bytes_in_use"] == base  # still pinned
        assert not pool.contains(_oid(7))  # but invisible to readers
        assert pool.get(_oid(7)) is None
        del g
        pool.release(_oid(7))  # last release frees, no eviction needed
        assert pool.stats()["bytes_in_use"] < base
        # The slot is reusable immediately.
        v2 = pool.create(_oid(7), 1 << 20)
        assert v2 is not None
        del v2
    finally:
        pool.destroy()
