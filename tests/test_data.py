"""ray_tpu.data tests (reference test strategy: python/ray/data/tests —
deterministic range datasource, small local clusters)."""
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_range_count_take(cluster):
    ds = rd.range(100, override_num_blocks=4)
    assert ds.count() == 100
    assert ds.take(3) == [{"id": 0}, {"id": 1}, {"id": 2}]
    assert ds.num_blocks() == 4


def test_map_batches_and_fusion(cluster):
    ds = (
        rd.range(64, override_num_blocks=4)
        .map_batches(lambda b: {"id": b["id"] * 2})
        .map_batches(lambda b: {"id": b["id"] + 1})
    )
    rows = ds.take_all()
    assert [r["id"] for r in rows] == [2 * i + 1 for i in range(64)]
    # both maps fused into one segment with the read
    from ray_tpu.data._plan import optimize

    segments = optimize(ds._plan)
    assert len(segments) == 1
    assert len(segments[0].spec.transforms) == 2


def test_map_filter_flat_map(cluster):
    ds = rd.range(20, override_num_blocks=2).map(lambda r: {"id": r["id"] * 10})
    assert ds.take(2) == [{"id": 0}, {"id": 10}]
    ds2 = rd.range(20, override_num_blocks=2).filter(lambda r: r["id"] % 2 == 0)
    assert ds2.count() == 10
    ds3 = rd.from_items([1, 2]).flat_map(
        lambda r: [{"x": r["item"]}, {"x": -r["item"]}]
    )
    assert sorted(r["x"] for r in ds3.take_all()) == [-2, -1, 1, 2]


def test_limit_pushdown_and_limit(cluster):
    ds = rd.range(1000, override_num_blocks=10).map(
        lambda r: {"id": r["id"]}
    ).limit(7)
    assert ds.count() == 7
    from ray_tpu.data._plan import optimize

    segs = optimize(ds._plan)
    assert segs[0].stop_after_rows == 7


def test_repartition(cluster):
    ds = rd.range(100, override_num_blocks=7).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 100
    assert sorted(r["id"] for r in ds.take_all()) == list(range(100))


def test_random_shuffle_and_sort(cluster):
    ds = rd.range(50, override_num_blocks=4).random_shuffle(seed=7)
    ids = [r["id"] for r in ds.take_all()]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))
    ds2 = ds.sort("id")
    assert [r["id"] for r in ds2.take_all()] == list(range(50))
    ds3 = rd.range(30, override_num_blocks=3).sort("id", descending=True)
    assert [r["id"] for r in ds3.take_all()] == list(reversed(range(30)))


def test_groupby(cluster):
    ds = rd.from_items(
        [{"k": i % 3, "v": float(i)} for i in range(30)], parallelism=4
    )
    out = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    expect = {}
    for i in range(30):
        expect[i % 3] = expect.get(i % 3, 0.0) + i
    assert out == expect
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}


def test_union_zip(cluster):
    a = rd.range(5, override_num_blocks=1)
    b = rd.range(5, override_num_blocks=1).map(lambda r: {"id": r["id"] + 5})
    assert sorted(r["id"] for r in a.union(b).take_all()) == list(range(10))
    z = a.zip(b)
    rows = z.take_all()
    assert rows[0] == {"id": 0, "id_1": 5}


def test_iter_batches(cluster):
    ds = rd.range(100, override_num_blocks=5)
    batches = list(ds.iter_batches(batch_size=32, drop_last=False))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]
    arr = np.concatenate([b["id"] for b in batches])
    assert arr.tolist() == list(range(100))
    pdb = list(ds.iter_batches(batch_size=None, batch_format="pandas"))
    assert sum(len(p) for p in pdb) == 100


def test_aggregates(cluster):
    ds = rd.range(10)
    assert ds.sum("id") == 45
    assert ds.min("id") == 0
    assert ds.max("id") == 9
    assert ds.mean("id") == 4.5
    assert ds.schema().names == ["id"]


def test_file_roundtrip_parquet_csv_json(cluster, tmp_path):
    ds = rd.range(20, override_num_blocks=2).map(
        lambda r: {"id": r["id"], "sq": r["id"] ** 2}
    )
    pdir = str(tmp_path / "pq")
    ds.write_parquet(pdir)
    back = rd.read_parquet(pdir)
    assert back.count() == 20
    assert sorted(r["sq"] for r in back.take_all()) == sorted(
        i ** 2 for i in range(20)
    )
    cdir = str(tmp_path / "csv")
    ds.write_csv(cdir)
    assert rd.read_csv(cdir).count() == 20
    jdir = str(tmp_path / "json")
    ds.write_json(jdir)
    assert rd.read_json(jdir).count() == 20


def test_tfrecords_roundtrip(cluster, tmp_path):
    ds = rd.from_items(
        [{"x": i, "y": float(i) / 2, "name": f"r{i}"} for i in range(8)]
    )
    tdir = str(tmp_path / "tfr")
    ds.write_tfrecords(tdir)
    back = rd.read_tfrecords(tdir)
    rows = sorted(back.take_all(), key=lambda r: r["x"])
    assert rows[3]["x"] == 3
    assert abs(rows[3]["y"] - 1.5) < 1e-6
    assert rows[3]["name"] == b"r3"


def test_from_pandas_numpy_arrow(cluster):
    import pandas as pd
    import pyarrow as pa

    df = pd.DataFrame({"a": [1, 2, 3]})
    assert rd.from_pandas(df).count() == 3
    assert rd.from_numpy(np.arange(4)).count() == 4
    assert rd.from_arrow(pa.table({"a": [1, 2]})).count() == 2
    out = rd.from_pandas(df).to_pandas()
    assert out["a"].tolist() == [1, 2, 3]


def test_split_and_streaming_split(cluster):
    ds = rd.range(40, override_num_blocks=4)
    parts = ds.split(2)
    assert sum(p.count() for p in parts) == 40

    its = ds.streaming_split(2, equal=True)
    seen = []
    for it in its:
        for b in it.iter_batches(batch_size=None):
            seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(40))
    # second epoch works (epoch barrier)
    again = []
    for it in its:
        for b in it.iter_batches(batch_size=None):
            again.extend(b["id"].tolist())
    assert sorted(again) == list(range(40))


def test_iter_jax_batches(cluster):
    import jax.numpy as jnp

    ds = rd.range(16, override_num_blocks=2)
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert len(batches) == 2
    assert isinstance(batches[0]["id"], jnp.ndarray)
    assert batches[0]["id"].sum() == sum(range(8))


def test_groupby_string_keys_across_workers(cluster):
    # Python hash() is salted per process; grouping must use a stable hash
    # or equal keys scatter into different partitions.
    ds = rd.from_items(
        [{"k": f"key{i % 5}", "v": 1.0} for i in range(200)], parallelism=8
    )
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {f"key{i}": 40 for i in range(5)}


def test_limit_exact_mid_block(cluster):
    assert rd.range(10, override_num_blocks=4).limit(5).count() == 5
    rows = rd.range(10, override_num_blocks=4).limit(5).take_all()
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_limit_not_pushed_past_map_batches(cluster):
    def double(b):
        import numpy as np

        return {"id": np.repeat(b["id"], 2)}

    ds = rd.range(20, override_num_blocks=2).map_batches(double).limit(5)
    assert ds.count() == 5


def test_tensor_shape_preserved(cluster):
    ds = rd.range_tensor(4, shape=(2, 2), override_num_blocks=2)
    batch = ds.take_batch(4)
    assert batch["item"].shape == (4, 2, 2)
    ds2 = rd.from_numpy(np.arange(24).reshape(4, 2, 3))
    assert ds2.take_batch(4)["item"].shape == (4, 2, 3)


def test_empty_after_filter_pipelines(cluster):
    # fns must not be called on schema-less emptied blocks; sort/shuffle
    # of all-empty data must not crash
    ds = (
        rd.range(10, override_num_blocks=3)
        .filter(lambda r: False)
        .map_batches(lambda b: {"y": b["id"] * 2})
    )
    assert ds.count() == 0
    assert rd.range(10, override_num_blocks=3).filter(
        lambda r: False
    ).sort("id").count() == 0


def test_tfrecord_negative_ints(cluster, tmp_path):
    ds = rd.from_items([{"x": -1}, {"x": -(2 ** 40)}, {"x": 7}])
    tdir = str(tmp_path / "neg")
    ds.write_tfrecords(tdir)
    vals = sorted(r["x"] for r in rd.read_tfrecords(tdir).take_all())
    assert vals == [-(2 ** 40), -1, 7]


def test_streaming_split_epoch_isolation(cluster):
    # a fast rank advancing epochs must not clobber a slow rank's epoch
    its = rd.range(20, override_num_blocks=2).streaming_split(2)
    fast, slow = its
    fast_e0 = [v for b in fast.iter_batches(batch_size=None)
               for v in b["id"].tolist()]
    # fast rank starts epoch 1 before the slow rank ever read epoch 0
    fast_e1 = [v for b in fast.iter_batches(batch_size=None)
               for v in b["id"].tolist()]
    slow_e0 = [v for b in slow.iter_batches(batch_size=None)
               for v in b["id"].tolist()]
    # epoch-0 halves must still cover the full dataset exactly
    assert sorted(fast_e0 + slow_e0) == list(range(20))
    assert len(fast_e1) == 10


def test_columns_ops(cluster):
    ds = rd.range(5).add_column("two", lambda b: b["id"] * 2)
    assert ds.take(1) == [{"id": 0, "two": 0}]
    assert ds.select_columns(["two"]).columns() == ["two"]
    assert ds.drop_columns(["two"]).columns() == ["id"]
    ds2 = ds.rename_columns({"two": "double"})
    assert "double" in ds2.columns()
