"""Llama-family causal LM in flax, designed for GSPMD sharding.

The reference framework ships no model code (models live in user code /
integrations); the TPU rebuild needs a flagship model family to carry
the Train/RLlib benchmarks (BASELINE.md: Llama-2-7B >=40% MFU on v5e).
Architecture follows Llama-2: RMSNorm, rotary embeddings, GQA
attention, SwiGLU MLP, untied or tied LM head.

Sharding: parameters keep flax's standard naming so
`parallel.mesh.spec_for_param` places them (kernel [in, out] ->
(fsdp, tensor); embedding [vocab, embed] -> (tensor, fsdp)).
Activations get in-graph constraints through
`parallel.with_logical_constraint`. Attention dispatches to the pallas
flash kernel on TPU and to ring attention when the mesh has a nontrivial
`seq` axis (long-context sequence parallelism, net-new vs reference).

Compute in bfloat16, parameters and reductions in float32 (MXU-friendly,
HBM-light).
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import flash_attention
from ..ops.ring_attention import ring_self_attention
from ..parallel.mesh import with_logical_constraint


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # Sequence parallelism: run attention as a ring over the mesh `seq`
    # axis (requires an ambient mesh passed to __call__ via module attr).
    remat: bool = True
    # "nothing": full per-layer recompute in backward (minimum memory,
    # pays an extra forward — the right trade at 1B+ params on 16 GiB).
    # "dots": save matmul outputs, recompute only elementwise — the
    # right trade for smaller models (e.g. sparse-MoE) where the extra
    # forward caps MFU at 0.75 of peak but activations fit.
    remat_policy: str = "nothing"

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, i, v, l = self.hidden_size, self.intermediate_size, self.vocab_size, self.num_layers
        hd = self.head_dim_
        attn = h * (self.num_heads * hd) * 2 + h * (self.num_kv_heads * hd) * 2
        mlp = 3 * h * i
        per_layer = attn + mlp + 2 * h
        emb = v * h * (1 if self.tie_embeddings else 2)
        return l * per_layer + emb + h


CONFIGS: Dict[str, LlamaConfig] = {
    # test-size
    "llama-tiny": LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=352, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=256,
    ),
    "llama-125m": LlamaConfig(
        vocab_size=32000, hidden_size=768, intermediate_size=2048, num_layers=12,
        num_heads=12, num_kv_heads=12, max_seq_len=2048,
    ),
    "llama-1b": LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5504, num_layers=22,
        num_heads=16, num_kv_heads=16, max_seq_len=4096,
    ),
    "llama-3b": LlamaConfig(
        vocab_size=32000, hidden_size=2560, intermediate_size=6912, num_layers=32,
        num_heads=20, num_kv_heads=20, max_seq_len=4096,
    ),
    "llama-2-7b": LlamaConfig(),  # the Llama-2-7B shape
}


def remat_policy(cfg: LlamaConfig):
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    return jax.checkpoint_policies.nothing_saveable


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings. x [B, H, T, D], positions [B, T]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None, :, None].astype(jnp.float32) * freqs  # [B,1,T,D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), self.param_dtype)
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        hd = cfg.head_dim_
        dense = lambda feats, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
        )
        q = dense((cfg.num_heads, hd), "q_proj")(x)
        k = dense((cfg.num_kv_heads, hd), "k_proj")(x)
        v = dense((cfg.num_kv_heads, hd), "v_proj")(x)
        # [B, T, H, D] -> [B, H, T, D]
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        use_ring = (
            self.mesh is not None and self.mesh.shape.get("seq", 1) > 1
        )
        if use_ring:
            o = ring_self_attention(q, k, v, self.mesh, causal=True)
        else:
            o = flash_attention(q, k, v, causal=True)
        o = o.transpose(0, 2, 1, 3)  # [B, T, H, D]
        out = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="o_proj",
        )(o)
        return out


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="gate_proj")(x)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
                      param_dtype=cfg.param_dtype, name="up_proj")(x)
        h = nn.silu(gate) * up
        h = with_logical_constraint(h, ("batch", "seq", "mlp"))
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="down_proj")(h)


class DecoderLayer(nn.Module):
    cfg: LlamaConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h = x + Attention(cfg, mesh=self.mesh, name="attn")(
            RMSNorm(cfg.rms_eps, cfg.param_dtype, name="input_norm")(x), positions
        )
        out = h + MLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_eps, cfg.param_dtype, name="post_attn_norm")(h)
        )
        return with_logical_constraint(out, ("batch", "seq", "embed"))


class LlamaForCausalLM(nn.Module):
    cfg: LlamaConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, input_ids, positions=None, return_hidden=False):
        """``return_hidden=True`` yields the final-norm hidden states
        instead of logits, so a chunked loss can apply the LM head
        per sequence chunk — at long T the full [B, T, V] logits
        tensor (4.2 GB in f32 at T=32k, V=32k) is the single biggest
        activation and never needs to exist."""
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1])[None], input_ids.shape
            )
        emb = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="embed_tokens",
        )
        if self.mesh is not None and self.mesh.size > 1:
            # One-hot matmul lookup: with the table sharded
            # (vocab=tensor, embed=fsdp) a gather forces SPMD into full
            # rematerialization (replicate-then-repartition every step);
            # a contraction over the vocab axis instead becomes partial
            # products + psum over `tensor`, rides the MXU, and XLA fuses
            # the one-hot so the [B,S,V] operand is never materialized.
            one_hot = jax.nn.one_hot(input_ids, cfg.vocab_size, dtype=cfg.dtype)
            x = jnp.einsum(
                "bsv,ve->bse", one_hot, emb.embedding.astype(cfg.dtype)
            )
        else:
            x = emb(input_ids)
        x = with_logical_constraint(x, ("batch", "seq", "embed"))
        layer_cls = DecoderLayer
        if cfg.remat:
            layer_cls = nn.remat(
                DecoderLayer, prevent_cse=False,
                policy=remat_policy(cfg),
            )
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, mesh=self.mesh, name=f"layers_{i}")(x, positions)
        x = RMSNorm(cfg.rms_eps, cfg.param_dtype, name="final_norm")(x)
        if return_hidden:
            return x
        if cfg.tie_embeddings:
            logits = emb.attend(x.astype(cfg.param_dtype))
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                param_dtype=cfg.param_dtype, name="lm_head",
            )(x)
        return logits


def lm_head_weight(params) -> jax.Array:
    """[V, H] output-projection weight from a param tree (tied
    embedding table, or the dedicated lm_head kernel transposed)."""
    p = params.get("params", params)
    if "lm_head" in p:
        return p["lm_head"]["kernel"].T
    return p["embed_tokens"]["embedding"]


def chunked_causal_lm_loss(
    model,
    params,
    input_ids: jax.Array,
    targets: jax.Array,
    mask: Optional[jax.Array] = None,
    chunk_size: int = 2048,
) -> jax.Array:
    """Next-token cross-entropy without materializing full logits.

    The [B, T, V] logits tensor is the largest activation at long T
    (f32 T=32k, V=32k is 4.2 GB — bigger than the whole remat'd
    transformer). Scanning the LM head + softmax-xent over sequence
    chunks keeps only [B, chunk, V] alive; jax.checkpoint recomputes
    each chunk's logits in the backward, so the memory bound holds
    end-to-end. Net-new vs the reference (its torch trainers
    materialize logits); the standard long-context recipe on TPU.
    """
    b, t = targets.shape
    hidden = model.apply(params, input_ids, return_hidden=True)
    head = lm_head_weight(params)  # [V, H]
    if mask is None:
        m_full = jnp.ones((b, t), jnp.float32)
    else:
        m_full = jnp.broadcast_to(
            mask.astype(jnp.float32), targets.shape
        )
    chunk_size = min(chunk_size, t)
    pad = (-t) % chunk_size
    if pad:
        # Pad to a whole number of chunks; padded rows carry mask 0 so
        # they never contribute (odd lengths must not collapse the
        # chunking into per-token scan steps).
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        m_full = jnp.pad(m_full, ((0, 0), (0, pad)))
        t += pad
    n_chunks = t // chunk_size
    h_c = hidden.reshape(b, n_chunks, chunk_size, -1).swapaxes(0, 1)
    t_c = targets.reshape(b, n_chunks, chunk_size).swapaxes(0, 1)
    m_c = m_full.reshape(b, n_chunks, chunk_size).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_nll(h, tg, m):
        # f32 accumulation on the MXU regardless of param dtype — the
        # full path's lm_head computes f32 logits, and the two losses
        # must stay numerically comparable.
        logits = jnp.matmul(
            h.astype(head.dtype),
            head.T,
            preferred_element_type=jnp.float32,
        )  # [B, C, V] f32
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tg[..., None], axis=-1)[..., 0]
        return jnp.sum((logz - gold) * m), jnp.sum(m)

    def body(carry, inp):
        nll, cnt = chunk_nll(*inp)
        return (carry[0] + nll, carry[1] + cnt), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (h_c, t_c, m_c),
    )
    return total / jnp.maximum(count, 1.0)


def causal_lm_loss(logits: jax.Array, targets: jax.Array,
                   mask: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross-entropy in f32. logits [B, T, V], targets [B, T]
    (already shifted by the data pipeline)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        # Broadcast BEFORE the sums: a shared [1, T] mask must weight
        # the denominator per batch row too, or the mean is scaled by B.
        mask = jnp.broadcast_to(mask.astype(nll.dtype), nll.shape)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)
