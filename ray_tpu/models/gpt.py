"""GPT-2/NeoX-style causal LM in flax (second dense family alongside
Llama): LayerNorm (with bias), learned position embeddings, GELU MLP,
standard MHA. Same GSPMD sharding conventions as llama.py
(parallel.mesh.spec_for_param + activation constraints).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import flash_attention
from ..parallel.mesh import with_logical_constraint


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None  # default 4x hidden
    max_seq_len: int = 1024
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def mlp_dim(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, l, v = self.hidden_size, self.num_layers, self.vocab_size
        per_layer = 4 * h * h + 2 * h * self.mlp_dim
        return l * per_layer + v * h + self.max_seq_len * h


CONFIGS = {
    "gpt2-tiny": GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                           num_heads=4, max_seq_len=256),
    "gpt2": GPTConfig(),
    "gpt2-medium": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-large": GPTConfig(hidden_size=1280, num_layers=36, num_heads=20),
}


class Block(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
        )
        h = ln("ln_1")(x)
        qkv = nn.DenseGeneral(
            (3, cfg.num_heads, cfg.head_dim), axis=-1, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="c_attn",
        )(h)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        o = flash_attention(q, k, v, causal=True).transpose(0, 2, 1, 3)
        attn_out = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="c_proj",
        )(o)
        x = x + attn_out
        h = ln("ln_2")(x)
        m = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_fc")(h)
        m = nn.gelu(m)
        m = with_logical_constraint(m, ("batch", "seq", "mlp"))
        m = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_proj_mlp")(m)
        x = x + m
        return with_logical_constraint(x, ("batch", "seq", "embed"))


class GPTForCausalLM(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1])[None], input_ids.shape
            )
        tok = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wte")
        pos = nn.Embed(cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wpe")
        x = tok(input_ids) + pos(positions)
        x = with_logical_constraint(x, ("batch", "seq", "embed"))
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(
                Block, prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable,
            )
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"h_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        return tok.attend(x.astype(cfg.param_dtype))
