from .llama import LlamaConfig, LlamaForCausalLM, CONFIGS  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM  # noqa: F401
from .gpt import CONFIGS as GPT_CONFIGS  # noqa: F401
from .mixtral import MixtralConfig, MixtralForCausalLM, moe_lm_loss  # noqa: F401
from .mixtral import CONFIGS as MIXTRAL_CONFIGS  # noqa: F401
