from .llama import LlamaConfig, LlamaForCausalLM, CONFIGS  # noqa: F401
