"""Mixtral-style sparse-MoE causal LM with expert parallelism.

Net-new vs the reference (SURVEY.md §2.3: EP/MoE "absent — integration
delegated"; here it's first-class). TPU-first design: experts live in
one stacked tensor with logical axis "expert" → the `expert` mesh axis,
and token dispatch/combine are dense einsums against a capacity-bounded
one-hot dispatch mask (GShard-style). Under GSPMD, batch-sharded
activations meeting expert-sharded weights compile into the all-to-all
over ICI automatically — no hand-written routing collectives, static
shapes throughout (XLA-friendly: no ragged tensors).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel import with_logical_constraint
from .llama import CONFIGS as LLAMA_CONFIGS
from .llama import Attention, LlamaConfig, RMSNorm, causal_lm_loss  # noqa: F401


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    num_experts_per_tok: int = 2  # top-k routing
    # Sparse models are small enough to save matmul outputs in remat:
    # full recompute would cap MFU at 0.75 of peak for no memory win.
    remat_policy: str = "dots"
    # Per-expert token capacity = capacity_factor * T * k / E
    # (capacity dispatch only).
    capacity_factor: float = 1.25
    router_aux_loss_coef: float = 0.02
    # "auto" (default): measured selection between the backends below,
    # cached per (backend, device kind, shape) — resolve_moe_dispatch().
    # "capacity": capacity-bounded static buffers with an [E, B, C, D]
    # expert axis — mesh-shards for expert parallelism (dispatch rides
    # an all-to-all over ICI) and lowers to plain batched matmuls, at
    # the cost of capacity_factor padding FLOPs (25% at 1.25).
    # "gmm": tile-aligned group-sorted dispatch through the pallas
    # grouped matmul (ops/gmm.py) — <=E*block_m rows of padding (~6%)
    # and zero drops; single-device per expert shard (the EP path
    # stays capacity). "ragged": exact-group lax.ragged_dot — the
    # semantic oracle; measured slower than both on current backends.
    moe_dispatch: str = "auto"

    def num_params(self) -> int:
        """Llama count minus its dense MLP, plus E stacked experts and
        the router (LlamaConfig.num_params would undercount the FFN by
        ~E x)."""
        h, i, l = self.hidden_size, self.intermediate_size, self.num_layers
        dense_mlp = 3 * h * i
        moe_mlp = self.num_experts * 3 * h * i + h * self.num_experts
        return super().num_params() + l * (moe_mlp - dense_mlp)

    def active_params_per_token(self) -> int:
        """FLOPs-relevant parameter count: only top-k experts run per
        token (what an MFU estimate should use)."""
        h, i, l = self.hidden_size, self.intermediate_size, self.num_layers
        dense_mlp = 3 * h * i
        active_mlp = self.num_experts_per_tok * 3 * h * i + h * self.num_experts
        return super().num_params() + l * (active_mlp - dense_mlp)


# moe_dispatch="auto" resolutions, keyed by _shape_key: warmed by
# resolve_moe_dispatch() (outside jit), read at trace time.
_RESOLVED: dict = {}


def _shape_key(cfg: "MixtralConfig") -> str:
    return (
        f"E{cfg.num_experts}-K{cfg.num_experts_per_tok}-"
        f"D{cfg.hidden_size}-F{cfg.intermediate_size}"
    )


def resolve_moe_dispatch(
    cfg: "MixtralConfig",
    tokens: int = 4096,
    mesh=None,
    steps: int = 10,
) -> str:
    """Measure-and-pick the MoE dispatch backend for this device.

    The judge of record is a timed probe of the dispatch+FFN core
    (fwd+bwd) at this config's shapes on the live backend — not a
    config flag: ragged_dot vs capacity vs the pallas gmm rank
    differently across TPU generations and compiler versions.
    Resolutions persist to ~/.cache/ray_tpu/moe_dispatch.json keyed by
    (backend, device kind, shape), so the probe runs once per machine.
    Under an expert-sharded mesh the capacity path is returned without
    probing (its [E, B, C, D] layout is what rides the EP all-to-all;
    the gmm layout is per-shard).
    """
    import json
    import os
    import time

    if cfg.moe_dispatch != "auto":
        return cfg.moe_dispatch
    env = os.environ.get("RAY_TPU_MOE_DISPATCH")
    if env:
        _RESOLVED[_shape_key(cfg)] = env
        return env
    if mesh is not None and mesh.shape.get("expert", 1) > 1:
        _RESOLVED[_shape_key(cfg)] = "capacity"
        return "capacity"
    skey = _shape_key(cfg)
    if skey in _RESOLVED:
        return _RESOLVED[skey]
    dev = jax.devices()[0]
    cache_key = (
        f"{jax.default_backend()}-{dev.device_kind}-{skey}-N{tokens}"
    )
    cache_path = os.path.join(
        os.path.expanduser("~"), ".cache", "ray_tpu", "moe_dispatch.json"
    )
    try:
        with open(cache_path) as f:
            disk = json.load(f)
    except (OSError, ValueError):
        disk = {}
    if cache_key in disk:
        _RESOLVED[skey] = disk[cache_key]
        return disk[cache_key]

    import numpy as np
    from dataclasses import replace as _replace

    probe_cfg = _replace(
        cfg,
        vocab_size=256,
        num_layers=1,
        num_heads=4,
        num_kv_heads=4,
        remat=False,
    )
    rng = np.random.RandomState(0)
    x = jnp.asarray(
        rng.randn(1, tokens, cfg.hidden_size), probe_cfg.dtype
    )

    def _time_backend(name: str) -> float:
        layer = MoELayer(_replace(probe_cfg, moe_dispatch=name))
        params = jax.jit(layer.init)(jax.random.PRNGKey(0), x[:, :256])

        @jax.jit
        def step(p, x):
            def loss(p):
                return (layer.apply(p, x) ** 2).sum()

            return jax.grad(loss)(p)

        g = step(params, x)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), g)
        t0 = time.perf_counter()
        for _ in range(steps):
            g = step(params, x)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), g)
        return time.perf_counter() - t0

    candidates = ["capacity", "gmm"]
    times = {}
    for name in candidates:
        try:
            times[name] = _time_backend(name)
        except Exception:  # noqa: BLE001 - backend unsupported here
            continue
    if not times:
        # Transient probe failure (e.g. chip busy): fall back WITHOUT
        # persisting, so the next process probes again.
        _RESOLVED[skey] = "capacity"
        return "capacity"
    winner = min(times, key=times.get)
    _RESOLVED[skey] = winner
    disk[cache_key] = winner
    try:
        os.makedirs(os.path.dirname(cache_path), exist_ok=True)
        with open(cache_path, "w") as f:
            json.dump(disk, f)
    except OSError:
        pass
    return winner


CONFIGS = {
    "mixtral-tiny": MixtralConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, num_experts=4, num_experts_per_tok=2,
        max_seq_len=256,
    ),
    "mixtral-small": MixtralConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=3584,
        num_layers=8, num_heads=16, num_kv_heads=8, num_experts=8,
        num_experts_per_tok=2, max_seq_len=4096,
    ),
}


class MoELayer(nn.Module):
    """Top-k router with two dispatch backends (cfg.moe_dispatch).

    "capacity" (default): gather/scatter into capacity-bounded static
    buffers with an explicit [E, B, C, D] expert axis — under GSPMD the
    expert dim mesh-shards and dispatch rides an all-to-all over ICI,
    and the expert FFN lowers to batched matmuls that fill the MXU.
    Still far cheaper than the GShard dense one-hot einsum, whose
    [B,T,E,C] mask costs O(B*T^2*D) MXU FLOPs at long T.

    "ragged" (opt-in): (token, k) pairs argsorted by expert feed
    `lax.ragged_dot` with exact group sizes — zero capacity padding and
    zero drops. Measured slower than capacity on current TPU backends
    (ragged_dot lowers to a masked loop), so it serves as the semantic
    oracle and the path for backends where it wins.

    Gradients flow through the gathers/ragged dots and gate weights."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dispatch = cfg.moe_dispatch
        if dispatch == "auto":
            # Trace-time: use the process cache warmed by
            # resolve_moe_dispatch() (bench/trainer call it before jit);
            # capacity is the safe fallback everywhere.
            dispatch = _RESOLVED.get(_shape_key(cfg), "capacity")
        if dispatch not in ("ragged", "capacity", "gmm"):
            raise ValueError(
                f"moe_dispatch must be 'auto', 'ragged', 'capacity' or "
                f"'gmm', got {cfg.moe_dispatch!r}"
            )
        B, T, D = x.shape
        E, K = cfg.num_experts, cfg.num_experts_per_tok
        C = max(1, int(cfg.capacity_factor * T * K / E))

        router = nn.Dense(
            E, use_bias=False, dtype=jnp.float32,
            param_dtype=cfg.param_dtype, name="router",
        )
        logits = router(x.astype(jnp.float32))  # [B, T, E] — fp32 routing
        probs = jax.nn.softmax(logits, axis=-1)

        # Top-k gates, renormalized over the chosen experts.
        gate_vals, gate_idx = jax.lax.top_k(probs, K)  # [B, T, K]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9
        )

        # Aux load-balance loss (Switch Transformer eq. 4): mean gate
        # fraction x mean dispatch fraction per expert.
        onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)  # [B,T,K,E]
        expert_mask = onehot.sum(2)  # [B, T, E] (0/1 per expert)
        frac_tokens = expert_mask.mean(axis=(0, 1))
        frac_probs = probs.mean(axis=(0, 1))
        aux = E * jnp.sum(frac_tokens * frac_probs)
        self.sow("intermediates", "router_aux_loss", aux)

        xd = x.astype(cfg.dtype)

        def pvar(name, shape):
            return self.param(
                name, nn.initializers.lecun_normal(), shape, cfg.param_dtype
            )

        w_gate = pvar("w_gate", (E, D, cfg.intermediate_size))
        w_up = pvar("w_up", (E, D, cfg.intermediate_size))
        w_down = pvar("w_down", (E, cfg.intermediate_size, D))

        if dispatch == "gmm":
            # Tile-aligned group-sorted dispatch through the pallas
            # grouped matmul: every block_m row-tile belongs to one
            # expert, so the FFN runs as dense MXU tiles with ~6%
            # padding instead of capacity's 25% — and zero drops.
            from ..ops.gmm import aligned_group_layout, gmm

            N = B * T * K
            x2 = xd.reshape(B * T, D)
            e_flat = gate_idx.reshape(N)
            order, dst, tile_group, m_pad = aligned_group_layout(
                e_flat, E, block_m=128
            )
            tok_of_pair = jnp.arange(N, dtype=jnp.int32) // K
            tok_sorted = tok_of_pair[order]
            # Row GATHER into the aligned layout (row scatters serialize
            # on TPU; gathers vectorize — same trick as the capacity
            # path). inv maps aligned slot -> sorted-pair index, with
            # padding slots reading a zero row.
            inv = (
                jnp.full((m_pad,), N, jnp.int32)
                .at[dst]
                .set(jnp.arange(N, dtype=jnp.int32), unique_indices=True)
            )
            src_tok = jnp.concatenate(
                [tok_sorted, jnp.full((1,), B * T, jnp.int32)]
            )[inv]
            x_pad = jnp.concatenate(
                [x2, jnp.zeros((1, D), x2.dtype)], axis=0
            )
            lhs = x_pad[src_tok]  # [m_pad, D]
            h = gmm(lhs, w_gate.astype(cfg.dtype), tile_group)
            u = gmm(lhs, w_up.astype(cfg.dtype), tile_group)
            act = nn.silu(h) * u
            eo = gmm(act, w_down.astype(cfg.dtype), tile_group)
            gates_sorted = gate_vals.astype(cfg.dtype).reshape(N)[order]
            pair_out = eo[dst] * gates_sorted[:, None]
            out2 = (
                jnp.zeros((B * T, D), cfg.dtype)
                .at[tok_sorted]
                .add(pair_out)
            )
            out = out2.reshape(B, T, D)
            return with_logical_constraint(out, ("batch", "seq", "embed"))

        if dispatch == "ragged":
            # Exact-group dispatch: argsort the (token, k) pairs by
            # expert and run each group through its expert with
            # lax.ragged_dot — FLOPs are exactly the active tokens'.
            N = B * T * K
            x2 = xd.reshape(B * T, D)
            e_flat = gate_idx.reshape(N)
            order = jnp.argsort(e_flat)
            tok_of_pair = jnp.arange(N, dtype=jnp.int32) // K
            tok_sorted = tok_of_pair[order]
            xs = x2[tok_sorted]  # [N, D] grouped by expert
            group_sizes = jnp.bincount(e_flat, length=E).astype(jnp.int32)
            h = jax.lax.ragged_dot(xs, w_gate.astype(cfg.dtype), group_sizes)
            u = jax.lax.ragged_dot(xs, w_up.astype(cfg.dtype), group_sizes)
            act = nn.silu(h) * u
            eo = jax.lax.ragged_dot(
                act, w_down.astype(cfg.dtype), group_sizes
            )
            gates_sorted = gate_vals.astype(cfg.dtype).reshape(N)[order]
            out2 = (
                jnp.zeros((B * T, D), cfg.dtype)
                .at[tok_sorted]
                .add(eo * gates_sorted[:, None])
            )
            out = out2.reshape(B, T, D)
            return with_logical_constraint(out, ("batch", "seq", "embed"))

        NK = T * K

        # Arrival-order position of each token within its expert's
        # buffer: cumsum over T of the small [B,T,E] mask (E is tiny) —
        # no sort, no [B,T,E,C] one-hot.
        position = (
            jnp.cumsum(expert_mask, axis=1) - expert_mask
        )  # [B, T, E] tokens before me per expert

        def route_one(xrow, idx_row, pos_row):
            """One batch row: the first C arrivals per expert own its
            buffer slots; drops past capacity land in per-pair dump
            slots (kept unique so XLA needs no collision handling).

            TPU shape of the dispatch: scatter only the int32 slot->token
            inverse map (cheap scalar scatter), then fill the buffer with
            a row GATHER — row scatters serialize on TPU, row gathers
            vectorize. Pair order stays token-major, so combine is a
            reshape-sum, not a scatter."""
            e_flat = idx_row.reshape(NK)  # expert of each (token, k) pair
            pos = jnp.take_along_axis(
                pos_row, idx_row, axis=1
            ).reshape(NK).astype(jnp.int32)  # position within expert
            keep = pos < C
            slot = jnp.where(
                keep, e_flat * C + pos, E * C + jnp.arange(NK, dtype=jnp.int32)
            )
            tok_ids = jnp.arange(NK, dtype=jnp.int32) // K
            inv = (
                jnp.full((E * C + NK,), T, jnp.int32)
                .at[slot]
                .set(tok_ids, unique_indices=True)
            )
            x_pad = jnp.concatenate(
                [xrow, jnp.zeros((1, D), xrow.dtype)], axis=0
            )
            buf = x_pad[inv[: E * C]]  # [E*C, D] row gather
            return buf, jnp.minimum(slot, E * C)

        buf, slot = jax.vmap(route_one)(
            xd, gate_idx, position.astype(jnp.float32)
        )
        # [B, E*C, D] -> [E, B, C, D]; under GSPMD the expert axis is
        # mesh-sharded (all-to-all over ICI).
        expert_in = buf.reshape(B, E, C, D).transpose(1, 0, 2, 3)
        expert_in = with_logical_constraint(
            expert_in, ("expert", "batch", None, "embed")
        )

        # Stacked expert FFN (SwiGLU like the dense path). E-major
        # weights (created above); parallel.mesh.spec_for_param shards
        # them P("expert", "fsdp"/"tensor", ...) by name.
        h = jnp.einsum("ebcd,edf->ebcf", expert_in, w_gate.astype(cfg.dtype))
        u = jnp.einsum("ebcd,edf->ebcf", expert_in, w_up.astype(cfg.dtype))
        act = nn.silu(h) * u
        expert_out = jnp.einsum("ebcf,efd->ebcd", act, w_down.astype(cfg.dtype))

        # Combine back to token order, weighted by gates: gather each
        # pair's expert output (dropped pairs read the zero dump row),
        # scale, and reduce the K pairs of every token — pair order is
        # token-major, so the reduction is a reshape-sum, no scatter.
        expert_out = expert_out.transpose(1, 0, 2, 3).reshape(B, E * C, D)

        def combine_one(eo_row, slot_row, gate_row):
            eo_row = jnp.concatenate(
                [eo_row, jnp.zeros((1, D), eo_row.dtype)], axis=0
            )
            pair_out = eo_row[slot_row] * gate_row[:, None]
            return pair_out.reshape(T, K, D).sum(1)

        out = jax.vmap(combine_one)(
            expert_out, slot, gate_vals.astype(cfg.dtype).reshape(B, NK)
        )
        return with_logical_constraint(out, ("batch", "seq", "embed"))


class MoEDecoderLayer(nn.Module):
    cfg: MixtralConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h = x + Attention(cfg, mesh=self.mesh, name="attn")(
            RMSNorm(cfg.rms_eps, cfg.param_dtype, name="input_norm")(x),
            positions,
        )
        out = h + MoELayer(cfg, name="moe")(
            RMSNorm(cfg.rms_eps, cfg.param_dtype, name="post_attn_norm")(h)
        )
        return with_logical_constraint(out, ("batch", "seq", "embed"))


class MixtralForCausalLM(nn.Module):
    cfg: MixtralConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1])[None], input_ids.shape
            )
        emb = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="embed_tokens",
        )
        x = emb(input_ids)
        x = with_logical_constraint(x, ("batch", "seq", "embed"))
        from .llama import remat_policy

        layer_cls = MoEDecoderLayer
        if cfg.remat:
            layer_cls = nn.remat(
                MoEDecoderLayer, prevent_cse=False,
                policy=remat_policy(cfg),
            )
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, mesh=self.mesh, name=f"layers_{i}")(x, positions)
        x = RMSNorm(cfg.rms_eps, cfg.param_dtype, name="final_norm")(x)
        logits = emb.attend(x.astype(cfg.param_dtype))
        return logits


def moe_lm_loss(model: MixtralForCausalLM, params, input_ids, targets,
                mask=None):
    """Causal LM loss + router aux loss (call instead of apply+loss so
    the sown aux terms are collected)."""
    logits, state = model.apply(
        params, input_ids, mutable=["intermediates"]
    )
    loss = causal_lm_loss(logits, targets, mask)
    aux_terms = jax.tree_util.tree_leaves(
        state.get("intermediates", {})
    )
    if aux_terms:
        loss = loss + model.cfg.router_aux_loss_coef * (
            sum(aux_terms) / len(aux_terms)
        )
    return loss
