"""Tuner: the public entry point.

Reference: python/ray/tune/tuner.py (Tuner.fit → TuneController) and
tune/result_grid.py (ResultGrid). `Tuner(trainer)` wraps a Train
trainer the same way the reference's BaseTrainer.as_trainable does
(train/base_trainer.py:819): each trial runs a full `fit()` with the
trial config merged into train_loop_config.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..train.checkpoint import Checkpoint
from ..train.config import Result, RunConfig
from .schedulers import TrialScheduler
from .search import Searcher
from .tune_controller import ERROR, TERMINATED, Trial, TuneController


@dataclass
class TuneConfig:
    """Reference: tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, trials, metric, mode):
        self._trials = list(trials)
        self._metric = metric
        self._mode = mode
        self.results = [
            Result(
                metrics=t.last_result or None,
                checkpoint=Checkpoint(t.best_checkpoint or t.latest_checkpoint)
                if (t.best_checkpoint or t.latest_checkpoint) else None,
                error=RuntimeError(t.error) if t.error else None,
                path=t.local_dir,
                metrics_history=t.metrics_history,
                config=dict(t.config),
            )
            for t in self._trials
        ]

    def __len__(self):
        return len(self.results)

    def __getitem__(self, i):
        return self.results[i]

    @property
    def errors(self):
        return [r.error for r in self.results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [
            r for r in self.results
            if r.metrics and metric in r.metrics
        ]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics for r in self.results if r.metrics])


def _default_experiment_dir(name: Optional[str],
                            storage_path: Optional[str]) -> str:
    base = storage_path or os.path.join(
        os.environ.get("RAY_TPU_RESULTS_DIR",
                       os.path.expanduser("~/ray_tpu_results"))
    )
    return os.path.join(base, name or "tune_experiment")


class Tuner:
    def __init__(
        self,
        trainable=None,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        _restore_path: Optional[str] = None,
    ):
        from ..train.trainer import JaxTrainer

        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._param_space = dict(param_space or {})
        if isinstance(trainable, JaxTrainer):
            # trial config is merged into the trainer's train_loop_config
            self._trainable = _trainer_to_trainable(trainable)
        else:
            self._trainable = trainable
        self._restore_path = _restore_path

    @classmethod
    def restore(
        cls,
        path: str,
        trainable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ) -> "Tuner":
        """Resume an interrupted experiment from its state file
        (reference: Tuner.restore). param_space/tune_config/run_config
        must match the original run; pass them again so restored
        PENDING trials keep their search space, metric, and stop
        criteria."""
        return cls(
            trainable,
            param_space=param_space,
            tune_config=tune_config,
            run_config=run_config,
            _restore_path=path,
        )

    def fit(self) -> ResultGrid:
        exp_dir = self._restore_path or _default_experiment_dir(
            self._run_config.name, self._run_config.storage_path
        )
        stop = getattr(self._run_config, "stop", None)
        controller = TuneController(
            self._trainable,
            param_space=self._param_space,
            metric=self._tune_config.metric,
            mode=self._tune_config.mode,
            search_alg=self._tune_config.search_alg,
            scheduler=self._tune_config.scheduler,
            num_samples=self._tune_config.num_samples,
            max_concurrent_trials=self._tune_config.max_concurrent_trials,
            stop=stop,
            max_failures=self._run_config.failure_config.max_failures,
            infra_retries=self._run_config.failure_config.infra_retries,
            experiment_dir=exp_dir,
        )
        if self._restore_path and os.path.exists(
            os.path.join(exp_dir, "experiment_state.json")
        ):
            controller.restore_experiment_state()
        trials = controller.run()
        return ResultGrid(trials, self._tune_config.metric,
                          self._tune_config.mode)


def _trainer_to_trainable(trainer):
    """Each trial re-runs the trainer with the trial config merged into
    train_loop_config (reference: base_trainer.py as_trainable :819)."""

    def trainable(config: Dict[str, Any]):
        import copy

        from ..train import session as train_session

        t = copy.copy(trainer)
        loop_config = dict(t._config or {})
        loop_config.update(config)
        t._config = loop_config
        outer = train_session.get_session()
        result = t.fit()
        # fit() consumed the inner session; re-report the final metrics to
        # the trial's session so Tune sees them.
        if outer is not None:
            train_session._session = outer
        if result.error:
            raise result.error
        if result.metrics:
            train_session.report(result.metrics, checkpoint=result.checkpoint)

    return trainable


def run(trainable, *, param_space=None, config=None, metric=None, mode="max",
        num_samples=1, search_alg=None, scheduler=None, stop=None,
        name=None, storage_path=None, max_concurrent_trials=None) -> ResultGrid:
    """Functional entry point (reference: tune.run)."""
    run_config = RunConfig(name=name, storage_path=storage_path)
    run_config.stop = stop  # type: ignore[attr-defined]
    return Tuner(
        trainable,
        param_space=param_space or config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            search_alg=search_alg, scheduler=scheduler,
            max_concurrent_trials=max_concurrent_trials,
        ),
        run_config=run_config,
    ).fit()
