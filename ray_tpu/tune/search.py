"""Search spaces + search algorithms.

Reference: python/ray/tune/search/ — sample domains
(tune/search/sample.py: Categorical/Float/Integer, grid_search),
BasicVariantGenerator (tune/search/basic_variant.py) doing grid
cartesian expansion x num_samples random resolution, the Searcher
interface (tune/search/searcher.py) and ConcurrencyLimiter
(tune/search/concurrency_limiter.py).
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional

import numpy as np


# ------------------------------------------------------------------ domains

class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Float(Domain):
    def __init__(self, lower, upper, log=False, q=None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.lower), np.log(self.upper))))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lower, upper):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        try:
            return self.fn({})
        except TypeError:
            return self.fn()


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda: random.gauss(mean, sd))


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, GridSearch) or (
        isinstance(v, dict) and set(v.keys()) == {"grid_search"}
    )


def _grid_values(v):
    return v.values if isinstance(v, GridSearch) else v["grid_search"]


def resolve_config(space: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    """Sample every Domain leaf; grid leaves must be pre-resolved."""
    out = {}
    for k, v in space.items():
        if isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict) and not _is_grid(v):
            out[k] = resolve_config(v, rng)
        elif _is_grid(v):
            raise ValueError("unexpanded grid_search leaf")
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------- searchers

class Searcher:
    """Reference: tune/search/searcher.py:Searcher."""

    #: suggest() sentinel: no config available *right now*, retry later
    #: (vs. None = search space exhausted, stop creating trials).
    BACKOFF = "__backoff__"

    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric, mode, space) -> None:
        self.metric = self.metric or metric
        self.mode = self.mode or mode
        self._space = space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result=None, error=False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cartesian product x num_samples random draws (reference:
    tune/search/basic_variant.py)."""

    def __init__(self, num_samples: int = 1, seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict]] = None):
        super().__init__()
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        self._points = list(points_to_evaluate or [])
        self._queue: Optional[List[Dict[str, Any]]] = None

    def set_search_properties(self, metric, mode, space) -> None:
        super().set_search_properties(metric, mode, space)
        grid_keys = [k for k, v in space.items() if _is_grid(v)]
        grids = [_grid_values(space[k]) for k in grid_keys]
        variants: List[Dict[str, Any]] = []
        combos = itertools.product(*grids) if grid_keys else [()]
        for combo in combos:
            base = dict(space)
            for k, val in zip(grid_keys, combo):
                base[k] = val
            variants.append(base)
        self._queue = []
        for point in self._points:
            # Unpinned grid keys resolve to their first value so the
            # config stays complete.
            merged = {
                k: (_grid_values(v)[0] if _is_grid(v) else v)
                for k, v in space.items()
            }
            merged.update(point)
            self._queue.append(resolve_config(merged, self._rng))
        for _ in range(self.num_samples):
            for v in variants:
                self._queue.append(resolve_config(v, self._rng))

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._queue:
            return None
        return self._queue.pop(0)

    @property
    def total_trials(self) -> Optional[int]:
        return len(self._queue) if self._queue is not None else None


class ConcurrencyLimiter(Searcher):
    """Reference: tune/search/concurrency_limiter.py."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, space) -> None:
        super().set_search_properties(metric, mode, space)
        self.searcher.set_search_properties(metric, mode, space)

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return Searcher.BACKOFF  # controller retries next step
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg is not Searcher.BACKOFF:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class OptunaSearch(Searcher):
    """Optuna TPE adapter (reference: tune/search/optuna/optuna_search.py).
    Gated: raises at construction if optuna is unavailable in this image."""

    def __init__(self, metric=None, mode=None, seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import optuna  # noqa: F401
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "OptunaSearch requires the `optuna` package"
            ) from e
        import optuna

        self._optuna = optuna
        sampler = optuna.samplers.TPESampler(seed=seed)
        self._study = optuna.create_study(
            direction=None, sampler=sampler,
            directions=None,
        )
        self._trials: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(metric, mode, space)

    def suggest(self, trial_id: str):
        ot = self._study.ask()
        self._trials[trial_id] = ot
        cfg = {}
        for k, v in self._space.items():
            if isinstance(v, Categorical):
                cfg[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, Float):
                cfg[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, Integer):
                cfg[k] = ot.suggest_int(k, v.lower, v.upper - 1)
            else:
                cfg[k] = v
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or result is None or self.metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
            return
        value = result[self.metric]
        if self.mode == "max":
            value = -value  # study minimizes
        self._study.tell(ot, value)
