"""Trial schedulers: early stopping + population based training.

Reference: python/ray/tune/schedulers/ — FIFOScheduler (trial_scheduler.py),
ASHA (async_hyperband.py), MedianStoppingRule (median_stopping_rule.py),
PopulationBasedTraining (pbt.py). Decisions are returned to the
TuneController on every reported result.
"""
from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

import numpy as np


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"

    def set_properties(self, metric: str, mode: str) -> None:
        self.metric = metric
        self.mode = mode

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, controller, trial, result) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung is stopped
    unless it is in the top 1/reduction_factor of results recorded there."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 4,
                 max_t: int = 100):
        self.time_attr = time_attr
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = {}
        self._trial_rungs: Dict[str, set] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(int(t))
            t *= reduction_factor
        self._milestones = milestones

    def _score(self, result) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is None:
            return self.CONTINUE
        reached = self._trial_rungs.setdefault(trial.trial_id, set())
        for m in self._milestones:
            # >= not ==: a trial reporting every k iterations must still be
            # evaluated at rungs it jumps over.
            if t >= m and m not in reached:
                reached.add(m)
                rung = self._rungs.setdefault(m, [])
                rung.append(score)
                cutoff = np.percentile(rung, (1 - 1 / self.rf) * 100)
                if score < cutoff:
                    return self.STOP
        if t >= self.max_t:
            return self.STOP
        return self.CONTINUE


# Reference alias (ray.tune.schedulers.ASHAScheduler)
ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average is worse than the median of
    completed averages at the same step (reference:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = {}

    def _signed(self, result) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        score = self._signed(result)
        if score is None:
            return self.CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(score)
        if t <= self.grace:
            return self.CONTINUE
        others = [
            float(np.mean(h))
            for tid, h in self._histories.items()
            if tid != trial.trial_id and len(h) > 0
        ]
        if len(others) < self.min_samples:
            return self.CONTINUE
        if float(np.mean(hist)) < float(np.median(others)):
            return self.STOP
        return self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): every
    ``perturbation_interval`` steps, bottom-quantile trials clone the
    checkpoint of a top-quantile trial and continue with mutated
    hyperparameters (exploit + explore)."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}

    def _signed(self, result):
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search import Domain

        new = dict(config)
        for key, spec in self.mutations.items():
            if isinstance(spec, list):
                new[key] = self._rng.choice(spec)
            elif isinstance(spec, Domain):
                new[key] = spec.sample(self._rng)
            elif callable(spec):
                new[key] = spec()
            elif key in new and isinstance(new[key], (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                new[key] = type(new[key])(new[key] * factor)
        return new

    def on_trial_result(self, controller, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        score = self._signed(result)
        if score is not None:
            self._scores[trial.trial_id] = score
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        if len(self._scores) < 2:
            return self.CONTINUE
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        k = max(1, int(len(ranked) * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and top:
            src_id = self._rng.choice(top)
            src = controller.get_trial(src_id)
            if src is not None and src.latest_checkpoint is not None:
                new_config = self._mutate(src.config)
                controller.exploit_trial(trial, src, new_config)
        return self.CONTINUE
