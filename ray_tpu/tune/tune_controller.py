"""TuneController: the experiment event loop.

Reference: python/ray/tune/execution/tune_controller.py — `step` (:666)
schedules trial actors (:964), drains results, feeds searcher +
scheduler, checkpoints experiment state (:1691) and restores (:1791).
One actor per trial; PBT exploits restart the actor from the source
trial's checkpoint with a mutated config.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu
from ..exceptions import RayActorError
from .schedulers import FIFOScheduler, TrialScheduler
from .search import BasicVariantGenerator, Searcher
from .trainable import _TrialActor

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    """Reference: tune/experiment/trial.py."""

    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_result: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    latest_checkpoint: Optional[str] = None
    best_checkpoint: Optional[str] = None
    best_score: Optional[float] = None
    error: Optional[str] = None
    num_failures: int = 0
    num_infra_failures: int = 0
    local_dir: str = ""

    def public_state(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "latest_checkpoint": self.latest_checkpoint,
            "best_checkpoint": self.best_checkpoint,
            "error": self.error,
        }


class TuneController:
    def __init__(
        self,
        trainable,
        *,
        param_space: Dict[str, Any],
        metric: Optional[str],
        mode: str = "max",
        search_alg: Optional[Searcher] = None,
        scheduler: Optional[TrialScheduler] = None,
        num_samples: int = 1,
        max_concurrent_trials: Optional[int] = None,
        stop: Optional[Dict[str, Any]] = None,
        max_failures: int = 0,
        infra_retries: int = 3,
        experiment_dir: str = "",
        poll_interval_s: float = 0.05,
    ):
        self.trainable = trainable
        self.metric = metric
        self.mode = mode
        self.stop_criteria = stop or {}
        self.max_failures = max_failures
        # Infra failures (the actor died: worker preempted/OOM-killed/
        # registration starved under load) retry on their OWN budget,
        # separate from user-code failures — a wedged host must not
        # convert healthy trials into ERROR results (reference: trial
        # actor restarts in tune/execution/ray_trial_executor; the
        # round-4 flakiness was exactly spurious actor loss under
        # contention surfacing as trial errors).
        self.infra_retries = infra_retries
        self.experiment_dir = experiment_dir
        os.makedirs(experiment_dir, exist_ok=True)
        # searcher; a user-supplied search_alg keeps its own settings.
        self.searcher = search_alg or BasicVariantGenerator(num_samples=num_samples)
        # Reference semantics (tune/tune.py): with an explicit
        # model-based searcher, num_samples caps total suggestions
        # (those searchers never self-exhaust). Queue-based searchers
        # (total_trials anywhere in the wrapper chain) encode their own
        # budget and must not be capped by the num_samples default.
        def _self_exhausting(s):
            while s is not None:
                if hasattr(s, "total_trials"):
                    return True
                s = getattr(s, "searcher", None)
            return False

        self._max_trials = (
            None if search_alg is None or _self_exhausting(search_alg)
            else num_samples
        )
        self.searcher.set_search_properties(metric, mode, param_space)
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_properties(metric, mode)
        if max_concurrent_trials is None:
            try:
                max_concurrent_trials = max(
                    1, int(ray_tpu.cluster_resources().get("CPU", 2)) - 1
                )
            except Exception:
                max_concurrent_trials = 2
        self.max_concurrent = max_concurrent_trials
        self.poll_interval_s = poll_interval_s

        self.trials: List[Trial] = []
        self._actors: Dict[str, Any] = {}  # trial_id -> actor handle
        self._searcher_done = False

    # ------------------------------------------------------------ helpers

    def get_trial(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    def _new_trial(self):
        if self._max_trials is not None and len(self.trials) >= self._max_trials:
            return None
        trial_id = f"trial_{len(self.trials):04d}_{uuid.uuid4().hex[:6]}"
        cfg = self.searcher.suggest(trial_id)
        if cfg is None or cfg is Searcher.BACKOFF:
            return cfg  # None = exhausted; BACKOFF = retry next step
        t = Trial(
            trial_id=trial_id,
            config=cfg,
            local_dir=os.path.join(self.experiment_dir, trial_id),
        )
        self.trials.append(t)
        return t

    def _start_trial(self, trial: Trial,
                     checkpoint_path: Optional[str] = None) -> None:
        actor = _TrialActor.remote(trial.trial_id, trial.local_dir)
        actor.run.remote(self.trainable, trial.config, checkpoint_path,
                         self.stop_criteria)
        self._actors[trial.trial_id] = actor
        trial.status = RUNNING

    def _stop_trial_actor(self, trial: Trial) -> None:
        actor = self._actors.pop(trial.trial_id, None)
        if actor is not None:
            try:
                actor.stop.remote()
                ray_tpu.kill(actor)
            except Exception:
                pass

    # ------------------------------------------------------- PBT exploit

    def exploit_trial(self, trial: Trial, source: Trial,
                      new_config: Dict[str, Any]) -> None:
        """Restart `trial` from `source`'s checkpoint with a mutated
        config (reference: pbt.py _exploit)."""
        self._stop_trial_actor(trial)
        trial.config = new_config
        trial.latest_checkpoint = source.latest_checkpoint
        self._start_trial(trial, checkpoint_path=source.latest_checkpoint)

    # ---------------------------------------------------------- the loop

    def _handle_result(self, trial: Trial, metrics: Dict[str, Any],
                       ckpt_path: Optional[str]) -> None:
        metrics.setdefault("training_iteration",
                           len(trial.metrics_history) + 1)
        metrics.setdefault("trial_id", trial.trial_id)
        trial.last_result = metrics
        trial.metrics_history.append(metrics)
        if ckpt_path:
            trial.latest_checkpoint = ckpt_path
            if self.metric and self.metric in metrics:
                score = float(metrics[self.metric])
                signed = score if self.mode == "max" else -score
                if trial.best_score is None or signed > trial.best_score:
                    trial.best_score = signed
                    trial.best_checkpoint = ckpt_path
        self.searcher.on_trial_result(trial.trial_id, metrics)
        decision = self.scheduler.on_trial_result(self, trial, metrics)
        stop_now = decision == TrialScheduler.STOP
        for key, bound in self.stop_criteria.items():
            if key in metrics and metrics[key] >= bound:
                stop_now = True
        if stop_now and trial.status == RUNNING:
            self._stop_trial_actor(trial)
            trial.status = TERMINATED
            self.searcher.on_trial_complete(trial.trial_id, trial.last_result)

    def _handle_done(self, trial: Trial) -> None:
        self._stop_trial_actor(trial)
        trial.status = TERMINATED
        self.searcher.on_trial_complete(trial.trial_id, trial.last_result)
        self.scheduler.on_trial_complete(self, trial, trial.last_result)

    def _handle_error(self, trial: Trial, err: BaseException) -> None:
        trial.num_failures += 1
        if trial.num_failures <= self.max_failures:
            self._stop_trial_actor(trial)
            self._start_trial(trial, checkpoint_path=trial.latest_checkpoint)
            return
        self._stop_trial_actor(trial)
        trial.status = ERROR
        trial.error = repr(err)
        self.searcher.on_trial_complete(trial.trial_id, error=True)

    def _handle_infra_failure(self, trial: Trial, err: BaseException) -> None:
        """The trial's actor died without the trainable raising (worker
        preemption, OOM kill, a registration timeout under host load).
        Restart from the latest checkpoint on the infra budget; only a
        persistently failing environment errors the trial."""
        trial.num_infra_failures += 1
        if trial.num_infra_failures <= self.infra_retries:
            import sys

            sys.stderr.write(
                f"tune: trial {trial.trial_id} lost its actor "
                f"({err!r}); restarting "
                f"({trial.num_infra_failures}/{self.infra_retries})\n"
            )
            self._stop_trial_actor(trial)
            self._start_trial(trial, checkpoint_path=trial.latest_checkpoint)
            return
        self._stop_trial_actor(trial)
        trial.status = ERROR
        trial.error = repr(err)
        self.searcher.on_trial_complete(trial.trial_id, error=True)

    def step(self) -> bool:
        """One controller iteration; returns False when all trials are done
        (reference: TuneController.step :666)."""
        # 1. fill free slots
        running = [t for t in self.trials if t.status == RUNNING]
        while len(running) < self.max_concurrent and not self._searcher_done:
            pending = [t for t in self.trials if t.status == PENDING]
            trial = pending[0] if pending else self._new_trial()
            if trial is None:
                self._searcher_done = True
                break
            if trial is Searcher.BACKOFF:
                break  # limiter at capacity; retry next step
            if trial.status == PENDING:
                self._start_trial(trial, checkpoint_path=trial.latest_checkpoint)
                running.append(trial)

        if not running:
            return False

        # 2. poll all running actors for their next event; each poll is
        # pinned to the actor incarnation it was sent to so a mid-step
        # restart (PBT exploit, infra retry) never consumes — or
        # errors on — a stale ref from the killed predecessor.
        polls = {
            t.trial_id: (
                self._actors[t.trial_id],
                self._actors[t.trial_id].next_result.remote(
                    timeout=self.poll_interval_s
                ),
            )
            for t in running
            if t.trial_id in self._actors
        }
        for trial_id, (actor, ref) in polls.items():
            trial = self.get_trial(trial_id)
            if trial is None or trial.status != RUNNING:
                continue  # stopped mid-step (scheduler/PBT)
            if self._actors.get(trial_id) is not actor:
                continue  # restarted mid-step: stale poll
            try:
                kind, payload = ray_tpu.get(ref)
            except RayActorError as e:
                self._handle_infra_failure(trial, e)
                continue
            if kind == "result":
                self._handle_result(trial, payload[0], payload[1])
            elif kind == "done":
                self._handle_done(trial)
            elif kind == "error":
                self._handle_error(trial, payload)
        self.save_experiment_state()
        return any(t.status in (PENDING, RUNNING) for t in self.trials) or (
            not self._searcher_done
        )

    def run(self) -> List[Trial]:
        while self.step():
            pass
        self.save_experiment_state()
        return self.trials

    # -------------------------------------------------- experiment state

    def _state_path(self) -> str:
        return os.path.join(self.experiment_dir, "experiment_state.json")

    def save_experiment_state(self) -> None:
        state = {
            "timestamp": time.time(),
            "metric": self.metric,
            "mode": self.mode,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": {k: v for k, v in t.config.items()},
                    "status": t.status,
                    "last_result": t.last_result,
                    "metrics_history": t.metrics_history,
                    "latest_checkpoint": t.latest_checkpoint,
                    "best_checkpoint": t.best_checkpoint,
                    "error": t.error,
                    "local_dir": t.local_dir,
                }
                for t in self.trials
            ],
        }
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, default=repr)
        os.replace(tmp, self._state_path())

    def restore_experiment_state(self) -> None:
        """Reload trial states; RUNNING trials are reset to PENDING and
        resume from their latest checkpoint (reference:
        tune_controller.py:1791 trial restore)."""
        with open(self._state_path()) as f:
            state = json.load(f)
        self.trials = []
        for ts in state["trials"]:
            t = Trial(
                trial_id=ts["trial_id"],
                config=ts["config"],
                status=ts["status"],
                last_result=ts["last_result"],
                metrics_history=ts.get("metrics_history", []),
                latest_checkpoint=ts.get("latest_checkpoint"),
                best_checkpoint=ts.get("best_checkpoint"),
                error=ts.get("error"),
                local_dir=ts.get("local_dir") or os.path.join(
                    self.experiment_dir, ts["trial_id"]
                ),
            )
            if t.status == RUNNING:
                t.status = PENDING
            self.trials.append(t)
        # Searcher alignment: drop one suggestion per existing trial.
        for t in self.trials:
            self.searcher.suggest(t.trial_id)
            if t.status in (TERMINATED, ERROR):
                self.searcher.on_trial_complete(
                    t.trial_id, t.last_result, error=t.status == ERROR
                )
