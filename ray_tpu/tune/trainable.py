"""Trainables: the unit of work Tune runs, and the actor hosting it.

Reference: python/ray/tune/trainable/ — class API (trainable.py:
setup/step/save_checkpoint/load_checkpoint) and function API
(function_trainable.py: the loop calls tune.report). Both run inside a
``_TrialActor``; function trainables stream results through the same
session queue the Train workers use (one report contract across
libraries, as in the reference's AIR session).
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ..train.checkpoint import Checkpoint
from ..train.session import TrainContext, TrainSession, init_session


class Trainable:
    """Class API (reference: tune/trainable/trainable.py)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = config or {}
        self.iteration = 0
        self.setup(self.config)

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        return None

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Reuse the instance for new hyperparams (PBT). Returning False
        forces a rebuild."""
        return False

    def cleanup(self) -> None:
        pass


@ray_tpu.remote
class _TrialActor:
    """One actor per running trial (reference: Tune runs each trial as a
    remote Trainable actor via RayActorManager — SURVEY.md §2.4)."""

    def __init__(self, trial_id: str, local_dir: str):
        self.trial_id = trial_id
        self.local_dir = local_dir
        os.makedirs(local_dir, exist_ok=True)
        self.session: Optional[TrainSession] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_flag = threading.Event()
        self._trainable: Optional[Trainable] = None
        self._ckpt_seq = 0

    # ------------------------------------------------------------- run

    def run(self, trainable, config: Dict[str, Any],
            checkpoint_path: Optional[str] = None,
            stop_criteria: Optional[Dict[str, Any]] = None) -> None:
        self.session = init_session(TrainContext(
            world_rank=0, world_size=1, local_rank=0, node_rank=0,
            experiment_name=self.trial_id, storage_path=self.local_dir,
        ))
        if checkpoint_path:
            self.session.context.latest_checkpoint = Checkpoint(checkpoint_path)
        self._stop_flag.clear()
        stop_criteria = stop_criteria or {}

        def runner():
            try:
                if isinstance(trainable, type) and issubclass(trainable, Trainable):
                    self._run_class(trainable, config, checkpoint_path,
                                    stop_criteria)
                else:
                    trainable(config)
                self.session.finish()
            except BaseException as e:  # noqa: BLE001
                traceback.print_exc()
                self.session.finish(error=e)

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def _run_class(self, cls, config, checkpoint_path, stop_criteria):
        t: Trainable = cls(config)
        self._trainable = t
        if checkpoint_path:
            t.load_checkpoint(checkpoint_path)
            # iteration restore: encoded in the checkpoint dir name
            base = os.path.basename(checkpoint_path.rstrip("/"))
            if base.startswith("checkpoint_"):
                t.iteration = int(base.split("_")[-1])
        max_iter = stop_criteria.get("training_iteration")
        while not self._stop_flag.is_set():
            result = t.step()
            t.iteration += 1
            result.setdefault("training_iteration", t.iteration)
            ckpt_dir = os.path.join(self.local_dir,
                                    f"checkpoint_{t.iteration:06d}")
            os.makedirs(ckpt_dir, exist_ok=True)
            saved = t.save_checkpoint(ckpt_dir)
            ckpt = Checkpoint(saved or ckpt_dir)
            self.session.report(result, checkpoint=ckpt)
            if result.get("done") or (max_iter and t.iteration >= max_iter):
                break
        t.cleanup()

    # ----------------------------------------------------------- polling

    def next_result(self, timeout: float = 10.0):
        """One (kind, payload) event: ("result", (metrics, ckpt_path)) |
        ("done", None) | ("error", exc) | ("timeout", None)."""
        import queue as _q

        try:
            item = self.session.next_result(timeout=timeout)
        except _q.Empty:
            return ("timeout", None)
        kind = item[0]
        if kind == "report":
            metrics, ckpt = item[1], item[2]
            path = ckpt.path if isinstance(ckpt, Checkpoint) else ckpt
            return ("result", (metrics, path))
        if kind == "done":
            err = self.session.error
            if err is not None:
                try:
                    import cloudpickle

                    cloudpickle.dumps(err)
                except Exception:
                    err = RuntimeError(str(err))
                return ("error", err)
            return ("done", None)
        return ("timeout", None)

    def stop(self) -> None:
        self._stop_flag.set()


def wrap_function(fn: Callable, extra: Dict[str, Any]) -> Callable:
    """tune.with_parameters (reference: tune/trainable/util.py)."""

    def wrapped(config):
        return fn(config, **extra)

    return wrapped
