"""Native model-based searchers: TPE, BOHB, Repeater.

Reference: python/ray/tune/search/hyperopt/hyperopt_search.py (TPE via
the hyperopt package), tune/search/bohb/ (TuneBOHB via hpbandster),
tune/search/repeater.py. Those adapters wrap external packages this
image doesn't carry; here the algorithms are implemented natively on
the same Searcher interface:

- ``TPESearch``: Tree-structured Parzen Estimator (Bergstra et al.,
  NeurIPS 2011). Observations split into good/bad by the gamma
  quantile of the objective; each dimension gets a kernel-density
  ("Parzen") model l(x) of the good points and g(x) of the bad, and
  candidates sampled from l are ranked by l(x)/g(x).
- ``BOHBSearch``: BOHB's model-based half (Falkner et al., ICML 2018):
  a TPE model per fidelity (training_iteration), always using the
  HIGHEST budget that has enough observations; pairs with the ASHA /
  HyperBand schedulers for the bandit half.
- ``Repeater``: evaluates every suggested config k times and reports
  the mean metric to the wrapped searcher (noisy objectives).
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from .search import Categorical, Domain, Float, Integer, Searcher, resolve_config


class _ParzenDim:
    """Per-dimension kernel density over observed values, mixed with a
    uniform prior so unexplored regions keep probability mass."""

    def __init__(self, domain: Domain):
        self.domain = domain

    # ------------------------------------------------------------ float
    def _bounds(self):
        d = self.domain
        if isinstance(d, Float) and d.log:
            return math.log(d.lower), math.log(d.upper)
        return float(d.lower), float(d.upper)

    def _to_unit(self, v: float) -> float:
        lo, hi = self._bounds()
        x = math.log(v) if isinstance(self.domain, Float) and self.domain.log \
            else float(v)
        return (x - lo) / (hi - lo)

    def _from_unit(self, u: float) -> Any:
        lo, hi = self._bounds()
        x = lo + min(max(u, 0.0), 1.0) * (hi - lo)
        d = self.domain
        if isinstance(d, Float):
            v = math.exp(x) if d.log else x
            if d.q:
                v = round(v / d.q) * d.q
            return min(max(v, d.lower), d.upper)
        return min(int(round(x)), d.upper - 1)

    def fit(self, obs: List[Any]) -> "_FittedDim":
        return _FittedDim(self, obs)

    def _bandwidth(self, us: List[float]) -> float:
        # Scott's rule on the unit interval: adapts to the spread of
        # the observations, so sampling tightens as the good set
        # concentrates. Floored so kernels never collapse to spikes —
        # and for integers the floor is ONE STEP, so neighboring
        # values stay reachable when the good set piles on one value
        # (otherwise a local optimum is inescapable).
        floor = 0.02
        if isinstance(self.domain, Integer):
            lo, hi = self._bounds()
            floor = max(floor, 1.0 / max(hi - lo, 1.0))
        n = len(us)
        if n < 2:
            return max(0.25, floor)
        mean = sum(us) / n
        var = sum((u - mean) ** 2 for u in us) / (n - 1)
        return max(floor, math.sqrt(var) * n ** -0.2)


class _FittedDim:
    """A _ParzenDim bound to one observation set: unit transforms,
    bandwidth, and category weights computed once, then reused across
    every candidate of a suggest() pass."""

    def __init__(self, pd: _ParzenDim, obs: List[Any]):
        self.pd = pd
        d = pd.domain
        self.categorical = isinstance(d, Categorical)
        if self.categorical:
            # Smoothed counts (add-one prior over all categories).
            self.weights = [1.0] * len(d.categories)
            for v in obs:
                self.weights[d.categories.index(v)] += 1.0
            self.total = sum(self.weights)
        else:
            self.us = [pd._to_unit(v) for v in obs]
            self.bw = pd._bandwidth(self.us)
            self._norm = self.bw * math.sqrt(2 * math.pi)

    def sample(self, rng: random.Random) -> Any:
        d = self.pd.domain
        if self.categorical:
            return rng.choices(d.categories, weights=self.weights)[0]
        if not self.us or rng.random() < 0.2:  # prior draw: exploration
            return d.sample(rng)
        center = rng.choice(self.us)
        return self.pd._from_unit(rng.gauss(center, self.bw))

    def logpdf(self, value: Any) -> float:
        d = self.pd.domain
        if self.categorical:
            return math.log(
                self.weights[d.categories.index(value)] / self.total
            )
        u = self.pd._to_unit(value)
        # Mixture: uniform prior (weight 1) + one kernel per observation.
        dens = 1.0  # uniform on [0, 1]
        for o in self.us:
            z = (u - o) / self.bw
            dens += math.exp(-0.5 * z * z) / self._norm
        return math.log(dens / (len(self.us) + 1))


class TPESearch(Searcher):
    """Tree-structured Parzen Estimator (reference adapter:
    hyperopt_search.py; algorithm implemented natively here)."""

    def __init__(self, metric=None, mode=None, seed: Optional[int] = None,
                 gamma: float = 0.25, n_candidates: int = 24,
                 min_observations: int = 8):
        super().__init__(metric, mode)
        self._rng = random.Random(seed)
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.min_observations = min_observations
        self._results: List[Dict[str, Any]] = []  # {config, value}
        self._pending: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------- model
    def _split(self, results):
        vals = sorted(r["value"] for r in results)
        cut = vals[max(0, int(math.ceil(self.gamma * len(vals))) - 1)]
        good = [r for r in results if r["value"] <= cut]
        bad = [r for r in results if r["value"] > cut]
        return good, bad

    def _model_dims(self):
        return {
            k: _ParzenDim(v)
            for k, v in self._space.items()
            if isinstance(v, (Float, Integer, Categorical))
        }

    @staticmethod
    def _key(cfg: Dict[str, Any]):
        try:
            return tuple(sorted(cfg.items()))
        except TypeError:  # unhashable leaf: no dedup possible
            return None

    def _suggest_from(self, results) -> Dict[str, Any]:
        if len(results) < self.min_observations:
            return resolve_config(self._space, self._rng)
        good, bad = self._split(results)
        dims = self._model_dims()
        # Tabu on exact repeats: re-evaluating a deterministic config
        # teaches nothing, and in discrete spaces the duplicates flood
        # the good-set quantile until the model collapses onto the
        # incumbent and can never escape it.
        tried = {self._key(r["config"]) for r in results}
        tried.update(self._key(c) for c in self._pending.values())
        tried.discard(None)  # unhashable configs can't be deduped
        models = {
            k: (
                dim.fit([r["config"][k] for r in good]),
                dim.fit([r["config"][k] for r in bad]),
            )
            for k, dim in dims.items()
        }
        best_cfg, best_score = None, -math.inf
        fallback = None
        for _ in range(self.n_candidates):
            cfg = resolve_config(self._space, self._rng)
            score = 0.0
            for k, (l_model, g_model) in models.items():
                cfg[k] = l_model.sample(self._rng)
                score += l_model.logpdf(cfg[k]) - g_model.logpdf(cfg[k])
            fallback = fallback or cfg
            key = self._key(cfg)
            if key is not None and key in tried:
                continue
            if score > best_score:
                best_cfg, best_score = cfg, score
        return best_cfg or fallback

    # --------------------------------------------------------- interface
    def suggest(self, trial_id: str):
        cfg = self._suggest_from(self._results)
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or error or result is None or self.metric not in result:
            return
        value = result[self.metric]
        if self.mode == "max":
            value = -value
        self._results.append({"config": cfg, "value": value})


class BOHBSearch(TPESearch):
    """BOHB's model half (reference adapter: tune/search/bohb/): one
    TPE model per fidelity, preferring the highest training_iteration
    with enough observations. Pair with the ASHA/HyperBand scheduler
    for early stopping (the bandit half)."""

    def __init__(self, metric=None, mode=None, seed=None, gamma=0.25,
                 n_candidates=24, min_observations=8):
        super().__init__(metric, mode, seed, gamma, n_candidates,
                         min_observations)
        # budget -> {trial_id: {config, value}}: ONE entry per trial
        # per fidelity (a mid-train report then a terminal report at
        # the same budget must overwrite, not append — duplicates made
        # min_observations trip on 3 unique configs and the model
        # locked onto best-of-3-random).
        self._by_budget: Dict[int, Dict[str, Dict[str, Any]]] = {}

    def on_trial_result(self, trial_id, result):
        cfg = self._pending.get(trial_id)
        if cfg is None or self.metric not in result:
            return
        budget = int(result.get("training_iteration", 1))
        value = result[self.metric]
        if self.mode == "max":
            value = -value
        self._by_budget.setdefault(budget, {})[trial_id] = {
            "config": dict(cfg), "value": value,
        }

    def on_trial_complete(self, trial_id, result=None, error=False):
        # Terminal result counts at its budget too.
        if result is not None and not error:
            self.on_trial_result(trial_id, result)
        self._pending.pop(trial_id, None)

    def suggest(self, trial_id: str):
        # Highest fidelity with a modelable population wins (BOHB §3.2).
        results: List[Dict[str, Any]] = []
        for budget in sorted(self._by_budget, reverse=True):
            if len(self._by_budget[budget]) >= self.min_observations:
                results = list(self._by_budget[budget].values())
                break
        cfg = self._suggest_from(results)
        self._pending[trial_id] = cfg
        return cfg


class Repeater(Searcher):
    """Evaluate each suggestion ``repeat`` times; the wrapped searcher
    sees one completion with the MEAN metric (reference:
    tune/search/repeater.py)."""

    def __init__(self, searcher: Searcher, repeat: int = 3):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.repeat = repeat
        self._groups: Dict[str, Dict[str, Any]] = {}  # group id -> state
        self._trial_group: Dict[str, str] = {}
        self._open: Optional[str] = None

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(metric, mode, space)
        self.searcher.set_search_properties(metric, mode, space)

    def suggest(self, trial_id: str):
        if self._open is None:
            cfg = self.searcher.suggest(trial_id)
            if cfg is None or cfg is Searcher.BACKOFF:
                return cfg
            self._groups[trial_id] = {
                "config": cfg, "values": [], "spawned": 1, "lead": trial_id,
            }
            self._trial_group[trial_id] = trial_id
            if self.repeat > 1:
                self._open = trial_id
            return cfg
        group = self._groups[self._open]
        group["spawned"] += 1
        self._trial_group[trial_id] = self._open
        if group["spawned"] >= self.repeat:
            self._open = None
        return dict(group["config"])

    def on_trial_complete(self, trial_id, result=None, error=False):
        gid = self._trial_group.pop(trial_id, None)
        if gid is None:
            return
        group = self._groups[gid]
        if not error and result is not None and self.metric in result:
            group["values"].append(result[self.metric])
        remaining = sum(1 for g in self._trial_group.values() if g == gid)
        if remaining == 0 and group["spawned"] < self.repeat:
            # Sequential execution (e.g. max_concurrent=1): the lead
            # finished before any sibling was suggested. Keep the group
            # open — the next suggest() continues it.
            self._open = gid
            return
        if remaining == 0:
            vals = group["values"]
            mean = (sum(vals) / len(vals)) if vals else None
            self.searcher.on_trial_complete(
                group["lead"],
                result=None if mean is None else {self.metric: mean},
                error=mean is None,
            )
            self._groups.pop(gid, None)
            if self._open == gid:
                self._open = None
