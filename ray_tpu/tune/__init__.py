"""ray_tpu.tune: distributed hyperparameter tuning.

Reference: python/ray/tune — Tuner.fit drives a TuneController event
loop over one actor per trial; searchers propose configs, schedulers
stop/exploit trials on reported results; experiment state checkpoints
for resume.

    from ray_tpu import tune

    def trainable(config):
        for step in range(10):
            tune.report({"score": config["lr"] * step})

    results = tune.Tuner(
        trainable,
        param_space={"lr": tune.loguniform(1e-4, 1e-1)},
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=8),
    ).fit()
"""
from ..train.session import get_context
from ..train.session import report as _train_report
from .schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    OptunaSearch,
    Searcher,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from .tpe import BOHBSearch, Repeater, TPESearch
from .trainable import Trainable, wrap_function
from .tune_controller import Trial, TuneController
from .tuner import ResultGrid, TuneConfig, Tuner, run


def report(metrics, *, checkpoint=None) -> None:
    """Reference: ray.tune.report — same session contract as
    ray_tpu.train.report."""
    _train_report(metrics, checkpoint=checkpoint)


def get_checkpoint():
    """Latest checkpoint for restoration inside a trial (reference:
    tune.get_checkpoint)."""
    from ..train.session import get_session

    s = get_session()
    return getattr(s.context, "latest_checkpoint", None) if s else None


def with_parameters(fn, **kwargs):
    """Reference: tune.with_parameters."""
    return wrap_function(fn, kwargs)


__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "MedianStoppingRule",
    "OptunaSearch",
    "TPESearch",
    "BOHBSearch",
    "Repeater",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "Trainable",
    "Trial",
    "TrialScheduler",
    "TuneConfig",
    "TuneController",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_context",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "randn",
    "report",
    "run",
    "sample_from",
    "uniform",
    "with_parameters",
]

from ray_tpu._private import usage_stats as _usage

_usage.record_library_usage("tune")
