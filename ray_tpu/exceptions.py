"""Exception taxonomy (reference: python/ray/exceptions.py)."""
from __future__ import annotations

import traceback as _tb
from typing import Optional


class RayTpuError(Exception):
    """Base class for ray_tpu errors."""


class RayTaskError(RayTpuError):
    """A task raised; re-raised at ``get`` with the remote traceback.

    Reference: exceptions.py RayTaskError — wraps the user exception and
    carries the remote stack so the driver sees where it failed.
    """

    def __init__(
        self,
        function_name: str,
        traceback_str: str,
        cause: Optional[BaseException] = None,
    ):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"{function_name} failed:\n{traceback_str}")

    @classmethod
    def from_exception(cls, function_name: str, exc: BaseException) -> "RayTaskError":
        return cls(function_name, "".join(_tb.format_exception(exc)), exc)

    def __reduce__(self):
        # The cause may not survive pickling (custom unpicklable exception);
        # degrade to traceback-only rather than fail the error report.
        import pickle

        cause = self.cause
        try:
            pickle.dumps(cause)
        except Exception:
            cause = None
        return (RayTaskError, (self.function_name, self.traceback_str, cause))

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is also an instance of the cause's type,
        so ``except UserError`` works across the task boundary."""
        if self.cause is None:
            return self
        cause_cls = type(self.cause)
        if issubclass(cause_cls, RayTaskError):
            return self
        try:
            derived = type(
                "RayTaskError(" + cause_cls.__name__ + ")",
                (RayTaskError, cause_cls),
                {},
            )
            instance = derived(self.function_name, self.traceback_str, self.cause)
            return instance
        except TypeError:
            return self


class RayActorError(RayTpuError):
    """The actor died before or during this method call
    (reference: exceptions.py:287)."""

    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        super().__init__(f"Actor {actor_id} unavailable: {reason}")


class ActorDiedError(RayActorError):
    pass


class ActorUnavailableError(RayActorError):
    """The actor is temporarily unreachable (reference:
    exceptions.py ActorUnavailableError); the call may be retried."""


class ActorUnschedulableError(RayTpuError):
    pass


class BackPressureError(RayTpuError):
    """Too many queued requests (reference: serve
    BackPressureError) — the caller should shed load or retry later."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` timed out before the object was available."""


class ObjectLostError(RayTpuError):
    """All copies of the object are gone and it cannot be reconstructed
    (reference: exceptions.py:511)."""

    def __init__(self, object_id=None):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost")


class OutOfMemoryError(RayTpuError):
    """Task/actor killed by the memory monitor (reference: exceptions.py:483)."""


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    pass


class TaskUnschedulableError(RayTpuError):
    """The task can never be scheduled (e.g. a hard NodeAffinity target
    that left the cluster). Reference: exceptions.py
    TaskUnschedulableError."""
