"""Usage statistics, offline-native (reference:
python/ray/_private/usage/usage_lib.py).

The reference batches cluster metadata + feature-usage tags and POSTs
them to a collector unless disabled. This environment has zero egress,
so the pipeline keeps the reference's *shape* — tag recording, cluster
snapshot, periodic flush, explicit enable/disable — but the sink is a
local JSONL file under the session temp dir that operators inspect
with ``ray_tpu usage``. Nothing ever leaves the machine.

Env toggles (reference parity): RAY_TPU_USAGE_STATS_ENABLED=0 disables
recording entirely.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_tags: Dict[str, str] = {}
_features: set = set()
_path: Optional[str] = None


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "False",
    )


def _sink_path() -> Optional[str]:
    global _path
    if _path is not None:
        return _path
    base = os.environ.get("RAY_TPU_TEMP_DIR", "/tmp/ray_tpu")
    try:
        os.makedirs(base, exist_ok=True)
        _path = os.path.join(base, "usage_stats.jsonl")
    except OSError:
        _path = None
    return _path


def record_library_usage(library: str) -> None:
    """Mark a library as used this session (reference:
    record_library_usage — called from data/train/tune/serve/rllib
    entry points)."""
    if not enabled():
        return
    with _lock:
        _features.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    if not enabled():
        return
    with _lock:
        _tags[key] = str(value)


def cluster_snapshot() -> Dict[str, Any]:
    """Cluster metadata the reference ships in each report."""
    snap: Dict[str, Any] = {
        "ts": time.time(),
        "session": os.environ.get("RAY_TPU_NODE_ID", ""),
    }
    try:
        import ray_tpu

        snap["total_resources"] = ray_tpu.cluster_resources()
        snap["num_nodes"] = len(ray_tpu.nodes())
    except Exception:  # noqa: BLE001 - not initialized
        pass
    with _lock:
        snap["libraries"] = sorted(_features)
        snap["tags"] = dict(_tags)
    return snap


def flush() -> Optional[str]:
    """Append one snapshot line to the local sink; returns the path."""
    if not enabled():
        return None
    path = _sink_path()
    if path is None:
        return None
    try:
        with open(path, "a") as f:
            f.write(json.dumps(cluster_snapshot()) + "\n")
    except OSError:
        return None
    return path


def read_all() -> List[Dict[str, Any]]:
    path = _sink_path()
    if path is None or not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
