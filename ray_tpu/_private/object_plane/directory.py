"""Sharded object directory: the head half of the object plane.

Reference: src/ray/object_manager/ownership_based_object_directory.h —
the directory is consulted per object id, never serialized through one
global table pass. Here the head's object table is split into N shards,
each with its own lock domain and its own refcount flush queue:

- The **facade** (dict-compatible: get/setdefault/pop/items/...) lets
  the GCS handlers keep their existing call sites; each call takes only
  the owning shard's lock, so directory traffic from different handler
  threads stops contending on one structure.

- **Flush queues**: refcount batches (`ref_flush`/legacy `update_refs`)
  are ENQUEUED by the dispatch loop — an O(batch) list append, no
  per-object holder mutation — and applied by one applier thread per
  shard under the shard lock. Appliers nominate free candidates; actual
  freeing re-checks and runs under the GCS lock via ``free_callback``
  (ownership-edge transitions are rare relative to instance churn, so
  this keeps every hot-path mutation off the dispatch loop while frees
  stay coherent with waiter/pin/store state).

- **Early-drop ledger** (per shard): an owner's release can race ahead
  of the worker's batched task_done that creates the entry (the leased
  path advertises return refs client-side only). The ledger remembers
  the release so seal-time reclaims the result instead of leaking it —
  the sharded port of the head's old ``_early_drops``.

Lock order: GCS lock -> shard lock (facade calls under the GCS lock).
Appliers take the shard lock alone, release it, then call the free
callback which takes the GCS lock — never both at once, so the two
domains cannot deadlock.

Test hook: ``GUARD``/``mark_dispatch`` flag the dispatch threads and
wrap entry holder-sets so a test can assert that NO per-object
refcount/holder-set mutation executes on the head dispatch loop.

The same invariant is enforced statically: the raylint thread-domain
rule reads the guarded-attrs declaration below and requires every
mutation of those attributes to sit in a ``# raylint: applier-only``
function (the runtime guard catches what static analysis can't prove;
the lint catches it before it runs).
"""
# raylint: guarded-attrs=holders,owner_released,had_holder
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import events as _events

#: Per-shard bound on remembered early drops (FIFO eviction).
EARLY_DROP_CAP = 2048

#: Per-shard bound on free tombstones (FIFO eviction). A tombstone
#: remembers that an entry was FREED so a late borrow add (or a get)
#: for it cannot resurrect a forever-PENDING ghost — the late holder
#: lands on a LOST entry and the get fails fast instead of wedging
#: (found by the chaos soak: release racing a batched badd).
TOMBSTONE_CAP = 4096

# ---------------------------------------------------------------- guard

#: When True (tests), GCS dispatch threads are flagged via
#: mark_dispatch() and holder-set mutations performed on them are
#: counted into ShardedObjectDirectory.stats["dispatch_mutations"].
GUARD = False

_guard_tl = threading.local()


def mark_dispatch(active: bool) -> None:
    _guard_tl.active = active


def on_dispatch_thread() -> bool:
    return getattr(_guard_tl, "active", False)


class _GuardedHolderSet(set):
    """Holder set that counts mutations made on dispatch threads."""

    __slots__ = ("_stats",)

    def __init__(self, stats, iterable=()):
        super().__init__(iterable)
        self._stats = stats

    def _check(self):
        if on_dispatch_thread():
            self._stats["dispatch_mutations"] += 1

    def add(self, item):
        self._check()
        super().add(item)

    def discard(self, item):
        self._check()
        super().discard(item)

    def remove(self, item):
        self._check()
        super().remove(item)


class _Shard:
    __slots__ = (
        "index", "lock", "entries", "queue", "early_drops",
        "tombstones", "applied", "enqueued",
    )

    def __init__(self, index: int):
        self.index = index
        self.lock = threading.Lock()
        self.entries: Dict[bytes, Any] = {}
        self.queue: List[tuple] = []
        self.early_drops: "OrderedDict[bytes, None]" = OrderedDict()
        self.tombstones: "OrderedDict[bytes, None]" = OrderedDict()
        self.applied = 0
        self.enqueued = 0


class ShardedObjectDirectory:
    """N-sharded object table + per-shard refcount flush queues.

    ``entry_factory`` builds a directory entry (the GCS's ObjectEntry);
    passed in to keep this module free of a gcs import cycle.
    ``free_callback(oids)`` is invoked by applier threads (no locks
    held) with entries that look reclaimable; the callback re-checks
    under the GCS lock and performs the actual free.
    """

    def __init__(
        self,
        entry_factory: Callable[[], Any],
        num_shards: Optional[int] = None,
        free_callback: Optional[Callable[[List[bytes]], None]] = None,
    ):
        from ..config import RayConfig

        n = int(num_shards or RayConfig.object_directory_shards)
        self.num_shards = max(1, n)
        self._entry_factory = entry_factory
        self.free_callback = free_callback
        # pin->borrow conversions ("pin2b") hand released pins back
        # through here once the borrow edge has landed (set by the GCS).
        self.unpin_callback: Optional[Callable[[List[bytes]], None]] = None
        self._shards = [_Shard(i) for i in range(self.num_shards)]
        # Clients known dead (bounded FIFO). A badd/add/pin2b op that
        # was still sitting in a shard queue when its client's death
        # sweep ran would otherwise apply AFTER the sweep and
        # resurrect a holder shadow nothing ever retracts (chaos-soak
        # leak: dead workers re-appearing in holder sets). Appliers
        # consult this under the shard lock; mutation happens on the
        # GCS death path.
        self.dead_clients: "OrderedDict[bytes, None]" = OrderedDict()
        self._dead_lock = threading.Lock()
        self._stopped = False
        # ONE applier thread services every shard queue. Shards keep
        # their own lock domains and flush queues (facade callers from
        # different dispatch threads contend per shard, not globally),
        # but apply/free runs on a single poll-coalescing thread: every
        # extra hot background thread in the head process measurably
        # taxed the dispatch hot path (~6us/task each at storm rates).
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._applying = False
        self.stats: Dict[str, int] = {
            "enqueued_ops": 0,
            "applied_ops": 0,
            "early_drops": 0,
            "free_candidates": 0,
            "dispatch_mutations": 0,
        }

    # ------------------------------------------------------------ sharding

    def _shard(self, oid: bytes) -> _Shard:
        return self._shards[hash(oid) % self.num_shards]

    def _wrap(self, entry):
        if GUARD and type(entry.holders) is set:
            # raylint: disable=thread-domain -- rebinds the set to its guard wrapper (same elements); not a refcount mutation
            entry.holders = _GuardedHolderSet(self.stats, entry.holders)
        return entry

    # ------------------------------------------------------- dict facade
    # Each call takes only the owning shard's lock; safe under the GCS
    # lock (lock order GCS -> shard).

    def get(self, oid: bytes, default=None):
        s = self._shard(oid)
        with s.lock:
            return s.entries.get(oid, default)

    def __getitem__(self, oid: bytes):
        s = self._shard(oid)
        with s.lock:
            return s.entries[oid]

    def __setitem__(self, oid: bytes, entry) -> None:
        s = self._shard(oid)
        with s.lock:
            s.tombstones.pop(oid, None)  # legitimate recreation
            s.entries[oid] = self._wrap(entry)

    def __contains__(self, oid: bytes) -> bool:
        s = self._shard(oid)
        with s.lock:
            return oid in s.entries

    def setdefault(self, oid: bytes, default):
        s = self._shard(oid)
        with s.lock:
            e = s.entries.get(oid)
            if e is None:
                s.tombstones.pop(oid, None)  # legitimate recreation
                e = s.entries[oid] = self._wrap(default)
            return e

    def pop(self, oid: bytes, default=None):
        s = self._shard(oid)
        with s.lock:
            return s.entries.pop(oid, default)

    def __len__(self) -> int:
        return sum(len(s.entries) for s in self._shards)

    def items(self) -> List[Tuple[bytes, Any]]:
        out: List[Tuple[bytes, Any]] = []
        for s in self._shards:
            with s.lock:
                out.extend(s.entries.items())
        return out

    def values(self) -> List[Any]:
        out: List[Any] = []
        for s in self._shards:
            with s.lock:
                out.extend(s.entries.values())
        return out

    def keys(self) -> List[bytes]:
        out: List[bytes] = []
        for s in self._shards:
            with s.lock:
                out.extend(s.entries.keys())
        return out

    def __iter__(self):
        return iter(self.keys())

    # ------------------------------------------------------- early drops

    def pop_reclaimable(self, oid: bytes):
        """Atomically re-check eligibility and remove the entry — ONE
        shard-lock acquisition on the retire path (which runs under the
        GCS lock: every instruction here extends the serialized region
        the dispatch hot path waits on). Returns the popped entry, or
        None if it became ineligible."""
        s = self._shard(oid)
        with s.lock:
            e = s.entries.get(oid)
            if e is None or not self._reclaimable(e):
                return None
            del s.entries[oid]
            return e

    def seal_lookup(self, oid: bytes, default):
        """Seal-time hot path: setdefault + early-drop consume in ONE
        shard-lock acquisition (one per sealed result at storm rates).
        Returns (entry, release_raced_ahead)."""
        s = self._shard(oid)
        with s.lock:
            e = s.entries.get(oid)
            if e is None:
                s.tombstones.pop(oid, None)  # result (re)seal is fresh state
                e = s.entries[oid] = self._wrap(default)
            dropped = s.early_drops.pop(oid, _MISSING) is not _MISSING
        return e, dropped

    def take_early_drop(self, oid: bytes) -> bool:
        """Seal-time check: did a release/remove race ahead of this
        entry's creation? Consumes the ledger record."""
        s = self._shard(oid)
        with s.lock:
            return s.early_drops.pop(oid, _MISSING) is not _MISSING

    # -------------------------------------------------------- tombstones

    def note_tombstone(self, oid: bytes) -> None:
        """The entry was freed: remember it (bounded) so late refcount
        traffic and gets fail fast instead of resurrecting a ghost."""
        s = self._shard(oid)
        with s.lock:
            s.tombstones[oid] = None
            while len(s.tombstones) > TOMBSTONE_CAP:
                s.tombstones.popitem(last=False)

    def is_tombstoned(self, oid: bytes) -> bool:
        s = self._shard(oid)
        with s.lock:
            return oid in s.tombstones

    # ------------------------------------------------------ dead clients

    DEAD_CLIENT_CAP = 1024

    def note_dead_client(self, cid: bytes) -> None:
        """Mark a client dead BEFORE sweeping its holder shadows, so
        its queued-but-unapplied holder ops are dropped at apply time
        instead of resurrecting after the sweep."""
        with self._dead_lock:
            self.dead_clients[cid] = None
            while len(self.dead_clients) > self.DEAD_CLIENT_CAP:
                self.dead_clients.popitem(last=False)

    def is_dead_client(self, cid: bytes) -> bool:
        with self._dead_lock:
            return cid in self.dead_clients

    # ------------------------------------------------------- flush queues

    # raylint: dispatch-only
    def enqueue(self, ops: List[tuple]) -> Dict[int, int]:
        """Dispatch-loop half: split a refcount batch across shard
        queues. O(batch) appends; NO entry mutation happens here.

        Each op is ``(kind, oid, client)`` with kind one of:
        release / badd / bdel / add / remove.
        Returns per-shard enqueue counts (flight-recorder attrs).
        """
        per_shard: Dict[int, List[tuple]] = {}
        for op in ops:
            idx = hash(op[1]) % self.num_shards
            per_shard.setdefault(idx, []).append(op)
        counts: Dict[int, int] = {}
        for idx, shard_ops in per_shard.items():
            s = self._shards[idx]
            with s.lock:
                s.queue.extend(shard_ops)
                s.enqueued += len(shard_ops)
            counts[idx] = len(shard_ops)
        self.stats["enqueued_ops"] += len(ops)
        self._ensure_applier()
        self._wake.set()
        return counts

    def _ensure_applier(self) -> None:
        if self._thread is None and not self._stopped:
            t = threading.Thread(
                target=self._apply_loop, name="objdir-apply", daemon=True,
            )
            self._thread = t
            t.start()

    #: Coalescing window between applier passes. Refcount edges are
    #: latency-tolerant (clients already batch them on a 100ms flush);
    #: each pass costs one GIL slice plus one free-callback GCS-lock
    #: acquisition, so the window bounds the background tax on the
    #: dispatch hot path.
    _COALESCE_S = 0.02
    #: Empty passes before the applier parks on its event again. While
    #: a storm flows it poll-coalesces instead of paying a park/wake
    #: GIL handoff per flush message (same rationale as the event
    #: aggregator's poll loop).
    _HOT_PASSES = 8

    # raylint: applier-only
    def _apply_loop(self) -> None:
        while not self._stopped:
            self._wake.wait()
            if self._stopped:
                return
            self._wake.clear()
            idle_passes = 0
            while idle_passes < self._HOT_PASSES and not self._stopped:
                time.sleep(self._COALESCE_S)
                t0 = time.monotonic()
                self._applying = True
                total = 0
                candidates: List[bytes] = []
                unpins: List[bytes] = []
                for s in self._shards:
                    with s.lock:
                        if not s.queue:
                            continue
                        ops, s.queue = s.queue, []
                        for op in ops:
                            try:
                                self._apply_one(s, op, candidates, unpins)
                            except Exception:  # noqa: BLE001
                                # A poisoned op must not kill the only
                                # applier thread (that would silently
                                # stop every free cluster-wide); drop
                                # it, counted never silent.
                                self.stats["apply_errors"] = (
                                    self.stats.get("apply_errors", 0) + 1
                                )
                        s.applied += len(ops)
                    total += len(ops)
                if not total:
                    self._applying = False
                    idle_passes += 1
                    continue
                idle_passes = 0
                self.stats["applied_ops"] += total
                try:
                    if unpins and self.unpin_callback is not None:
                        self.unpin_callback(unpins)
                    if candidates:
                        self.stats["free_candidates"] += len(candidates)
                        cb = self.free_callback
                        if cb is not None:
                            # No locks held: the callback takes the
                            # GCS lock and re-checks eligibility there.
                            cb(candidates)
                except Exception:  # noqa: BLE001 - applier must survive
                    # A failing free/unpin callback drops this pass's
                    # candidates; the entries stay resident until the
                    # next retraction re-nominates them. Counted,
                    # never silent (raylint swallowed-fault).
                    self.stats["callback_errors"] = (
                        self.stats.get("callback_errors", 0) + 1
                    )
                finally:
                    self._applying = False
                if _events.enabled():
                    _events.record(
                        _events.REFS, "apply", "SHARD_APPLY",
                        {
                            "ops": total,
                            "freed_candidates": len(candidates),
                            "seconds": time.monotonic() - t0,
                        },
                    )

    # raylint: applier-only
    def _apply_one(self, s: _Shard, op: tuple,
                   candidates: List[bytes],
                   unpins: Optional[List[bytes]] = None) -> None:
        """One refcount op under the shard lock (applier thread)."""
        kind, oid, cid = op
        entry = s.entries.get(oid)
        dead = cid in self.dead_clients
        if dead and kind in ("badd", "add", "pin2b"):
            # The client died while this op sat in the queue: adding
            # its holder now would outlive every retraction path.
            self.stats["dead_client_ops"] = (
                self.stats.get("dead_client_ops", 0) + 1
            )
            if kind == "pin2b":
                # The pin release half must still run or task_pins leak.
                if unpins is not None:
                    unpins.append(oid)
                if entry is not None and self._reclaimable(entry):
                    candidates.append(oid)
            return
        if kind == "pin2b":
            # Dependency-pin -> borrow conversion (task_done piggyback):
            # record the borrow, then queue the pin release — the GCS
            # decrements task_pins under its own lock via
            # unpin_callback, AFTER this holder is visible.
            if entry is not None:
                entry.holders.add(cid)
                entry.had_holder = True
            if unpins is not None:
                unpins.append(oid)
            return
        if kind == "release":
            if entry is None:
                self._note_early_drop(s, oid)
                return
            entry.owner_released = True
            entry.had_holder = True
            if self._reclaimable(entry):
                candidates.append(oid)
        elif kind == "badd" or kind == "add":
            if entry is None:
                entry = s.entries[oid] = self._wrap(self._entry_factory())
                if oid in s.tombstones:
                    # The object was already FREED (the holder's add
                    # lost the race to the owner's release): a PENDING
                    # ghost here would park any get on it forever.
                    # LOST fails those gets fast, and the entry retires
                    # once this late holder retracts.
                    entry.status = "LOST"
                    self.stats["tombstone_hits"] = (
                        self.stats.get("tombstone_hits", 0) + 1
                    )
            entry.holders.add(cid)
            entry.had_holder = True
        elif kind == "bdel":
            if entry is None:
                # The owner decides this object's lifetime; a shadow
                # retraction for an entry not yet sealed carries no
                # information the owner's release won't.
                return
            entry.holders.discard(cid)
            if self._reclaimable(entry):
                candidates.append(oid)
        elif kind == "remove":
            if entry is None:
                self._note_early_drop(s, oid)
                return
            # A removal implies the client held the ref, even if its
            # add was compressed away within one flush window.
            entry.had_holder = True
            entry.holders.discard(cid)
            if self._reclaimable(entry):
                candidates.append(oid)

    def _note_early_drop(self, s: _Shard, oid: bytes) -> None:
        s.early_drops[oid] = None
        self.stats["early_drops"] += 1
        while len(s.early_drops) > EARLY_DROP_CAP:
            s.early_drops.popitem(last=False)

    @staticmethod
    def _reclaimable(entry) -> bool:
        """Shard-side pre-filter; the free callback re-checks under the
        GCS lock (same predicate as gcs._maybe_free)."""
        if entry.status == "PENDING" or entry.waiters:
            return False
        if entry.task_pins > 0 or entry.child_pins > 0:
            return False
        if entry.holders:
            return False
        hold = getattr(entry, "promoted_hold_until", 0.0)
        if hold and time.monotonic() < hold:
            # Dead-owner grace window (see gcs._sweep_client_refs): a
            # buffered borrow edge may still be in flight for it.
            return False
        return entry.owner_released or (
            entry.owner is None and entry.had_holder
        )

    # ----------------------------------------------------------- control

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until every queued op has been applied (tests/barriers).
        Returns False on timeout."""
        deadline = time.monotonic() + timeout
        while True:
            if self.queue_depth() == 0 and not self._applying:
                return True
            if time.monotonic() > deadline:
                return False
            self._ensure_applier()
            self._wake.set()
            time.sleep(0.001)

    def queue_depth(self) -> int:
        total = 0
        for s in self._shards:
            with s.lock:
                total += len(s.queue)
        return total

    def stop(self) -> None:
        self._stopped = True
        self._wake.set()


_MISSING = object()
