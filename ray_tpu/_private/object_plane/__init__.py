"""Owner-sharded object plane.

Reference: the ownership model of the reference's core worker —
``reference_count.h`` (each object's *owner*, the process that created
it, keeps the authoritative reference state: local instance counts plus
the set of remote borrowers) and
``ownership_based_object_directory.h`` (the directory is keyed by
object id and consulted per object, not serialized through one global
table lock).

Two cooperating pieces replace the centralized per-object bookkeeping
that previously rode the head's single dispatch loop:

- :mod:`.owner_refs` — owner-side reference counting in every client
  process. Local 0<->1 instance transitions for objects this process
  owns never cross the wire at all; only *ownership-edge* transitions
  (the owner's authoritative count draining to zero, borrow edges
  opening/closing, owner death) are batched to the head.

- :mod:`.pull_manager` — admission control over the transfer plane
  (``pull_manager.h``): pulls queue by priority class (get > wait >
  task-args) and activate under a bounded in-flight byte budget, so a
  bulk broadcast cannot starve concurrent gets.

- :mod:`.directory` — the head's object table sharded N ways, each
  shard with its own lock domain and flush queue. The dispatch loop
  only enqueues refcount batches; per-shard applier threads mutate
  holder state and nominate free candidates off the dispatch path.

Ownerless objects (refs constructed without an owner, stream items,
promoted entries after owner death) fall back to head-side holder
sets, preserving the pre-plane semantics exactly.
"""
from . import directory, owner_refs, pull_manager  # noqa: F401
