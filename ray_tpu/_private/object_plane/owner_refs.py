"""Owner-side reference counting: the client half of the object plane.

Reference: src/ray/core_worker/reference_count.h — the process that
creates an object (its *owner*) keeps the authoritative reference
state: the count of local ObjectRef instances plus the set of remote
processes borrowing the ref. The cluster directory is only told about
ownership-edge transitions:

- ``release`` — the owner's authoritative view (local count + borrows)
  drained to zero: the object's memory can be reclaimed everywhere.
- ``badd``/``bdel`` — a *borrowed* ref (owner is another process)
  appeared in / vanished from this process; routed through the head to
  the owner, which folds it into its authoritative view.
- ``add``/``remove`` — head-fallback holder transitions for ownerless
  refs (owner unknown: detached handles, stream items consumed through
  a bare id); these keep the centralized semantics of the previous
  ``ref_tracker`` for objects no owner claims.

Python refcounting still does the heavy lifting: ObjectRef.__init__
calls track(), __del__ calls untrack(); only edges cross the wire,
batched on a flusher thread. The common case — every instance of an
object lives in the owner process — now costs ZERO wire traffic and
zero head-side work until the final release.

Flap/suppression invariants (regression-tested):
- a ref held and dropped (or 1->0->1 flapped) within one flush window
  sends NOTHING for un-advertised oids;
- a remove/bdel/release is only sent after its add (or for owner
  returns, after submission advertised the entry), so a bare removal
  can never race ahead of the state it retracts.

Thread domain (raylint-enforced): every mutation of the guarded
bookkeeping declared below happens in a ``# raylint: applier-only``
method, all of which hold ``self._lock`` — the tracker's equivalent
of the directory's single applier thread.
"""
# raylint: guarded-attrs=_counts,_owner_of,_dirty,_zeroed,_advertised,_borrows,_unacked,_dead_borrowers
from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from .. import chaos as _chaos
from .. import events as _events

FLUSH_INTERVAL_S = 0.1
#: Unacked ref_flush batches older than this are resent (at-least-once;
#: the head applies edges idempotently and sequences them per conn).
RETRANSMIT_S = 1.0
#: Resend attempts per batch before it is counted lost (never silent).
RETRANSMIT_MAX = 20
#: Recently-dead borrowers remembered so a head→owner borrow relay that
#: was delayed/reordered past the borrower_died sweep cannot re-add a
#: borrow edge nothing will ever retract.
DEAD_BORROWER_CAP = 256


class OwnerRefTracker:
    """Per-process instance tracking with owner-side authority.

    API-compatible with the legacy centralized ``RefTracker``
    (incr/decr/holds/mark_advertised/flush/stop) so the client wiring
    and the lifetime tests drive both the same way.
    """

    def __init__(self, client):
        # weakref: the tracker thread must not keep a closed client alive.
        self._client = weakref.ref(client)
        self._self_id: bytes = client.worker_id.binary()
        self._counts: Dict[bytes, int] = {}
        # oid -> owner worker id. b"" = ownerless (head fallback).
        # First truthy owner wins: classification is stable per process.
        self._owner_of: Dict[bytes, bytes] = {}
        self._dirty: Set[bytes] = set()
        self._lock = threading.Lock()
        self._flusher: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stopped = False
        # oids whose local count hit zero; the client drops lineage for
        # them at flush time.
        self._zeroed: Set[bytes] = set()
        # oids whose presence the remote side already knows about (the
        # head for owned/ownerless oids, the owner for borrowed ones).
        # A retraction (release/bdel/remove) is only valid after its
        # advertisement: a ref held and dropped within one flush window
        # must send NOTHING — a bare retraction racing ahead of the
        # still-batched advertisement would free a live object.
        self._advertised: Set[bytes] = set()
        # Owned oids -> remote borrower worker ids (fed by head-relayed
        # borrow_update pushes). A drained local count does NOT release
        # while borrowers remain — the owner is the authority.
        self._borrows: Dict[bytes, Set[bytes]] = {}
        # At-least-once flush protocol: every edge-carrying ref_flush
        # gets a per-process sequence number and is retained here until
        # the head acks it; unacked batches retransmit on the flusher
        # (the head's per-conn sequencer dedups and re-orders). A batch
        # that a lossy transport eats is the correctness-critical path
        # for owner-side counting — one lost release leaks the object
        # cluster-wide forever.
        self._seq = 0
        self._unacked: "OrderedDict[int, List]" = OrderedDict()
        # Client conn generation the current numbering belongs to: a
        # fresh conn means a fresh head-side sequencer, so flush()
        # renumbers unacked batches before its first send on the new
        # conn (checked under the lock — NOT only in on_reconnect, or
        # a flush racing the conn swap would ship a stale seq and
        # poison the new sequencer's baseline).
        self._gen_seen = 0
        # Borrowers swept by borrower_died; late borrow adds for them
        # are stale and must be ignored (see DEAD_BORROWER_CAP).
        self._dead_borrowers: "OrderedDict[bytes, None]" = OrderedDict()
        self.stats: Dict[str, int] = {
            "flushes": 0, "releases": 0, "badd": 0, "bdel": 0,
            "fallback_adds": 0, "fallback_removes": 0,
            "retransmits": 0, "lost_batches": 0, "stale_borrow_adds": 0,
        }

    # ------------------------------------------------------------- tracking

    # raylint: applier-only
    def incr(self, oid: bytes, owner: bytes = b"") -> None:
        with self._lock:
            n = self._counts.get(oid, 0) + 1
            self._counts[oid] = n
            if owner and not self._owner_of.get(oid):
                self._owner_of[oid] = owner
            if n == 1:
                if not self._dirty:
                    self._wake.set()
                self._dirty.add(oid)
                self._zeroed.discard(oid)
                self._ensure_flusher()

    # raylint: applier-only
    def decr(self, oid: bytes) -> None:
        with self._lock:
            n = self._counts.get(oid, 0) - 1
            if n <= 0:
                self._counts.pop(oid, None)
                if not self._dirty:
                    self._wake.set()
                self._dirty.add(oid)
                self._zeroed.add(oid)
            else:
                self._counts[oid] = n

    def holds(self, oid: bytes) -> bool:
        with self._lock:
            return self._counts.get(oid, 0) > 0

    def owner_of(self, oid: bytes) -> bytes:
        with self._lock:
            return self._owner_of.get(oid, b"")

    # raylint: applier-only
    def mark_advertised(self, oid: bytes) -> None:
        """The remote side already records this oid's presence here:
        the head holds the entry for owner return-refs/puts from birth,
        or a task_done piggybacked this process's borrow. The eventual
        drop must send its retraction."""
        with self._lock:
            self._advertised.add(oid)

    # raylint: applier-only
    def mark_owned(self, oid: bytes) -> None:
        """Force owner classification (refs this process created)."""
        with self._lock:
            self._owner_of[oid] = self._self_id

    # raylint: applier-only
    def forget(self, oids) -> None:
        """Explicit free(): drop all bookkeeping so the instances still
        alive cannot emit retractions for an entry already gone."""
        with self._lock:
            for oid in oids:
                self._counts.pop(oid, None)
                self._owner_of.pop(oid, None)
                self._advertised.discard(oid)
                self._borrows.pop(oid, None)
                self._dirty.discard(oid)
                self._zeroed.discard(oid)

    # ---------------------------------------------------- borrow authority

    # raylint: applier-only
    def apply_borrow_update(self, borrower: bytes, add, remove) -> None:
        """Head-relayed borrow edges for objects this process owns."""
        requeue = False
        with self._lock:
            if add and borrower in self._dead_borrowers:
                # The relay lost a race with the borrower_died sweep
                # (delayed/reordered delivery): adding now would pin the
                # object on an edge nothing will ever retract.
                self.stats["stale_borrow_adds"] += len(add)
                add = ()
            for oid in add or ():
                self._borrows.setdefault(oid, set()).add(borrower)
            for oid in remove or ():
                s = self._borrows.get(oid)
                if s is None:
                    continue
                s.discard(borrower)
                if not s:
                    del self._borrows[oid]
                    if (
                        self._counts.get(oid, 0) <= 0
                        and oid in self._advertised
                    ):
                        # Last borrower gone after our count drained:
                        # the release can go out now.
                        if not self._dirty:
                            self._wake.set()
                        self._dirty.add(oid)
                        requeue = True
        if requeue:
            self._ensure_flusher()

    # raylint: applier-only
    def on_reconnect(self) -> Dict[bytes, List[bytes]]:
        """The head restarted and this client re-registered on a fresh
        connection. Three things must replay (the head's per-conn
        sequencer numbers from 1 again and its object soft state is
        being rebuilt from bearers of truth):

        - unacked ref_flush batches renumber 1..k in their original
          order and retransmit immediately (the old numbering would
          read as a permanent gap to the new sequencer);
        - live borrowed/fallback refs are marked un-advertised so the
          next flush re-sends their badd/add edges;
        - owned refs (silent while alive by design) are returned as a
          reconcile payload — ``{oid: [borrower, ...]}`` — for the
          client to re-advertise into the head's recovery window.
        """
        owned: Dict[bytes, List[bytes]] = {}
        with self._lock:
            self._maybe_renumber_locked()
            for oid, n in self._counts.items():
                if n <= 0:
                    continue
                owner = self._owner_of.get(oid, b"")
                if owner == self._self_id:
                    if oid in self._advertised:
                        owned[oid] = sorted(self._borrows.get(oid, ()))
                else:
                    # Borrowed / head-fallback: re-advertise through the
                    # normal flush path.
                    self._advertised.discard(oid)
                    self._dirty.add(oid)
            # Owned oids kept alive only by remote borrowers (local
            # count drained): still ours to re-advertise.
            for oid, bs in self._borrows.items():
                if (
                    oid not in owned
                    and self._owner_of.get(oid) == self._self_id
                    and oid in self._advertised
                ):
                    owned[oid] = sorted(bs)
            if self._dirty or self._unacked:
                self._wake.set()
        self._ensure_flusher()
        return owned

    # raylint: applier-only
    def sweep_borrower(self, borrower: bytes) -> None:
        """A borrowing process died without retracting its borrows."""
        requeue = False
        with self._lock:
            self._dead_borrowers[borrower] = None
            while len(self._dead_borrowers) > DEAD_BORROWER_CAP:
                self._dead_borrowers.popitem(last=False)
            for oid in list(self._borrows):
                s = self._borrows[oid]
                s.discard(borrower)
                if not s:
                    del self._borrows[oid]
                    if (
                        self._counts.get(oid, 0) <= 0
                        and oid in self._advertised
                    ):
                        if not self._dirty:
                            self._wake.set()
                        self._dirty.add(oid)
                        requeue = True
        if requeue:
            self._ensure_flusher()

    # ------------------------------------------------------------- flushing

    # raylint: applier-only
    def _maybe_renumber_locked(self) -> None:
        """Caller holds self._lock. Renumber unacked batches 1..k
        (original order, due immediately) when the client moved to a
        new connection — see _gen_seen."""
        client = self._client()
        gen = getattr(client, "_conn_gen", 0) if client is not None else 0
        if gen == self._gen_seen:
            return
        self._gen_seen = gen
        old = list(self._unacked.values())
        self._unacked.clear()
        self._seq = 0
        for rec in old:
            self._seq += 1
            rec[0]["seq"] = self._seq
            rec[1] = 0.0  # due immediately
            rec[2] = 1  # fresh head: reset the attempt budget
            self._unacked[self._seq] = rec

    def _ensure_flusher(self):
        if self._flusher is None and not self._stopped:
            self._flusher = threading.Thread(
                target=self._flush_loop, name="ref-flusher", daemon=True
            )
            self._flusher.start()

    def _flush_loop(self):
        # Park while clean: an idle process's tracker must cost zero
        # wakeups. incr/decr arm the event on the empty->dirty edge;
        # the interval sleep then batches the burst. With unacked
        # batches outstanding the park is bounded so retransmits run
        # even when no new edges arrive.
        while not self._stopped:
            if self._unacked:
                self._wake.wait(RETRANSMIT_S / 2)
            else:
                self._wake.wait()
            if self._stopped:
                return
            time.sleep(FLUSH_INTERVAL_S)
            self._wake.clear()
            client = self._client()
            if client is None:
                return
            if client.conn.closed:
                # Head connection down. If a failover reconnect may
                # still land, stay alive — the unacked batches and the
                # reconcile re-advertisement need this thread after the
                # swap. Otherwise the session is over.
                if client.conn_failover_pending():
                    self._wake.set()
                    time.sleep(FLUSH_INTERVAL_S)
                    continue
                return
            self.flush(client)

    # raylint: applier-only
    def _classify(
        self
    ) -> Tuple[List[bytes], List[Tuple[bytes, bytes]],
               List[Tuple[bytes, bytes]], List[bytes], List[bytes],
               Set[bytes]]:
        """Net edge transitions for the dirty set. Caller holds the
        lock. Returns (release, badd, bdel, add, remove, zeroed)."""
        release: List[bytes] = []
        badd: List[Tuple[bytes, bytes]] = []
        bdel: List[Tuple[bytes, bytes]] = []
        add: List[bytes] = []
        remove: List[bytes] = []
        dirty, self._dirty = self._dirty, set()
        for oid in dirty:
            n = self._counts.get(oid, 0)
            owner = self._owner_of.get(oid, b"")
            owned = owner == self._self_id
            if n > 0:
                # Alive. Owned oids cost nothing — the head entry's
                # lifetime is governed solely by our eventual release.
                if owned:
                    continue
                if oid in self._advertised:
                    continue
                self._advertised.add(oid)
                if owner:
                    badd.append((owner, oid))
                else:
                    add.append(oid)
                continue
            # Drained locally.
            if owned:
                if self._borrows.get(oid):
                    # Remote borrowers keep the object alive; the
                    # borrow-drain path re-dirties this oid.
                    continue
                if oid in self._advertised:
                    self._advertised.discard(oid)
                    release.append(oid)
                # Never-advertised owned oids (flapped within one
                # window before submission registered) send nothing.
                self._owner_of.pop(oid, None)
                self._borrows.pop(oid, None)
            elif owner:
                if oid in self._advertised:
                    self._advertised.discard(oid)
                    bdel.append((owner, oid))
                self._owner_of.pop(oid, None)
            else:
                if oid in self._advertised:
                    self._advertised.discard(oid)
                    remove.append(oid)
                self._owner_of.pop(oid, None)
        return release, badd, bdel, add, remove, dirty

    # raylint: applier-only
    def flush(self, client) -> None:
        """Send the net ownership-edge transitions since the last
        flush (idempotent set semantics server-side, so transient
        1->0->1 flaps are safe)."""
        with self._lock:
            self._maybe_renumber_locked()
            if not self._dirty and not self._zeroed:
                pending_ack = bool(self._unacked)
                if not pending_ack:
                    return
                release = badd = bdel = add = remove = ()
                zeroed = ()
            else:
                release, badd, bdel, add, remove, _ = self._classify()
                zeroed, self._zeroed = self._zeroed, set()
        if not (release or badd or bdel or add or remove or zeroed):
            # Nothing new this window: just service retransmits.
            self._retransmit_due(client)
            return
        if zeroed:
            for oid in zeroed:
                client._lineage.pop(oid, None)
            client._wait_prune(zeroed)
        if not (release or badd or bdel or add or remove):
            return
        self.stats["flushes"] += 1
        self.stats["releases"] += len(release)
        self.stats["badd"] += len(badd)
        self.stats["bdel"] += len(bdel)
        self.stats["fallback_adds"] += len(add)
        self.stats["fallback_removes"] += len(remove)
        if _events.enabled():
            _events.record(
                _events.REFS, self._self_id.hex()[:12], "REF_FLUSH",
                {
                    "release": len(release), "badd": len(badd),
                    "bdel": len(bdel), "fallback": len(add) + len(remove),
                },
            )
        from ..protocol import ConnectionLost

        msg = {"type": "ref_flush", "client": self._self_id}
        if release:
            msg["release"] = release
        if badd:
            msg["badd"] = badd
        if bdel:
            msg["bdel"] = bdel
        if add:
            msg["add"] = add
        if remove:
            msg["remove"] = remove
        with self._lock:
            self._seq += 1
            msg["seq"] = self._seq
            # [msg, sent_at, attempts] — retained until the head acks.
            self._unacked[msg["seq"]] = [msg, time.monotonic(), 1]
        # Chaos kill point: "owner killed between SEAL and REF_FLUSH" —
        # the edges above are classified (and lineage dropped) but the
        # batch never reaches the head.
        _chaos.kill_point("owner.pre_ref_flush")
        try:
            # raylint: disable=raw-send-on-gcs-path -- this IS the at-least-once layer: the batch is retained in _unacked above and retransmits until the head acks
            client.conn.send(msg)
        except ConnectionLost:
            # The batch stays in _unacked; it retransmits on the next
            # connection if a failover lands (the send was already
            # at-least-once, so conn loss is just a longer gap).
            if not client.conn_failover_pending():
                self._stopped = True
            return
        self._retransmit_due(client)

    # raylint: applier-only
    def ack(self, seq: int) -> None:
        """Head acknowledged a ref_flush batch (delivered to its
        per-conn sequencer; idempotent application from there)."""
        with self._lock:
            self._unacked.pop(seq, None)

    # raylint: applier-only
    def _retransmit_due(self, client) -> None:
        """Resend unacked batches past the retransmit age; bounded
        attempts, lost batches counted — never silent."""
        now = time.monotonic()
        resend: List[dict] = []
        with self._lock:
            for seq, rec in list(self._unacked.items()):
                if now - rec[1] < RETRANSMIT_S:
                    break  # OrderedDict: the rest are younger
                if rec[2] >= RETRANSMIT_MAX:
                    del self._unacked[seq]
                    self.stats["lost_batches"] += 1
                    continue
                rec[1] = now
                rec[2] += 1
                resend.append(rec[0])
        if not resend:
            return
        from ..protocol import ConnectionLost

        self.stats["retransmits"] += len(resend)
        if _events.enabled():
            _events.record(
                _events.REFS, self._self_id.hex()[:12], "REF_REFLUSH",
                {"batches": len(resend)},
            )
        try:
            for m in resend:
                client.conn.send(m)
        except ConnectionLost:
            if not client.conn_failover_pending():
                self._stopped = True

    def stop(self):
        self._stopped = True
        self._wake.set()
