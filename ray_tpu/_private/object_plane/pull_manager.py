"""Admission-controlled pull manager: the node-level front of the
object transfer plane.

Reference: src/ray/object_manager/pull_manager.h:52 — pull requests are
prioritized get > wait > task-argument (FIFO within a class) and only
activate while their total object bytes fit an in-flight budget; a
completed, failed, or cancelled pull releases its budget and activates
the next queued request. That admission control is what keeps a bulk
broadcast (a learner fanning weights out to hundreds of rollout actors)
from starving concurrent small ``ray.get``\\ s: the broadcast's chunk
train queues object-by-object while gets jump ahead the moment budget
frees.

This manager fronts :class:`~..object_transfer.ObjectFetcher` in every
process that pulls (drivers and workers — each process is its own
admission domain over the shared node pool):

- requests enter a priority queue keyed ``(class, seq)``;
- a request activates only while ``in_flight_bytes + size`` fits the
  effective budget (``pull_in_flight_bytes``, default a quarter of the
  node pool), **demoted** to the pool's current free space when the
  store shrinks under spill pressure so pulls don't land on a pool the
  spill rung is actively draining;
- one oversized request may run alone (liveness: an object larger than
  the whole budget must still be fetchable) — flagged ``solo`` in its
  activation event;
- concurrent pulls of one object dedup here: followers ride the active
  leader without charging budget;
- ``cancel`` (ref-drop, explicit free) removes queued requests and
  frees their budget share immediately.

Every transition records a REFS flight-recorder event (PULL_QUEUED /
PULL_ACTIVATE / PULL_DONE / PULL_CANCEL) — the pressure_soak scenario
asserts the budget invariant straight from those events — and feeds
Prometheus gauges (per-class queue depth, in-flight bytes).
"""
from __future__ import annotations

import heapq
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from .. import events as _events

#: Priority classes, highest first (reference: pull_manager.h). The
#: wait class is currently RESERVED: ray.wait in this runtime is
#: push-based readiness and never fetches object data, so no product
#: path runs at PULL_WAIT yet — it exists to mirror the reference's
#: ordering and for a future fetch_local wait.
PULL_GET, PULL_WAIT, PULL_TASK_ARGS = 0, 1, 2
CLASS_NAMES = {PULL_GET: "get", PULL_WAIT: "wait", PULL_TASK_ARGS: "task_args"}

#: Request states.
_QUEUED, _ACTIVE, _CANCELLED, _TIMED_OUT = range(4)

# Per-thread pull class (set by the worker runtime around task-argument
# resolution — same idiom as events.set_task_context). Thread-local,
# not a contextvar: the gets that pull run on the thread resolving the
# args.
_ctx = threading.local()


@contextmanager
def pull_class(cls: int):
    """Scope the calling thread's pulls to a priority class."""
    prev = getattr(_ctx, "pull_class", None)
    _ctx.pull_class = cls
    try:
        yield
    finally:
        _ctx.pull_class = prev


def current_pull_class() -> int:
    cls = getattr(_ctx, "pull_class", None)
    return PULL_GET if cls is None else cls


class _Request:
    __slots__ = ("oid", "size", "cls", "seq", "state", "charge")

    def __init__(self, oid: bytes, size: int, cls: int, seq: int):
        self.oid = oid
        self.size = size
        self.cls = cls
        self.seq = seq
        self.state = _QUEUED
        self.charge = max(int(size), 1)


class _ActivePull:
    """One in-flight object: the leader fetches, followers wait."""

    __slots__ = ("charge", "done", "ok", "t0")

    def __init__(self, charge: int):
        self.charge = charge
        self.done = threading.Event()
        self.ok = False
        self.t0 = time.monotonic()


class PullManager:
    def __init__(self, fetcher, store=None,
                 budget_bytes: Optional[int] = None):
        """``budget_bytes`` overrides the config/auto budget (tests);
        ``store`` supplies pool stats for the auto budget and the
        spill-pressure demotion."""
        self._fetcher = fetcher
        self._store = store
        self._budget_override = budget_bytes
        self._cond = threading.Condition()
        self._seq = 0
        #: Min-heap of (cls, seq, request) — FIFO within class.
        self._heap: List[tuple] = []
        self._queued_per_class: Dict[int, int] = {}
        self._active: Dict[bytes, _ActivePull] = {}
        self._in_flight_bytes = 0
        self._closed = False
        # Pool stats are a ctypes call; cache briefly so a get storm
        # doesn't pay one per admission decision.
        self._pool_cache = (0.0, 0, 0)  # (stamp, size, in_use)
        self._gauges = None

    # ------------------------------------------------------------- budget

    def effective_budget(self) -> int:
        """Current admission budget in bytes. The configured budget,
        demoted to the pool's free space while the store runs hot
        (spill pressure must drain the pool, not race new pulls into
        it) — floored at one transfer chunk so the plane always moves."""
        from ..object_transfer import CHUNK_BYTES
        from ..config import RayConfig

        base = self._budget_override
        if base is None:
            base = int(RayConfig.pull_in_flight_bytes)
        pool_size, in_use = self._pool_stats()
        if not base:
            base = max(4 * CHUNK_BYTES, pool_size // 4) if pool_size \
                else 256 << 20
        if pool_size:
            free = max(0, pool_size - in_use)
            return max(CHUNK_BYTES, min(base, free))
        return base

    def _pool_stats(self):
        pool = getattr(self._store, "_pool", None) if self._store else None
        if pool is None:
            return 0, 0
        now = time.monotonic()
        stamp, size, in_use = self._pool_cache
        if now - stamp < 0.05:
            return size, in_use
        try:
            st = pool.stats()
            size = st.get("pool_size") or st.get("arena_size") or 0
            in_use = st.get("bytes_in_use", 0)
        except Exception:  # noqa: BLE001 - store mid-close
            size, in_use = 0, 0
        self._pool_cache = (now, size, in_use)
        return size, in_use

    # -------------------------------------------------------------- stats

    def stats(self) -> Dict[str, int]:
        with self._cond:
            out = {
                "in_flight_bytes": self._in_flight_bytes,
                "active": len(self._active),
                "budget": self.effective_budget(),
            }
            for cls, name in CLASS_NAMES.items():
                out[f"queued_{name}"] = self._queued_per_class.get(cls, 0)
        return out

    def _update_gauges_locked(self) -> None:
        """Per-class queue depth + in-flight bytes Prometheus gauges
        (published through util.metrics' per-process KV flush). Lazy:
        processes that never pull pay nothing."""
        try:
            if self._gauges is None:
                from ...util.metrics import Gauge

                self._gauges = (
                    Gauge(
                        "ray_tpu_pull_queue_depth",
                        "queued pull requests by priority class",
                        tag_keys=("pull_class",),
                    ),
                    Gauge(
                        "ray_tpu_pull_in_flight_bytes",
                        "total bytes of admitted in-flight pulls",
                    ),
                )
            depth, in_flight = self._gauges
            for cls, name in CLASS_NAMES.items():
                depth.set(
                    self._queued_per_class.get(cls, 0),
                    {"pull_class": name},
                )
            in_flight.set(self._in_flight_bytes)
        except Exception:  # noqa: BLE001 - metrics must never break pulls
            self._gauges = None

    # --------------------------------------------------------------- pull

    def pull(self, oid, address: str, size: int = 0,
             priority: Optional[int] = None,
             timeout: Optional[float] = 60.0,
             resolve=None) -> bool:
        """Admission-gated fetch of ``oid`` from ``address`` into the
        local store. Blocks until the request activates (budget) and the
        underlying chunk pull finishes; False on cancellation, admission
        timeout, or fetch failure. ``timeout`` covers BOTH the queue
        wait and the fetch; None (a patient, deadline-less get) waits
        for admission indefinitely — being parked behind a saturated
        budget is a transient, not a loss — and gives the fetch itself
        the fetcher's usual 60s window. ``size`` is the directory's
        sealed size — the budget charge (0 = unknown, charged as 1
        byte). ``resolve`` (optional) re-leads a below-floor pull onto a
        fresh holder inside the one admitted attempt — the budget is
        charged once, never per re-lead (see ObjectFetcher.pull)."""
        key = oid.binary()
        deadline = None if timeout is None else time.monotonic() + timeout
        cls = current_pull_class() if priority is None else priority
        rec = _events.get_recorder()
        with self._cond:
            leader = self._active.get(key)
            if leader is None:
                req = self._enqueue_locked(key, size, cls, rec)
                while req.state == _QUEUED:
                    remaining = (
                        None if deadline is None
                        else deadline - time.monotonic()
                    )
                    if (
                        remaining is not None and remaining <= 0
                    ) or self._closed:
                        req.state = _TIMED_OUT
                        self._queued_per_class[cls] = max(
                            0, self._queued_per_class.get(cls, 0) - 1
                        )
                        self._update_gauges_locked()
                        return False
                    self._cond.wait(remaining)
                if req.state != _ACTIVE:
                    return False
                leader = self._active[key]
                is_leader = True
            else:
                is_leader = False
        if not is_leader:
            # Dedup: ride the active pull; no budget charge, no wire
            # traffic (reference: PullManager dedup of concurrent
            # requests for one object).
            leader.done.wait(
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            return self._store.contains(oid) if self._store else leader.ok
        ok = False
        try:
            ok = self._fetcher.pull(
                oid, address,
                timeout=(
                    60.0 if deadline is None
                    else max(0.1, deadline - time.monotonic())
                ),
                resolve=resolve,
            )
        finally:
            self._release(key, leader, ok, rec)
        return ok

    def _enqueue_locked(self, key: bytes, size: int, cls: int,
                        rec) -> _Request:
        self._seq += 1
        req = _Request(key, size, cls, self._seq)
        heapq.heappush(self._heap, (cls, req.seq, req))
        self._queued_per_class[cls] = self._queued_per_class.get(cls, 0) + 1
        if rec.enabled:
            rec.record(
                _events.REFS, _hex12(key), "PULL_QUEUED",
                {
                    "cls": CLASS_NAMES.get(cls, cls), "bytes": size,
                    "depth": len(self._heap),
                },
            )
        self._maybe_activate_locked(rec)
        return req

    def _release(self, key: bytes, active: _ActivePull, ok: bool,
                 rec) -> None:
        with self._cond:
            if self._active.get(key) is active:
                del self._active[key]
            self._in_flight_bytes -= active.charge
            active.ok = ok
            active.done.set()
            if rec.enabled:
                rec.record(
                    _events.REFS, _hex12(key), "PULL_DONE",
                    {
                        "ok": ok, "in_flight": self._in_flight_bytes,
                        "seconds": round(time.monotonic() - active.t0, 6),
                    },
                )
            self._maybe_activate_locked(rec)
            self._cond.notify_all()

    def _maybe_activate_locked(self, rec) -> None:
        budget = self.effective_budget()
        while self._heap:
            cls, _seq, req = self._heap[0]
            if req.state != _QUEUED:
                heapq.heappop(self._heap)  # cancelled/timed out: discard
                continue
            if req.oid in self._active:
                # An earlier request for the same object is mid-flight:
                # this one resolves as a follower once it completes —
                # re-queue behind the release (cheap: the release's
                # activation pass re-examines it).
                break
            solo = not self._active
            if not solo and self._in_flight_bytes + req.charge > budget:
                break  # head-of-line waits for budget; FIFO within class
            heapq.heappop(self._heap)
            self._queued_per_class[cls] = max(
                0, self._queued_per_class.get(cls, 0) - 1
            )
            active = _ActivePull(req.charge)
            self._active[req.oid] = active
            self._in_flight_bytes += req.charge
            req.state = _ACTIVE
            if rec.enabled:
                attrs = {
                    "cls": CLASS_NAMES.get(cls, cls), "bytes": req.size,
                    "in_flight": self._in_flight_bytes, "budget": budget,
                }
                # Flag from the ADMISSION MODE, not the post-hoc
                # in_flight-vs-budget comparison: a buggy over-admission
                # of a non-solo request must show up as an unflagged
                # overrun (the pressure soak asserts exactly that), not
                # be self-excused by the overrun it caused.
                if solo and self._in_flight_bytes > budget:
                    attrs["solo"] = True  # oversize liveness admission
                rec.record(
                    _events.REFS, _hex12(req.oid), "PULL_ACTIVATE", attrs
                )
        self._update_gauges_locked()
        self._cond.notify_all()

    # ------------------------------------------------------------- cancel

    def cancel(self, oid_bytes: bytes) -> int:
        """Drop queued pulls for an object whose last ref died; their
        budget share frees immediately (active pulls run out — their
        release frees budget the normal way). Returns requests
        cancelled."""
        rec = _events.get_recorder()
        n = 0
        with self._cond:
            for _cls, _seq, req in self._heap:
                if req.oid == oid_bytes and req.state == _QUEUED:
                    req.state = _CANCELLED
                    self._queued_per_class[req.cls] = max(
                        0, self._queued_per_class.get(req.cls, 0) - 1
                    )
                    n += 1
                    if rec.enabled:
                        rec.record(
                            _events.REFS, _hex12(oid_bytes), "PULL_CANCEL",
                            {"cls": CLASS_NAMES.get(req.cls, req.cls)},
                        )
            if n:
                self._maybe_activate_locked(rec)
                self._cond.notify_all()
        return n

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


def _hex12(key: bytes) -> str:
    return key.hex()[:12]
