"""Fork-server worker spawning (zygote).

Reference behavior: the worker pool keeps prestarted idle workers so a
task/actor never pays interpreter cold-start
(src/ray/raylet/worker_pool.cc StartWorkerProcess + prestart). On a
loaded node the cold start is the dominant cost of actor creation
(~0.5 s of CPU per python+ray import); this zygote pays it once and
then forks warm children in ~5 ms.

Protocol (newline-delimited JSON over the zygote's stdin/stdout):

  request:  {"env": {...overrides}, "log": "/path/worker.out"}
  response: {"pid": 12345} | {"error": "..."}

The zygote is kept strictly single-threaded so fork() is safe, and it
never connects to anything — a forked child owns only its inherited
module imports. Child bootstrap: new session, stdio redirected to the
worker log, env overrides applied, then worker_main.main().

Fork-shared randomness: ids.py registers an os.register_at_fork hook
re-seeding its per-process unique-id prefix — without it every forked
worker would mint colliding task/object ids.
"""
from __future__ import annotations

import json
import os
import signal
import sys


def _child(env: dict, log_path: str) -> None:
    os.setsid()
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    # stdio: control pipe must not leak into the worker.
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    os.close(devnull)
    out = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    os.dup2(out, 1)
    os.dup2(out, 2)
    os.close(out)
    os.environ.update(env)
    for k, v in list(env.items()):
        if v == "":
            os.environ.pop(k, None)
    # Line-buffer the redirected stdio like a fresh interpreter would.
    sys.stdout = os.fdopen(1, "w", buffering=1)
    sys.stderr = os.fdopen(2, "w", buffering=1)
    from . import worker_main

    try:
        worker_main.main()
    except SystemExit:
        raise
    except BaseException:  # noqa: BLE001
        import traceback

        traceback.print_exc()
        os._exit(1)
    os._exit(0)


def main() -> None:
    # Children are reaped automatically; the zygote never waits on them
    # (their lifecycle is tracked by the control plane via pid).
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)
    # Warm the expensive imports ONCE, before any fork. worker_main
    # pulls in the whole ray_tpu core (not jax — workers import that
    # lazily when a task needs it). The modules the BOOT path imports
    # lazily must also be warmed here: anything left out is re-imported
    # — and source-compiled — by every forked child, which was ~70% of
    # CoreClient.__init__ time in the boot profile.
    from . import worker_main  # noqa: F401
    from . import (  # noqa: F401
        native_store,
        object_store,
        object_transfer,
        ref_tracker,
        runtime_env,
        worker,
    )
    import ray_tpu  # noqa: F401  (public API: tasks resolve through it)

    stdin = sys.stdin
    stdout = sys.stdout
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            pid = os.fork()
        except Exception as e:  # noqa: BLE001
            stdout.write(json.dumps({"error": str(e)}) + "\n")
            stdout.flush()
            continue
        if pid == 0:
            _child(req.get("env", {}), req["log"])
            os._exit(0)  # unreachable
        stdout.write(json.dumps({"pid": pid}) + "\n")
        stdout.flush()


if __name__ == "__main__":
    main()
