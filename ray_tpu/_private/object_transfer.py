"""Node-to-node object transfer: chunked pull over the data plane.

Reference: src/ray/object_manager/object_manager.h:63,117 — the object
manager transfers objects between nodes in 5 MiB chunks over gRPC, with
a pull manager deduplicating concurrent requests. Here each node daemon
(and the head) runs an ObjectTransferServer over its local store;
consumers pull missing objects chunk-by-chunk and seal them into their
own node pool. Objects are immutable once sealed, so a pulled replica
is always coherent; dedup of concurrent pulls of the same object is
done consumer-side in ObjectFetcher.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from . import events as _events
from . import transport
from .ids import ObjectID
from .object_store import ObjectStore
from .protocol import ConnectionLost, PeerConn

CHUNK_BYTES = 4 << 20  # reference: object_manager_default_chunk_size (5 MiB)


class ObjectTransferServer:
    """Serves raw object bytes from the node-local store.

    One listener per node; any peer (another node's worker, the driver,
    a daemon) connects and issues pull_chunk requests:

        {"type": "pull_chunk", "object_id": bytes, "offset": int}
          -> {"ok": True, "data": bytes, "size": total_size}
    """

    def __init__(self, store: ObjectStore, address: str, authkey: bytes):
        self._store = store
        self._authkey = authkey
        self._listener = transport.make_listener(address, authkey)
        self.address = transport.listener_address(self._listener)
        self._peers = []
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="obj-transfer-accept", daemon=True
        )
        self._thread.start()

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                break
            holder = {}
            peer = PeerConn(
                conn,
                push_handler=lambda msg, h=holder: self._handle(h["peer"], msg),
                name="obj-transfer",
                autostart=False,
                handshake=lambda c: transport.server_handshake(
                    c, self._authkey,
                    tcp=transport.is_tcp_address(self.address),
                ),
            )
            holder["peer"] = peer
            self._peers.append(peer)
            peer.start()

    def _handle(self, peer: PeerConn, msg):
        if msg.get("type") != "pull_chunk":
            if "req_id" in msg:
                peer.reply(msg, ok=False, error="unknown message")
            return
        oid = ObjectID(msg["object_id"])
        offset = msg.get("offset", 0)
        try:
            raw = self._store.get_raw(oid)
        except Exception as e:  # noqa: BLE001
            peer.reply(msg, ok=False, error=f"{type(e).__name__}: {e}")
            return
        if raw is None:
            # Restore rung: the object may have been spilled to disk on
            # this node; serve the file so cross-node pulls of spilled
            # objects still work (reference: spilled-object restore,
            # local_object_manager.h:100-110).
            import os

            from .object_store import spill_path

            spill_dir = os.environ.get("RAY_TPU_SPILL_DIR", "")
            path = spill_path(spill_dir, oid) if spill_dir else ""
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(CHUNK_BYTES)
                    size = os.path.getsize(path)
                peer.reply(msg, ok=True, data=data, size=size)
            except OSError:
                peer.reply(msg, ok=False, error="object not found")
            return
        try:
            size = len(raw)
            data = bytes(raw[offset : offset + CHUNK_BYTES])
            peer.reply(msg, ok=True, data=data, size=size)
        finally:
            self._store.release_raw(oid)

    def shutdown(self):
        self._shutdown = True
        try:
            self._listener.close()
        except Exception:  # noqa: BLE001
            pass
        for p in self._peers:
            p.close()


class ObjectFetcher:
    """Pulls remote objects into the local store (consumer side).

    Connections to remote transfer servers are cached per address;
    concurrent pulls of the same object are deduplicated so the chunks
    cross the wire once (reference: PullManager dedup, pull_manager.h:52).
    """

    def __init__(self, store: ObjectStore, authkey: bytes):
        self._store = store
        self._authkey = authkey
        self._conns: Dict[str, PeerConn] = {}
        self._lock = threading.Lock()
        self._inflight: Dict[bytes, threading.Event] = {}

    def _conn_for(self, address: str) -> PeerConn:
        with self._lock:
            peer = self._conns.get(address)
            if peer is not None and not peer.closed:
                return peer
        raw = transport.connect(address, self._authkey)
        peer = PeerConn(raw, push_handler=lambda m: None, name="obj-fetch")
        with self._lock:
            existing = self._conns.get(address)
            if existing is not None and not existing.closed:
                peer.close()
                return existing
            self._conns[address] = peer
        return peer

    def pull(self, oid: ObjectID, address: str, timeout: Optional[float] = 60.0) -> bool:
        """Fetch the object from `address` into the local store.

        Returns True when the object is locally readable afterwards."""
        key = oid.binary()
        with self._lock:
            ev = self._inflight.get(key)
            if ev is None:
                self._inflight[key] = ev = threading.Event()
                leader = True
            else:
                leader = False
        if not leader:
            ev.wait(timeout)
            return self._store.contains(oid)
        try:
            if self._store.contains(oid):
                return True
            _rec = _events.get_recorder()
            if not _rec.enabled:
                return self._pull_chunks(oid, address, timeout)[0]
            t0 = time.time()
            ok, size = self._pull_chunks(oid, address, timeout)
            _rec.record(
                _events.TRANSFER, oid.hex(), "PULL",
                {
                    "ok": ok, "seconds": time.time() - t0,
                    "from": address, "bytes": size,
                },
            )
            return ok
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()

    def _pull_chunks(
        self, oid: ObjectID, address: str, timeout
    ) -> Tuple[bool, int]:
        """Returns (locally readable, object size in bytes)."""
        peer = self._conn_for(address)
        first = peer.request(
            {"type": "pull_chunk", "object_id": oid.binary(), "offset": 0},
            timeout=timeout,
        )
        if not first.get("ok"):
            return False, 0
        size = first["size"]
        view = self._store.create_raw(oid, size)
        if view is None:
            # Local store can't hold it (exists already counts as success).
            return self._store.contains(oid), size
        try:
            data = first["data"]
            view[: len(data)] = data
            offset = len(data)
            while offset < size:
                reply = peer.request(
                    {
                        "type": "pull_chunk",
                        "object_id": oid.binary(),
                        "offset": offset,
                    },
                    timeout=timeout,
                )
                if not reply.get("ok"):
                    self._store.abort_raw(oid)
                    return False, size
                chunk = reply["data"]
                view[offset : offset + len(chunk)] = chunk
                offset += len(chunk)
        except (ConnectionLost, TimeoutError):
            self._store.abort_raw(oid)
            return False, size
        finally:
            del view
        self._store.seal_raw(oid)
        return True, size

    def close(self):
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
