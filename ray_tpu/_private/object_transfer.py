"""Node-to-node object transfer: chunked pull over the data plane.

Reference: src/ray/object_manager/object_manager.h:63,117 — the object
manager transfers objects between nodes in 5 MiB chunks over gRPC, with
a pull manager deduplicating concurrent requests. Here each node daemon
(and the head) runs an ObjectTransferServer over its local store;
consumers pull missing objects chunk-by-chunk and seal them into their
own node pool. Objects are immutable once sealed, so a pulled replica
is always coherent; dedup of concurrent pulls of the same object is
done consumer-side in ObjectFetcher.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from . import chaos as _chaos
from . import events as _events
from . import transport
from .config import RayConfig
from .ids import ObjectID
from .object_store import ObjectStore
from .protocol import ConnectionLost, PeerConn

CHUNK_BYTES = 4 << 20  # reference: object_manager_default_chunk_size (5 MiB)
#: Per-attempt ceiling on one chunk request: a dropped request surfaces
#: as a timeout this fast and the pull retries with backoff instead of
#: burning the whole pull deadline waiting on one lost frame.
ATTEMPT_TIMEOUT_S = 10.0

#: Chaos role of the data plane: transfer-server conns (both ends) tag
#: their peer with this so a `throttle:raylet<->transfer=...` rule slows
#: chunk traffic without touching the control plane — a gray failure
#: (heartbeats keep flowing), not a partition.
TRANSFER_ROLE = "transfer"


class SlowProviderError(Exception):
    """One pull attempt measured below the hedged-pull throughput floor
    (pull_relead_floor_bytes_s) past the grace window: the consumer
    should re-lead onto a re-resolved holder instead of waiting out the
    straggler."""

    def __init__(self, size: int, bytes_per_s: float):
        super().__init__(f"pull below floor: {bytes_per_s:.0f} B/s")
        self.size = size
        self.bytes_per_s = bytes_per_s


def _host_id() -> str:
    """Identity of this physical host, stable across processes.

    boot_id distinguishes machines sharing an IP namespace; two
    containers on one kernel share it but not /dev/shm, which is fine —
    the shm attach just fails and the pull falls back to chunked TCP.
    """
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        import socket

        return socket.gethostname()


class ObjectTransferServer:
    """Serves raw object bytes from the node-local store.

    One listener per node; any peer (another node's worker, the driver,
    a daemon) connects and issues pull_chunk requests:

        {"type": "pull_chunk", "object_id": bytes, "offset": int}
          -> {"ok": True, "data": bytes, "size": total_size}
    """

    def __init__(self, store: ObjectStore, address: str, authkey: bytes):
        self._store = store
        self._authkey = authkey
        self._listener = transport.make_listener(address, authkey)
        self.address = transport.listener_address(self._listener)
        self._peers = []
        self._shutdown = False
        # Zombie fence (membership protocol): once the owning raylet
        # learns it was declared dead, its segment adverts are stale —
        # shm_locate must stop naming the pool so no NEW pull can map
        # a segment the fleet already considers gone. Chunk pulls keep
        # working: they copy bytes, they never hand out the mapping.
        self.shm_fenced = False
        # Spill files already checksum-verified by this server, keyed
        # (path, size, mtime_ns): spill files are immutable once
        # renamed into place, so one streaming CRC pass covers every
        # subsequent puller/retry instead of re-reading the whole file
        # per offset-0 request. Bounded FIFO.
        self._verified_spills: "Dict[Tuple[str, int, int], bool]" = {}
        self._thread = threading.Thread(
            target=self._accept_loop, name="obj-transfer-accept", daemon=True
        )
        self._thread.start()

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                break
            holder = {}
            peer = PeerConn(
                conn,
                push_handler=lambda msg, h=holder: self._handle(h["peer"], msg),
                name="obj-transfer",
                autostart=False,
                handshake=lambda c: transport.server_handshake(
                    c, self._authkey,
                    tcp=transport.is_tcp_address(self.address),
                ),
            )
            holder["peer"] = peer
            peer.peer_role = TRANSFER_ROLE
            self._peers.append(peer)
            peer.start()

    def _handle(self, peer: PeerConn, msg):
        if msg.get("type") == "shm_locate":
            # Same-host shortcut handshake: name the node segment that
            # holds the object so a consumer on THIS host can map it and
            # copy once — zero bytes over the socket. A consumer on
            # another host sees the host-id mismatch and pulls chunks.
            if self.shm_fenced:
                peer.reply(msg, ok=False, fenced=True,
                           error="provider fenced", host=_host_id())
                return
            src = self._store.shm_source(ObjectID(msg["object_id"]))
            if src is None:
                peer.reply(msg, ok=False, error="no shm source",
                           host=_host_id())
            else:
                peer.reply(msg, ok=True, host=_host_id(),
                           pool=src[0], size=src[1])
            return
        if msg.get("type") != "pull_chunk":
            if "req_id" in msg:
                peer.reply(msg, ok=False, error="unknown message")
            return
        oid = ObjectID(msg["object_id"])
        offset = msg.get("offset", 0)
        try:
            raw = self._store.get_raw(oid)
        except Exception as e:  # noqa: BLE001
            peer.reply(msg, ok=False, error=f"{type(e).__name__}: {e}")
            return
        if raw is None:
            # Restore rung: the object may have been spilled to disk on
            # this node; serve the file so cross-node pulls of spilled
            # objects still work (reference: spilled-object restore,
            # local_object_manager.h:100-110). The header is validated
            # before a single byte leaves — a truncated or corrupt spill
            # file answers "not found" (the consumer's get resolves
            # through lineage reconstruction), never garbage.
            import os

            from .object_store import (
                SPILL_HEADER_BYTES, SpillCorruptionError, spill_file_meta,
                spill_path, verify_spill_file,
            )

            spill_dir = os.environ.get("RAY_TPU_SPILL_DIR", "")
            path = spill_path(spill_dir, oid) if spill_dir else ""
            try:
                if offset == 0:
                    # Full streaming checksum once per FILE (no
                    # payload-sized allocation): immutable spill files
                    # verify on their first offset-0 request and later
                    # pulls/retries hit the verified cache; non-zero
                    # offsets re-check only the cheap size header.
                    st = os.stat(path)
                    ck = (path, st.st_size, st.st_mtime_ns)
                    if ck in self._verified_spills:
                        size, _crc = spill_file_meta(path)
                    else:
                        size = verify_spill_file(path)
                        if len(self._verified_spills) >= 1024:
                            self._verified_spills.pop(
                                next(iter(self._verified_spills))
                            )
                        self._verified_spills[ck] = True
                else:
                    size, _crc = spill_file_meta(path)
                with open(path, "rb") as f:
                    f.seek(SPILL_HEADER_BYTES + offset)
                    data = f.read(CHUNK_BYTES)
                peer.reply(msg, ok=True, data=data, size=size)
            except SpillCorruptionError as e:
                peer.reply(msg, ok=False, error=f"spill corrupt: {e}")
            except OSError:
                peer.reply(msg, ok=False, error="object not found")
            return
        try:
            size = len(raw)
            data = bytes(raw[offset : offset + CHUNK_BYTES])
            peer.reply(msg, ok=True, data=data, size=size)
        finally:
            self._store.release_raw(oid)

    def fence_shm(self):
        """Permanently stop answering shm_locate with this node's pool
        (zombie self-fence). Not reversible: the re-registered
        incarnation runs on per-object segments (or a fresh daemon)."""
        self.shm_fenced = True

    def shutdown(self):
        self._shutdown = True
        try:
            self._listener.close()
        except Exception:  # noqa: BLE001
            pass
        for p in self._peers:
            p.close()


class ObjectFetcher:
    """Pulls remote objects into the local store (consumer side).

    Connections to remote transfer servers are cached per address;
    concurrent pulls of the same object are deduplicated so the chunks
    cross the wire once (reference: PullManager dedup, pull_manager.h:52).
    """

    def __init__(self, store: ObjectStore, authkey: bytes):
        self._store = store
        self._authkey = authkey
        self._conns: Dict[str, PeerConn] = {}
        self._lock = threading.Lock()
        self._inflight: Dict[bytes, threading.Event] = {}
        # Same-host shortcut state: provider address -> its host id
        # (learned on the first shm_locate; remote hosts are never asked
        # again), and provider pool name -> our read-only attachment.
        self._peer_hosts: Dict[str, str] = {}
        self._peer_pools: Dict[str, object] = {}
        # Counted-never-silent shortcut faults (attach/copy/teardown
        # races degrade to the TCP pull, but the count must exist).
        self._shm_pull_failed = 0

    def _conn_for(self, address: str) -> PeerConn:
        with self._lock:
            peer = self._conns.get(address)
            if peer is not None and not peer.closed:
                return peer
        raw = transport.connect(address, self._authkey)
        peer = PeerConn(raw, push_handler=lambda m: None, name="obj-fetch")
        peer.peer_role = TRANSFER_ROLE
        with self._lock:
            existing = self._conns.get(address)
            if existing is not None and not existing.closed:
                peer.close()
                return existing
            self._conns[address] = peer
        return peer

    def _drop_conn(self, address: str) -> None:
        """Forget a cached transfer conn (failed attempt: reconnect)."""
        with self._lock:
            peer = self._conns.pop(address, None)
        if peer is not None:
            peer.close()

    def pull(self, oid: ObjectID, address: str, timeout: Optional[float] = 60.0,
             resolve=None) -> bool:
        """Fetch the object from `address` into the local store.

        Transient failures (lost/timed-out chunk request, dropped conn)
        retry with exponential backoff + jitter until ``timeout``
        (reference: PullManager retries pulls on a timer,
        pull_manager.h); a definitive "object not found" fails fast so
        directory re-lookup/reconstruction can run instead.

        ``resolve``, when given, is called with the current (slow)
        provider address after an attempt falls below the hedged-pull
        throughput floor; it returns a fresh address to re-lead onto
        (or None to stay). The re-lead happens INSIDE this one call, so
        an admission-controlled caller charges its byte budget once.

        Returns True when the object is locally readable afterwards."""
        key = oid.binary()
        with self._lock:
            ev = self._inflight.get(key)
            if ev is None:
                self._inflight[key] = ev = threading.Event()
                leader = True
            else:
                leader = False
        if not leader:
            ev.wait(timeout)
            return self._store.contains(oid)
        try:
            if self._store.contains(oid):
                return True
            _rec = _events.get_recorder()
            t0 = time.time()
            deadline = time.monotonic() + (timeout or 60.0)
            backoff = _chaos.Backoff(base_s=0.05, cap_s=2.0)
            ok, size, attempts = False, 0, 0
            # Providers already flagged slow: when the re-lead resolves
            # back to the same (sole) holder, the next attempt runs
            # with the floor DISABLED — a slow pull beats a livelock of
            # aborted attempts.
            slow_addrs: set = set()
            while True:
                attempts += 1
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    ok, size = self._try_shm_pull(
                        oid, address, min(remaining, ATTEMPT_TIMEOUT_S)
                    )
                    if ok:
                        break
                    ok, size, transient = self._pull_chunks(
                        oid, address, min(remaining, ATTEMPT_TIMEOUT_S),
                        floor_enabled=address not in slow_addrs,
                    )
                except SlowProviderError as slow:
                    slow_addrs.add(address)
                    # Hedged pull: this holder is a straggler, not dead.
                    # Re-lead onto a re-resolved holder immediately (no
                    # backoff — the bytes so far were arriving, just too
                    # slowly to wait out).
                    self._drop_conn(address)
                    if _rec.enabled:
                        _rec.record(
                            _events.REFS, oid.hex(), "PULL_RELEAD",
                            {
                                "addr": address,
                                "bytes_s": round(slow.bytes_per_s),
                                "attempt": attempts,
                            },
                        )
                    if resolve is not None:
                        fresh = resolve(address)
                        if fresh:
                            address = fresh
                    continue
                except (ConnectionLost, OSError):
                    ok, size, transient = False, 0, True
                if ok or not transient:
                    break
                # Reconnect next attempt: the conn may be the casualty.
                self._drop_conn(address)
                if _rec.enabled:
                    _rec.record(
                        _events.TRANSFER, oid.hex(), "PULL_RETRY",
                        {"attempt": attempts, "from": address},
                    )
                delay = min(
                    backoff.next_delay(),
                    max(0.0, deadline - time.monotonic()),
                )
                if delay > 0:
                    time.sleep(delay)
            if _rec.enabled:
                _rec.record(
                    _events.TRANSFER, oid.hex(), "PULL",
                    {
                        "ok": ok, "seconds": time.time() - t0,
                        "from": address, "bytes": size,
                        "attempts": attempts,
                    },
                )
            return ok
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()

    def _try_shm_pull(self, oid: ObjectID, address: str, timeout) -> Tuple[bool, int]:
        """Same-host pull through the provider's node segment: map its
        pool by name and copy the payload once — zero socket bytes for
        the data plane, so an n-worker same-host broadcast is one copy
        per node instead of n socket round-trips of the full payload.
        Returns (pulled, size); any miss (remote host, pool-less
        provider, attach failure, raced eviction) falls back to the
        chunked TCP pull. Never raises."""
        import concurrent.futures

        if RayConfig.transfer_force_tcp:
            # Testing hook: the straggler soak throttles the chunked
            # data plane at the PeerConn boundary; the shm shortcut
            # moves zero socket bytes and would bypass it.
            return False, 0
        known = self._peer_hosts.get(address)
        me = _host_id()
        if known is not None and known != me:
            return False, 0  # provider is on another machine: TCP
        try:
            peer = self._conn_for(address)
            reply = peer.request(
                {"type": "shm_locate", "object_id": oid.binary()},
                timeout=timeout,
            )
        except (ConnectionLost, OSError, TimeoutError,
                concurrent.futures.TimeoutError):
            return False, 0
        host = reply.get("host")
        if host:
            self._peer_hosts[address] = host
        if host != me or not reply.get("ok"):
            return False, 0
        pool_name, size = reply["pool"], reply["size"]
        key = oid.binary()
        try:
            pool = self._peer_pools.get(pool_name)
            if pool is None:
                from .native_store import PoolStore, native_available

                if not native_available():
                    return False, 0
                pool = PoolStore(pool_name, create=False)
                self._peer_pools[pool_name] = pool
            src = pool.get(key)  # pins against provider-side delete
        except Exception:  # noqa: BLE001 - foreign /dev/shm namespace
            self._shm_pull_failed += 1
            self._peer_pools.pop(pool_name, None)
            self._peer_hosts[address] = f"!{host}"  # never retry attach
            return False, 0
        if src is None:
            return False, 0  # raced eviction/spill: TCP path re-resolves
        try:
            view = self._store.create_raw(oid, size)
            if view is None:
                return self._store.contains(oid), size
            try:
                view[:size] = src[:size]
                del view
            except Exception:  # noqa: BLE001 - reclaim the partial
                self._shm_pull_failed += 1
                del view
                self._store.abort_raw(oid)
                return False, 0
            self._store.seal_raw(oid)
        finally:
            del src
            try:
                pool.release(key)
            except Exception:  # noqa: BLE001 - pool torn down mid-copy
                self._shm_pull_failed += 1
                self._peer_pools.pop(pool_name, None)
        rec = _events.get_recorder()
        if rec.enabled:
            rec.record(
                _events.TRANSFER, oid.hex(), "SHM_PULL",
                {"from": address, "bytes": size, "pool": pool_name},
            )
        return True, size

    def _pull_chunks(
        self, oid: ObjectID, address: str, timeout, floor_enabled: bool = True
    ) -> Tuple[bool, int, bool]:
        """One pull attempt. Returns (locally readable, size,
        transient) — transient=True means a retry may succeed (timeout,
        lost conn); False is definitive (object not found). Raises
        SlowProviderError when ``floor_enabled`` and measured
        throughput stays under pull_relead_floor_bytes_s past the
        grace window."""
        import concurrent.futures

        peer = self._conn_for(address)
        # The attempt clock starts BEFORE the first chunk request: on a
        # starved link the first chunk is where the pacing time goes,
        # and anchoring after it would let a two-chunk object finish
        # the loop inside the grace window without ever measuring.
        t_attempt = time.monotonic()
        try:
            first = peer.request(
                {"type": "pull_chunk", "object_id": oid.binary(), "offset": 0},
                timeout=timeout,
            )
        except (TimeoutError, concurrent.futures.TimeoutError):
            return False, 0, True
        if not first.get("ok"):
            return False, 0, False
        size = first["size"]
        view = self._store.create_raw(oid, size)
        if view is None:
            # Local store can't hold it (exists already counts as success).
            return self._store.contains(oid), size, False
        floor = RayConfig.pull_relead_floor_bytes_s if floor_enabled else 0
        grace = RayConfig.pull_relead_grace_s
        try:
            data = first["data"]
            view[: len(data)] = data
            offset = len(data)
            while offset < size:
                elapsed = time.monotonic() - t_attempt
                if floor and elapsed > grace:
                    rate = offset / elapsed
                    if rate < floor:
                        # Straggling provider: abandon this attempt's
                        # partial bytes (reclaimed) and let the caller
                        # re-lead onto another holder.
                        self._store.abort_raw(oid)
                        raise SlowProviderError(size, rate)
                # Chaos: "kill node mid-pull" — a consumer dying with a
                # half-written unsealed replica (the abort path must
                # reclaim it, and the producer side must shrug).
                _chaos.kill_point("transfer.mid_pull")
                reply = peer.request(
                    {
                        "type": "pull_chunk",
                        "object_id": oid.binary(),
                        "offset": offset,
                    },
                    timeout=timeout,
                )
                if not reply.get("ok"):
                    self._store.abort_raw(oid)
                    return False, size, False
                chunk = reply["data"]
                view[offset : offset + len(chunk)] = chunk
                offset += len(chunk)
        except (ConnectionLost, TimeoutError,
                concurrent.futures.TimeoutError):
            self._store.abort_raw(oid)
            return False, size, True
        finally:
            del view
        self._store.seal_raw(oid)
        return True, size, False

    def close(self):
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
            pools = list(self._peer_pools.values())
            self._peer_pools.clear()
        for c in conns:
            c.close()
        for p in pools:
            try:
                p.close()  # detach only — the provider owns the segment
            except Exception:  # noqa: BLE001 - already destroyed
                self._shm_pull_failed += 1
