"""Per-node log monitor: tail worker log files, publish to the driver.

Reference: python/ray/_private/log_monitor.py (per-node tailer shipping
worker stdout/stderr to drivers) + ray_logging/__init__.py:259-294
(dedup of identical lines flooding from many workers). Each node — the
head and every raylet — runs one LogMonitor over its session logs dir;
new lines batch into control-plane messages, the GCS keeps a bounded
ring of recent lines for `ray-tpu logs`, and drivers that subscribed
get them pushed and printed with a ``(worker=... node=...)`` prefix.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Tuple

SCAN_INTERVAL_S = 0.25
# Dedup window: identical lines from different workers within this many
# seconds collapse into one line + a repeat counter.
DEDUP_WINDOW_S = 5.0
MAX_BATCH_LINES = 500

# Task-context marker a worker's _TaggedStream (worker_main.py) frames
# into its stdout: "\x1et=<task_id_hex>\x1e<line>". Lifted out of the
# line and into the worker tag here, so the dashboard log viewer can
# correlate a log line to its flight-recorder timeline row while the
# visible line stays untouched.
_TASK_MARK = "\x1et="


def _tag_line(tag: str, line: str):
    """(worker_tag, line) with any task marker folded into the tag."""
    if line.startswith(_TASK_MARK):
        end = line.find("\x1e", len(_TASK_MARK))
        if end > 0:
            tid = line[len(_TASK_MARK):end]
            return (f"{tag} task={tid[:12]}", line[end + 1:])
    return (tag, line)


class LogMonitor:
    def __init__(
        self,
        logs_dir: str,
        publish: Callable[[List[Tuple[str, str]]], None],
    ):
        self._dir = logs_dir
        self._publish = publish
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, bytes] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="log-monitor", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(SCAN_INTERVAL_S):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - keep tailing
                pass

    def poll_once(self):
        """One scan pass (exposed for tests and final flushes)."""
        if not os.path.isdir(self._dir):
            return
        entries: List[Tuple[str, str]] = []  # (worker_tag, line)
        for fname in sorted(os.listdir(self._dir)):
            if not (fname.startswith("worker-") and fname.endswith(".out")):
                continue
            path = os.path.join(self._dir, fname)
            tag = fname[len("worker-"):-len(".out")]
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(fname, 0)
            if size <= off:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read(min(size - off, 1 << 20))
            except OSError:
                continue
            self._offsets[fname] = off + len(data)
            data = self._partial.pop(fname, b"") + data
            *lines, tail = data.split(b"\n")
            if tail:
                self._partial[fname] = tail
            for raw in lines:
                line = raw.decode(errors="replace").rstrip("\r")
                if line:
                    entries.append(_tag_line(tag, line))
            if len(entries) >= MAX_BATCH_LINES:
                # Bound message size without losing lines (offsets only
                # cover bytes actually read): flush and keep scanning.
                self._publish(entries)
                entries = []
        if entries:
            self._publish(entries)


class LogDeduplicator:
    """Collapse identical lines arriving from many workers in a short
    window (reference: ray_logging dedup — '[repeated Nx across
    cluster]')."""

    def __init__(self, window_s: float = DEDUP_WINDOW_S):
        self._window = window_s
        self._seen: Dict[str, Tuple[float, int]] = {}

    def filter(self, entries: List[Tuple[str, str, str]]):
        """entries: (node, worker, line) -> entries to emit now."""
        now = time.time()
        out = []
        for node, worker, line in entries:
            first, count = self._seen.get(line, (0.0, 0))
            if now - first > self._window:
                if count > 1:
                    # Window expired with suppressed repeats: summarize
                    # them before emitting the fresh occurrence.
                    out.append(
                        (node, worker,
                         f"[repeated {count - 1}x across cluster] {line}")
                    )
                self._seen[line] = (now, 1)
                out.append((node, worker, line))
            else:
                self._seen[line] = (first, count + 1)
        # Opportunistic GC of old window entries.
        if len(self._seen) > 4096:
            cutoff = now - self._window
            self._seen = {
                k: v for k, v in self._seen.items() if v[0] >= cutoff
            }
        return out

    def flush_repeats(self):
        """Emit summaries for lines suppressed inside the window."""
        now = time.time()
        out = []
        for line, (first, count) in list(self._seen.items()):
            if count > 1 and now - first > self._window:
                out.append(("", "", f"[repeated {count - 1}x] {line}"))
                self._seen[line] = (first, 1)
        return out
