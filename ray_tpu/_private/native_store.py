"""ctypes bindings + pool-backed store over the C++ core.

The C++ library (native/store.cpp) owns allocation, the object table,
refcounts, and LRU eviction inside one shm pool; Python reads/writes
payloads through a zero-copy memoryview of the same mapping. Falls
back silently (native_available() False) if the library can't build.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "_native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libray_tpu_store.so")
_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "native",
    "store.cpp",
)

_lib = None
_lib_lock = threading.Lock()


def _build() -> bool:
    os.makedirs(_NATIVE_DIR, exist_ok=True)
    # Build to a private name, then atomically publish (same pattern as
    # fastpath._build): a concurrent builder in another cluster process
    # must never dlopen a half-written .so.
    tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            [
                "g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-Wall",
                "-o", tmp, _SRC, "-lpthread", "-lrt",
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return True
    except Exception:  # noqa: BLE001 - no toolchain → fallback store
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
        ):
            if not _build() and not os.path.exists(_LIB_PATH):
                return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.store_create.restype = ctypes.c_uint64
        lib.store_create.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
            ctypes.c_int32,
        ]
        lib.store_attach.restype = ctypes.c_uint64
        lib.store_attach.argtypes = [ctypes.c_char_p]
        lib.store_create_object.restype = ctypes.c_uint64
        lib.store_create_object.argtypes = [
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.store_seal.restype = ctypes.c_int32
        lib.store_seal.argtypes = [ctypes.c_uint64, ctypes.c_char_p]
        lib.store_get.restype = ctypes.c_int32
        lib.store_get.argtypes = [
            ctypes.c_uint64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.store_contains.restype = ctypes.c_int32
        lib.store_contains.argtypes = [ctypes.c_uint64, ctypes.c_char_p]
        lib.store_release.restype = ctypes.c_int32
        lib.store_release.argtypes = [ctypes.c_uint64, ctypes.c_char_p]
        lib.store_delete.restype = ctypes.c_int32
        lib.store_delete.argtypes = [ctypes.c_uint64, ctypes.c_char_p]
        lib.store_register.restype = ctypes.c_int32
        lib.store_register.argtypes = [ctypes.c_uint64, ctypes.c_int32]
        lib.store_sweep.restype = ctypes.c_int32
        lib.store_sweep.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.store_stats.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.store_sweep_stats.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.store_detach.argtypes = [ctypes.c_uint64]
        lib.store_destroy.restype = ctypes.c_int32
        lib.store_destroy.argtypes = [ctypes.c_char_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    if os.environ.get("RAY_TPU_NATIVE_STORE", "1") == "0":
        return False
    return get_lib() is not None


def default_pool_bytes() -> int:
    env = os.environ.get("RAY_TPU_POOL_SIZE")
    if env:
        return int(env)
    try:
        st = os.statvfs("/dev/shm")
        avail = st.f_bavail * st.f_frsize
    except OSError:
        avail = 2 << 30
    return max(64 << 20, min(4 << 30, int(avail * 0.3)))


class PoolStore:
    """One process's view of the node pool."""

    def __init__(self, name: str, create: bool, pool_bytes: Optional[int] = None,
                 max_objects: int = 65536, evict: bool = False):
        """evict=False (default): a full pool fails creates and callers
        fall back to per-object segments — nothing pins
        client-referenced objects across processes yet, so LRU eviction
        could free data a live ObjectRef still names."""
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self.name = name
        self._lib = lib
        if create:
            self._h = lib.store_create(
                name.encode(), pool_bytes or default_pool_bytes(), max_objects,
                1 if evict else 0,
            )
        else:
            self._h = lib.store_attach(name.encode())
        if not self._h:
            raise RuntimeError(
                f"store_{'create' if create else 'attach'}({name}) failed"
            )
        self._owner = create
        # Register in the pool's client registry so this process's refs
        # are sweepable if it dies uncleanly (SIGKILL). -1 (registry
        # full) degrades to unregistered: refcounts still correct while
        # alive, just not crash-sweepable.
        self.client_slot = lib.store_register(self._h, os.getpid())
        # Map the pool in Python for zero-copy payload access.
        from multiprocessing import resource_tracker, shared_memory

        self._shm = shared_memory.SharedMemory(name=name)
        try:
            resource_tracker.unregister(self._shm._name, "shared_memory")  # noqa: SLF001
        except Exception:  # noqa: BLE001
            pass
        self.buf = self._shm.buf

    # ------------------------------------------------------------ objects
    def create(self, object_id: bytes, size: int) -> Optional[memoryview]:
        """Returns a writable view of the payload, or None (full/exists)."""
        if not self._h:
            return None
        err = ctypes.c_int32(0)
        off = self._lib.store_create_object(
            self._h, object_id, size, ctypes.byref(err)
        )
        if off == 0:
            return None
        return self.buf[off : off + size]

    def seal(self, object_id: bytes) -> bool:
        return bool(self._h) and self._lib.store_seal(self._h, object_id) == 0

    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Read-side view; caller must release() when done with it."""
        if not self._h:
            return None
        off = ctypes.c_uint64(0)
        size = ctypes.c_uint64(0)
        rc = self._lib.store_get(
            self._h, object_id, ctypes.byref(off), ctypes.byref(size)
        )
        if rc != 0:
            return None
        return self.buf[off.value : off.value + size.value]

    def contains(self, object_id: bytes) -> bool:
        return bool(self._h) and self._lib.store_contains(self._h, object_id) == 1

    def release(self, object_id: bytes) -> None:
        if self._h:
            self._lib.store_release(self._h, object_id)

    def delete(self, object_id: bytes) -> None:
        if self._h:
            self._lib.store_delete(self._h, object_id)

    def stats(self) -> dict:
        # Detached-handle calls (e.g. a monitor thread racing shutdown)
        # must fail as exceptions, not native crashes.
        if not self._h:
            raise RuntimeError("store closed")
        out = (ctypes.c_uint64 * 8)()
        self._lib.store_stats(self._h, out)
        return {
            "arena_size": out[0],
            "bytes_in_use": out[1],
            "num_objects": out[2],
            "num_evictions": out[3],
            "bytes_evicted": out[4],
            "pool_size": out[5],
            "max_objects": out[6],
            "ledger_overflows": out[7],
        }

    def sweep(self) -> dict:
        """Drop dead clients' refs (disconnect sweep). Reclaims a
        SIGKILLed creator's unsealed partials — they never seal — and
        completes deferred deletes its refs were pinning."""
        if not self._h:
            raise RuntimeError("store closed")
        out = (ctypes.c_uint64 * 4)()
        self._lib.store_sweep(self._h, out)
        return {
            "clients_swept": out[0],
            "refs_dropped": out[1],
            "partials_reclaimed": out[2],
            "ledger_overflows": out[3],
        }

    def sweep_stats(self) -> dict:
        if not self._h:
            raise RuntimeError("store closed")
        out = (ctypes.c_uint64 * 4)()
        self._lib.store_sweep_stats(self._h, out)
        return {
            "num_sweeps": out[0],
            "refs_swept": out[1],
            "partials_reclaimed": out[2],
            "active_clients": out[3],
        }

    def close(self) -> None:
        if self._h:
            try:
                self._shm.close()
            except BufferError:
                self._shm.close = lambda: None  # views still alive
            self._lib.store_detach(self._h)
            self._h = 0

    def destroy(self) -> None:
        name = self.name
        self.close()
        if self._owner:
            try:
                self._lib.store_destroy(name.encode())
            except Exception:  # noqa: BLE001
                pass
