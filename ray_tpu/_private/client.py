"""Core client: the library linked into every driver and worker process.

Reference: the CoreWorker library (src/ray/core_worker/core_worker.h:292)
— submission, object get/put/wait, KV access — minus the execution loop,
which lives in worker_main. One instance per process, connected to the
GCS over the session's unix socket.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import serialization
from .config import RayConfig
from .ids import ObjectID, WorkerID
from .object_store import ObjectStore
from .protocol import ConnectionLost, PeerConn
from .task_spec import TaskSpec
from ..exceptions import GetTimeoutError, RayTaskError, RayTpuError
from ..object_ref import ObjectRef


class CoreClient:
    def __init__(
        self,
        address: str,
        authkey: bytes,
        role: str,
        worker_id: Optional[WorkerID] = None,
        push_handler: Optional[Callable[[Dict[str, Any]], None]] = None,
        transfer_addr: Optional[str] = None,
    ):
        from . import transport
        from .object_transfer import ObjectFetcher

        self.worker_id = worker_id or WorkerID.from_random()
        self.role = role
        self.store = ObjectStore()
        self._push_handler = push_handler or (lambda msg: None)
        conn = transport.connect(address, authkey)
        self.conn = PeerConn(conn, push_handler=self._on_push, name=f"client-{role}")
        hello = {
            "type": "hello",
            "role": role,
            "worker_id": self.worker_id.binary(),
            "pid": os.getpid(),
        }
        if transfer_addr:
            hello["transfer_addr"] = transfer_addr
        reply = self.conn.request(
            hello, timeout=RayConfig.worker_register_timeout_s
        )
        if not reply.get("ok"):
            raise RayTpuError(f"failed to register with GCS: {reply}")
        self.session_dir = reply["session_dir"]
        # The node this process's objects live on; objects located on
        # other nodes are pulled through the transfer plane.
        self.node_id: Optional[bytes] = reply.get("node_id")
        self._fetcher = ObjectFetcher(self.store, authkey)
        self._authkey = authkey
        self._registered_functions: set = set()
        self._fn_lock = threading.Lock()
        # Direct actor-call path (reference: actor calls bypass raylets,
        # gRPC straight to the actor process —
        # transport/direct_actor_task_submitter.h). aid -> PeerConn, or
        # None when the actor must stay on the GCS route (restartable).
        self._direct_lock = threading.Lock()
        self._direct_conns: Dict[bytes, Optional[Any]] = {}
        self._direct_results: Dict[bytes, Any] = {}  # oid -> Future(fields)
        self._direct_oids: Dict[bytes, set] = {}  # aid -> unresolved oids

    def _on_push(self, msg: Dict[str, Any]):
        self._push_handler(msg)

    # ------------------------------------------------------------------ submit

    def register_function_once(self, function_id: bytes, blob: bytes) -> Optional[bytes]:
        """Returns the blob if this client hasn't shipped it yet, else None."""
        with self._fn_lock:
            if function_id in self._registered_functions:
                return None
            self._registered_functions.add(function_id)
            return blob

    def fetch_function(self, function_id: bytes) -> bytes:
        reply = self.conn.request({"type": "get_function", "function_id": function_id})
        if not reply.get("ok"):
            raise RayTpuError(f"function {function_id.hex()} not found in GCS")
        return reply["blob"]

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        self.conn.send({"type": "submit_task", "spec": spec})
        owner = self.worker_id.binary()
        return [ObjectRef(oid, owner) for oid in spec.return_object_ids()]

    # ----------------------------------------------------- direct actor path
    def _direct_conn_for(self, aid: bytes):
        with self._direct_lock:
            if aid in self._direct_conns:
                return self._direct_conns[aid]
        # First call: ask the GCS (parks until the actor is ALIVE, then
        # returns its socket — or fallback for restartable/dead actors).
        reply = self.request({"type": "get_actor_direct", "actor_id": aid})
        conn = None
        if reply.get("ok") and not reply.get("fallback") and reply.get("addr"):
            from multiprocessing.connection import Client as MpClient

            try:
                raw = MpClient(
                    reply["addr"], family="AF_UNIX", authkey=self._authkey
                )
                conn = PeerConn(
                    raw,
                    push_handler=lambda msg: None,
                    on_close=lambda a=aid: self._on_direct_close(a),
                    name="direct",
                )
            except OSError:
                conn = None
        with self._direct_lock:
            self._direct_conns[aid] = conn
        return conn

    def submit_actor_direct(self, spec: TaskSpec) -> Optional[List[ObjectRef]]:
        """Send an actor method straight to its worker; returns None to
        fall back to GCS routing (restartable or dead actors)."""
        from concurrent.futures import Future

        aid = spec.actor_id.binary()
        conn = self._direct_conn_for(aid)
        if conn is None:
            return None
        oids = [oid.binary() for oid in spec.return_object_ids()]
        futs = []
        with self._direct_lock:
            pending = self._direct_oids.setdefault(aid, set())
            for ob in oids:
                f: Future = Future()
                self._direct_results[ob] = f
                pending.add(ob)
                futs.append(f)
        try:
            rfut = conn.request_async({"type": "execute_task", "spec": spec})
        except BaseException:
            self._on_direct_close(aid)
            return None
        rfut.add_done_callback(
            lambda f, oids=oids, aid=aid: self._resolve_direct(aid, oids, f)
        )
        owner = self.worker_id.binary()
        return [ObjectRef(oid, owner) for oid in spec.return_object_ids()]

    def _resolve_direct(self, aid: bytes, oids, rfut) -> None:
        from ..exceptions import ActorDiedError

        try:
            reply = rfut.result()
        except BaseException:
            reply = None
        with self._direct_lock:
            pending = self._direct_oids.get(aid, set())
            futs = [
                (ob, self._direct_results.get(ob)) for ob in oids
            ]
            pending.difference_update(oids)
        for i, (ob, f) in enumerate(futs):
            if f is None or f.done():
                continue
            if reply is None:
                f.set_exception(ActorDiedError(reason="connection lost"))
            elif reply.get("error") is not None:
                f.set_result({"status": "FAILED", "error": reply["error"]})
            else:
                fields = dict(reply["results"][i])
                fields["status"] = "READY"
                f.set_result(fields)

    def _on_direct_close(self, aid: bytes) -> None:
        from ..exceptions import ActorDiedError

        with self._direct_lock:
            self._direct_conns[aid] = None
            pending = self._direct_oids.pop(aid, set())
            futs = [self._direct_results.get(ob) for ob in pending]
        for f in futs:
            if f is not None and not f.done():
                f.set_exception(ActorDiedError(reason="actor connection lost"))

    # ------------------------------------------------------------------ objects

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        self.put_with_id(oid, value)
        return ObjectRef(oid, self.worker_id.binary())

    def put_with_id(self, oid: ObjectID, value: Any) -> Dict[str, Any]:
        """Seal a value; small values inline through the GCS, large ones go
        to the shm store (reference: max_direct_call_object_size split
        between memory store and plasma)."""
        value = serialization.prepare_value(value)
        payload, buffers = serialization.dumps(value)
        size = serialization.serialized_size(payload, buffers)
        if size <= RayConfig.max_inline_object_size:
            blob = bytearray(size)
            serialization.write_to(memoryview(blob), payload, buffers)
            fields = {"object_id": oid.binary(), "inline": bytes(blob), "size": size}
        else:
            name = object_segment_put(self.store, oid, payload, buffers, size)
            fields = {"object_id": oid.binary(), "segment": name, "size": size}
        reply = self.conn.request({"type": "put_object", **fields})
        if not reply.get("ok"):
            raise RayTpuError(f"put failed: {reply}")
        return fields

    def _materialize(self, reply: Dict[str, Any], oid: ObjectID) -> Any:
        if reply.get("status") == "FAILED":
            err = serialization.unpack(reply["error"])
            if isinstance(err, RayTaskError):
                raise err.as_instanceof_cause()
            raise err
        if reply.get("inline") is not None:
            return serialization.unpack(reply["inline"])
        # Cross-node: the object's primary copy lives on another node —
        # pull it into the local store first (reference: raylet
        # PullManager fetching via the object directory).
        owner_node = reply.get("node_id")
        if (
            owner_node is not None
            and owner_node != self.node_id
            and not self.store.contains(oid)
        ):
            addr = reply.get("transfer_addr")
            if not addr or not self._fetcher.pull(oid, addr):
                from ..exceptions import ObjectLostError

                raise ObjectLostError(
                    f"object {oid.hex()} on node "
                    f"{owner_node.hex()[:8]} could not be fetched"
                )
        return self.store.get(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(f"get timed out on {ref}")
            # Direct actor-call results resolve on the direct socket —
            # no GCS round-trip on the critical path.
            fut = self._direct_results.get(ref.id().binary())
            if fut is not None:
                try:
                    reply = fut.result(timeout=remaining)
                except TimeoutError:
                    raise GetTimeoutError(f"get timed out on {ref}") from None
                out.append(self._materialize(reply, ref.id()))
                continue
            try:
                reply = self.conn.request(
                    {"type": "get_object", "object_id": ref.id().binary()},
                    timeout=remaining,
                )
            except TimeoutError:
                raise GetTimeoutError(f"get timed out on {ref}") from None
            out.append(self._materialize(reply, ref.id()))
        return out

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int = 1,
        timeout: Optional[float] = None,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        ids = [r.id().binary() for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            reply = self.conn.request({"type": "check_ready", "object_ids": ids})
            ready_set = set(reply["ready"])
            if len(ready_set) >= num_returns or (
                deadline is not None and time.monotonic() >= deadline
            ):
                ready = [r for r in refs if r.id().binary() in ready_set][:num_returns]
                ready_ids = {r.id().binary() for r in ready}
                rest = [r for r in refs if r.id().binary() not in ready_ids]
                return ready, rest
            pending_ids = [i for i in ids if i not in ready_set]
            block = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                self.conn.request(
                    {"type": "wait_any", "object_ids": pending_ids}, timeout=block
                )
            except TimeoutError:
                pass

    def free(self, refs: Sequence[ObjectRef]):
        with self._direct_lock:
            for r in refs:
                self._direct_results.pop(r.id().binary(), None)
        self.conn.send(
            {"type": "free_objects", "object_ids": [r.id().binary() for r in refs]}
        )
        # Drop our local copies (pulled replicas / remote-driver puts);
        # the GCS fan-out only reaches node daemons, not this process.
        for r in refs:
            try:
                self.store.delete(r.id())
            except Exception:  # noqa: BLE001
                pass

    # ---------------------------------------------------------------------- kv

    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True, ns: str = "") -> bool:
        r = self.conn.request(
            {"type": "kv_put", "key": key, "value": value, "overwrite": overwrite, "ns": ns}
        )
        return r.get("added", False)

    def kv_get(self, key: bytes, ns: str = "") -> Optional[bytes]:
        return self.conn.request({"type": "kv_get", "key": key, "ns": ns}).get("value")

    def kv_del(self, key: bytes, ns: str = "") -> bool:
        return self.conn.request({"type": "kv_del", "key": key, "ns": ns}).get("deleted", False)

    def kv_exists(self, key: bytes, ns: str = "") -> bool:
        return self.conn.request({"type": "kv_exists", "key": key, "ns": ns}).get("exists", False)

    def kv_keys(self, prefix: bytes = b"", ns: str = "") -> List[bytes]:
        return self.conn.request({"type": "kv_keys", "prefix": prefix, "ns": ns}).get("keys", [])

    # ------------------------------------------------------------------- misc

    def cluster_info(self) -> Dict[str, Any]:
        return self.conn.request({"type": "cluster_info"})

    def request(self, msg: Dict[str, Any], timeout: Optional[float] = None) -> Dict[str, Any]:
        return self.conn.request(msg, timeout=timeout)

    def send(self, msg: Dict[str, Any]) -> None:
        self.conn.send(msg)

    def close(self):
        self.conn.close()
        self._fetcher.close()
        self.store.close()


def object_segment_put(store: ObjectStore, oid: ObjectID, payload, buffers, size) -> str:
    return store.put_serialized(oid, payload, buffers, size)
