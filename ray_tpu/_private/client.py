"""Core client: the library linked into every driver and worker process.

Reference: the CoreWorker library (src/ray/core_worker/core_worker.h:292)
— submission, object get/put/wait, KV access — minus the execution loop,
which lives in worker_main. One instance per process, connected to the
GCS over the session's unix socket.
"""
from __future__ import annotations

import concurrent.futures
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import chaos as _chaos
from . import serialization
from . import events as _events
from . import fastpath as _fastpath
from .config import RayConfig
from .ids import ObjectID, WorkerID, fast_unique_bytes
from .object_store import ObjectStore
from .protocol import OP_CALL, ConnectionLost, PeerConn
from .task_spec import TaskSpec
from ..exceptions import GetTimeoutError, RayTaskError, RayTpuError
from ..object_ref import ObjectRef

_MISSING = object()  # direct-route state: never looked up
_fp = _fastpath.get()  # native hot path (None → pure Python)
_return_oids = (
    _fp.return_oids
    if _fp is not None
    else lambda tid, n: [ObjectID.bytes_for_return(tid, i) for i in range(n)]
)
_LEASE_PIPELINE_MAX = 16  # max in-flight tasks per leased worker
_LEASE_IDLE_RETURN_S = 0.5  # idle leases are given back after this
_FLUSH_INTERVAL_S = 0.002  # safety flush for lazily-buffered sends

# get() resolution kinds: direct-call reply socket, same-host store hit
# (zero RPCs), or the GCS directory.
_GET_DIRECT = object()
_GET_LOCAL = object()
_GET_GCS = object()


class CoreClient:
    def __init__(
        self,
        address: str,
        authkey: bytes,
        role: str,
        worker_id: Optional[WorkerID] = None,
        push_handler: Optional[Callable[[Dict[str, Any]], None]] = None,
        transfer_addr: Optional[str] = None,
        direct_addr: Optional[str] = None,
        reconnect: Optional[bool] = None,
    ):
        from . import transport
        from .object_transfer import ObjectFetcher

        self.worker_id = worker_id or WorkerID.from_random()
        self.role = role
        self.store = ObjectStore()
        # Read-your-writes contract for state reads (list/timeline):
        # worker processes bind this to their _DoneBatcher.flush so
        # locally-coalesced task_done records reach the GCS before a
        # state query from this process is answered (the GCS-side flush
        # barrier cannot ping the requesting worker — its conn reader
        # thread is busy carrying the request; gcs._barrier_flush_events).
        self.pre_state_read_flush: Optional[Callable[[], None]] = None
        self._push_handler = push_handler or (lambda msg: None)
        self._address = address
        self._authkey = authkey
        self._transfer_addr = transfer_addr
        self._direct_addr = direct_addr
        # Head failover (reference: gcs_rpc_client retries across a GCS
        # restart). Workers always ride a failover — their head (the
        # same session address, unix or TCP) may be restarted by a
        # supervisor; drivers opt in when they connected to an external
        # head (an in-process head dies with this process).
        self._reconnect_enabled = (
            role == "worker" if reconnect is None else bool(reconnect)
        )
        self._closing = False
        self._reconnecting = False
        self._reconnect_lock = threading.Lock()
        # Leased-task resubmits that hit ConnectionLost park here for
        # ONE drainer thread (threads-per-lost-lease would balloon
        # during the exact mass-lease-death outage this serves).
        self._resubmit_pending: List[Any] = []
        self._resubmit_lock = threading.Lock()
        self._resubmit_thread: Optional[threading.Thread] = None
        self._conn_gen = 0
        #: Set when the head is gone for good (reconnect disabled,
        #: budget exhausted, or close()): watchers exit on this.
        self.head_permanently_lost = threading.Event()
        #: Worker runtime hooks: extra reconnect-hello payload (hosted
        #: actors, executing tasks, sealed locations) and a post-
        #: reconnect callback (done-batcher retransmit, drop_actors).
        self.reconcile_info: Optional[Callable[[], Dict[str, Any]]] = None
        self.on_reconnected: Optional[
            Callable[[Dict[str, Any]], None]
        ] = None
        self.done_ack: Optional[Callable[[int], None]] = None
        # Initial connect: ONE retry policy (chaos.Backoff, full
        # jitter) instead of failing on the first refused connect — a
        # worker spawned while the head restarts, or a driver racing
        # head bring-up, must absorb the same failure mode the
        # reconnect path does (reconnect stampede note in raylet.py).
        bo = _chaos.Backoff(
            base_s=0.1, cap_s=2.0,
            budget_s=(
                RayConfig.worker_register_timeout_s
                if role == "worker"
                else 5.0
            ),
        )
        conn = _chaos.retry_call(
            lambda: transport.connect(address, authkey),
            retry_on=(OSError,),
            backoff=bo,
        )
        self.conn = PeerConn(
            conn,
            push_handler=self._on_push,
            on_close=lambda gen=0: self._on_head_conn_close(gen),
            name=f"client-{role}",
        )
        # Partition-chaos role stamp: the far side of this conn is the
        # head (link-cut rules are expressed between named roles).
        self.conn.peer_role = "head"
        reply = self.conn.request(
            self._hello_msg(), timeout=RayConfig.worker_register_timeout_s
        )
        if not reply.get("ok"):
            raise RayTpuError(f"failed to register with GCS: {reply}")
        self.session_dir = reply["session_dir"]
        # The node this process's objects live on; objects located on
        # other nodes are pulled through the transfer plane.
        self.node_id: Optional[bytes] = reply.get("node_id")
        self._fetcher = ObjectFetcher(self.store, authkey)
        # Admission control over the transfer plane (pull_manager.h):
        # pulls queue get > wait > task-args under a bounded in-flight
        # byte budget so bulk broadcasts can't starve small gets.
        from .object_plane.pull_manager import PullManager

        self._pull_manager = PullManager(self._fetcher, store=self.store)
        self._registered_functions: set = set()
        self._fn_lock = threading.Lock()
        # Direct actor-call path (reference: actor calls bypass raylets,
        # gRPC straight to the actor process —
        # transport/direct_actor_task_submitter.h). aid -> PeerConn once
        # established, "resolving" while the GCS lookup is in flight
        # (calls buffer so one ordered stream flows down exactly one
        # path), or None when the actor stays on the GCS route
        # (restartable actors).
        self._direct_lock = threading.RLock()
        self._direct_conns: Dict[bytes, Any] = {}
        self._direct_buffer: Dict[bytes, list] = {}  # aid -> specs awaiting route
        self._direct_results: Dict[bytes, Any] = {}  # oid -> Future(fields)
        self._direct_oids: Dict[bytes, set] = {}  # aid -> unresolved oids
        # Leased-worker pools per scheduling class (direct task transport).
        self._lease_lock = threading.Lock()
        self._leases: Dict[Any, list] = {}
        # Per-class grow hold-off: a monotonic deadline computed from
        # exponential backoff + jitter (one policy, chaos.Backoff)
        # instead of a fixed 100ms window, so a saturated or briefly-
        # unavailable head sees the retry rate decay instead of a
        # stampede of synchronized grow round-trips. The deadline entry
        # is popped (and the backoff reset) on a successful grow.
        self._lease_grow_hold_until: Dict[Any, float] = {}
        self._lease_backoff: Dict[Any, Any] = {}
        self._lease_reaper: Optional[threading.Thread] = None
        # Distributed refcounting + lineage (reference_count.h:61,
        # task_manager.h:269): live ObjectRef instances in this process
        # feed the tracker; specs this client submitted are retained for
        # reconstruction until their return refs die.
        from .ref_tracker import RefTracker, set_current

        self._lineage: Dict[bytes, TaskSpec] = {}
        self._tracker = RefTracker(self)
        set_current(self._tracker)
        # Lazily-buffered connections (hot-path frames coalesce into one
        # wire message per burst); flushed before any blocking get/wait
        # and by a safety timer for fire-and-forget callers.
        self._lazy_conns: set = set()
        self._lazy_flusher: Optional[threading.Thread] = None
        self._lazy_evt = threading.Event()
        self._lazy_parked = False
        # Push-based wait (reference: raylet/wait_manager.h — waits are
        # registered once and completed by callbacks, never polled).
        # _wait_ready is a monotone set of locally-known-ready ids fed by
        # (a) direct-call reply callbacks and (b) one-shot GCS
        # subscriptions answered with ("RDY", oids) pushes; wait() just
        # partitions against it under the condvar. Pruned when refs die.
        self._wait_cond = threading.Condition()
        self._wait_ready: set = set()
        self._wait_interest: set = set()  # ids a wait() is blocked on
        self._wait_subscribed: set = set()  # ids subscribed at the GCS
        # Superset of ready ∪ interest: ids wait() has classified.
        # Registration happens ONCE per id (O(changed) across a whole
        # drain-by-wait loop); the per-call scan pays one set probe per
        # already-tracked ref instead of re-classifying. Pruned with
        # the other wait sets when refs die.
        self._wait_tracked: set = set()
        # registered counts first-time classifications — the perf
        # assertion in ray_perf checks it stays O(refs), not O(n^2).
        self._wait_stats = {"registered": 0}
        self._head_conn_lost = False

    # --------------------------------------------------------- lazy flushing

    def _mark_lazy(self, conn: PeerConn) -> None:
        self._lazy_conns.add(conn)
        if self._lazy_parked:
            self._lazy_parked = False
            self._lazy_evt.set()
        if self._lazy_flusher is None:
            self._lazy_flusher = threading.Thread(
                target=self._lazy_flush_loop, name="lazy-flusher", daemon=True
            )
            self._lazy_flusher.start()

    def flush_lazy(self) -> None:
        # Hot path (runs before every blocking get/wait): flush() itself
        # early-outs on an empty buffer, so one call per conn is cheap.
        for c in tuple(self._lazy_conns):
            try:
                c.flush()
            except ConnectionLost:
                self._lazy_conns.discard(c)
            else:
                if c.closed:
                    self._lazy_conns.discard(c)

    def _lazy_flush_loop(self) -> None:
        # Safety flush for fire-and-forget senders, parked while no conn
        # has buffered frames — an idle process must cost zero wakeups
        # (hundreds of workers x a 2 ms timer would saturate a core on
        # their own; see the 150-actor scale stress).
        while self._running():
            busy = False
            for c in tuple(self._lazy_conns):
                if c.has_buffered:
                    busy = True
                    break
            if busy:
                time.sleep(_FLUSH_INTERVAL_S)
                self.flush_lazy()
                continue
            self._lazy_parked = True
            # Re-check under the parked flag: a send_lazy racing the
            # scan above sees parked=True and sets the event.
            if any(c.has_buffered for c in tuple(self._lazy_conns)):
                self._lazy_parked = False
                continue
            self._lazy_evt.wait()
            self._lazy_evt.clear()
            self._lazy_parked = False

    # ------------------------------------------------------- head failover
    # Reference: gcs_rpc_client.h retries RPCs across a GCS restart and
    # bearers of truth re-report via NotifyGCSRestart. Here: on conn
    # loss, a reconnect thread re-dials the SAME head address with
    # chaos.Backoff, re-registers under the same worker/job id
    # (hello reconnect=True), then replays in-flight state — wait
    # re-subscriptions, owned-object reconciliation, unacked
    # ref_flush/task_done batches (per-batch seq + head-side dedup make
    # retransmission safe). Blocked get()/wait() callers park on the
    # failover instead of raising.

    def _hello_msg(self, reconnect: bool = False) -> Dict[str, Any]:
        hello: Dict[str, Any] = {
            "type": "hello",
            "role": self.role,
            "worker_id": self.worker_id.binary(),
            "pid": os.getpid(),
        }
        if self._transfer_addr:
            hello["transfer_addr"] = self._transfer_addr
        if self._direct_addr:
            hello["direct_addr"] = self._direct_addr
        nid_hex = os.environ.get("RAY_TPU_NODE_ID")
        if nid_hex:
            hello["node_id"] = bytes.fromhex(nid_hex)
        if os.environ.get("RAY_TPU_LOCAL_ONLY"):
            # Raylet-leased worker: the daemon dispatches to us, the GCS
            # only keeps directory/worker bookkeeping.
            hello["local_only"] = True
        if reconnect:
            hello["reconnect"] = True
            info = self.reconcile_info
            if info is not None:
                try:
                    hello.update(info())
                except Exception:  # noqa: BLE001 - reconcile is best-effort
                    pass
        return hello

    def _on_head_conn_close(self, gen: int = -1) -> None:
        if gen >= 0 and gen != self._conn_gen:
            return  # a superseded connection's late close: ignore
        # Blocked waiters must observe head loss (the old polling wait
        # raised out of its per-iteration request; push-based waits
        # would otherwise sleep forever on the condvar).
        with self._wait_cond:
            self._head_conn_lost = True
            self._wait_cond.notify_all()
        if self._closing or not self._reconnect_enabled:
            self.head_permanently_lost.set()
            return
        with self._reconnect_lock:
            if self._reconnecting or self._closing:
                return
            self._reconnecting = True
        threading.Thread(
            target=self._reconnect_loop, name="head-reconnect", daemon=True
        ).start()

    def conn_failover_pending(self) -> bool:
        """True while the head connection may yet come back (a failover
        reconnect is possible and not exhausted) — loops that would
        exit on a closed conn should idle instead."""
        return (
            self._reconnect_enabled
            and not self._closing
            and not self.head_permanently_lost.is_set()
        )

    def _running(self) -> bool:
        """Session liveness for background loops: the current conn is
        open, or a failover may still bring a new one."""
        if self._closing:
            return False
        if not self.conn.closed:
            return True
        return self.conn_failover_pending()

    def _reconnect_loop(self) -> None:
        from . import transport

        t0 = time.monotonic()
        if _events.enabled():
            _events.record(
                _events.HEAD, self.worker_id.hex()[:12], "HEAD_DOWN",
                {"role": self.role},
            )
        bo = _chaos.Backoff(
            base_s=0.2, cap_s=2.0,
            budget_s=RayConfig.gcs_reconnect_budget_s,
        )
        reply = None
        conn = None
        while not self._closing:
            try:
                raw = transport.connect(self._address, self._authkey)
            except OSError:
                if bo.sleep():
                    continue
                break
            conn = PeerConn(
                raw, push_handler=self._on_push, name=f"client-{self.role}"
            )
            conn.peer_role = "head"
            try:
                reply = conn.request(
                    self._hello_msg(reconnect=True),
                    timeout=RayConfig.worker_register_timeout_s,
                )
            except (
                ConnectionLost, TimeoutError,
                concurrent.futures.TimeoutError, OSError,
            ):
                reply = None
            if reply is not None and reply.get("fenced"):
                # The head fenced this identity (declared-dead worker
                # whose W_DEAD record outlived the partition): replaying
                # the same hello can never succeed — give up now so the
                # process exits instead of burning the whole budget.
                conn.close()
                reply, conn = None, None
                break
            if reply is None or not reply.get("ok"):
                conn.close()
                reply, conn = None, None
                if bo.sleep():
                    continue
                break
            break
        ok = reply is not None and conn is not None
        if ok:
            self.session_dir = reply["session_dir"]
            if reply.get("node_id"):
                self.node_id = reply["node_id"]
            self._conn_gen += 1
            self.conn = conn
            conn.set_on_close(
                lambda gen=self._conn_gen: self._on_head_conn_close(gen)
            )
        with self._reconnect_lock:
            self._reconnecting = False
        if not ok:
            self.head_permanently_lost.set()
            with self._wait_cond:
                self._wait_cond.notify_all()
            return
        with self._wait_cond:
            self._head_conn_lost = False
            self._wait_cond.notify_all()
        try:
            self._replay_after_reconnect(reply)
        except Exception:  # noqa: BLE001 - replay is best-effort; the
            pass  # recovery sweep covers what a racing close drops
        if _events.enabled():
            _events.record(
                _events.HEAD, self.worker_id.hex()[:12], "HEAD_RECONNECT",
                {
                    "outage_s": round(time.monotonic() - t0, 3),
                    "attempts": bo.attempts + 1,
                    "role": self.role,
                },
            )

    def _replay_after_reconnect(self, reply: Dict[str, Any]) -> None:
        """Re-advertise in-flight state to the restarted head: owned
        objects + live borrow edges (tracker reconcile), one-shot wait
        subscriptions, and the runtime's extras (done-batch replay)."""
        on_rec = getattr(self._tracker, "on_reconnect", None)
        owned = on_rec() if on_rec is not None else {}
        if owned:
            items = []
            for oid, borrowers in owned.items():
                try:
                    loc = self.store.location_of(ObjectID(oid))
                except Exception:  # noqa: BLE001
                    loc = None
                items.append((oid, loc, borrowers))
            try:
                self.conn.send(
                    {
                        "type": "reconcile",
                        "client": self.worker_id.binary(),
                        "owned": items,
                    }
                )
            except ConnectionLost:
                pass
        with self._wait_cond:
            subs = list(self._wait_subscribed)
        if subs:
            try:
                r = self.conn.request(
                    {"type": "wait_subscribe", "object_ids": subs}
                )
                ready = r.get("ready")
                if ready:
                    self._wait_mark(ready, subscribed=True)
            except (ConnectionLost, TimeoutError):
                pass
        cb = self.on_reconnected
        if cb is not None:
            try:
                cb(reply)
            except Exception:  # noqa: BLE001
                pass

    def _await_failover(self) -> bool:
        """Park the calling thread until the failover lands (True) or
        is hopeless (False). Callers re-issue their request on True."""
        if not self.conn_failover_pending():
            return False
        deadline = (
            time.monotonic()
            + RayConfig.gcs_reconnect_budget_s
            + RayConfig.worker_register_timeout_s
        )
        while time.monotonic() < deadline:
            if self.head_permanently_lost.is_set() or self._closing:
                return False
            with self._wait_cond:
                if not self._head_conn_lost and not self.conn.closed:
                    return True
                # Parked, not polled: the reconnect loop notifies this
                # condvar on both success and final failure (the
                # timeout only guards a close handler that never ran).
                self._wait_cond.wait(timeout=0.25)
        return False

    def send_reliable(self, msg: Dict[str, Any]) -> None:
        """A send that survives a head failover: on conn loss, park
        until the reconnect re-registers, then resend on the new conn
        (used for submits — the task must not be dropped because the
        head was mid-restart)."""
        while True:
            try:
                self.conn.send(msg)
                return
            except ConnectionLost:
                if not self._await_failover():
                    raise

    def request_reliable(
        self, msg: Dict[str, Any], timeout: Optional[float] = None
    ) -> Dict[str, Any]:
        """Request/reply across a failover: a lost connection re-issues
        the request on the reconnected one (request ids are assigned
        per-conn, so re-sending the same dict is safe)."""
        while True:
            try:
                return self.conn.request(msg, timeout=timeout)
            except ConnectionLost:
                if not self._await_failover():
                    raise

    # raylint: dispatch-only
    def _on_push(self, msg: Dict[str, Any]):
        if type(msg) is tuple and msg[0] == "RDY":
            self._wait_mark(msg[1], subscribed=True)
            return
        mtype = msg.get("type") if type(msg) is dict else None
        if mtype == "borrow_update":
            # Object plane: head-relayed borrow edges for objects this
            # process owns — fold into the authoritative view.
            self._tracker.apply_borrow_update(
                msg.get("borrower", b""), msg.get("add"), msg.get("remove")
            )
            return
        if mtype == "borrower_died":
            self._tracker.sweep_borrower(msg.get("client", b""))
            return
        if mtype == "ref_flush_ack":
            # At-least-once ref_flush: the head received the batch;
            # stop retransmitting it.
            ack = getattr(self._tracker, "ack", None)
            if ack is not None:
                ack(msg.get("seq", 0))
            return
        if mtype == "task_done_ack":
            # At-least-once task_done_batch (worker runtime): the head
            # received the completion batch; stop retransmitting it.
            ack = self.done_ack
            if ack is not None:
                ack(msg.get("seq", 0))
            return
        if mtype == "fenced":
            # Membership fence: the head declared this client dead while
            # a partition hid its heartbeats. A fenced worker's results
            # and refcount edges are already being dropped head-side —
            # the only correct move is to stop being this identity.
            # Workers exit (the raylet's fresh incarnation respawns
            # capacity); a driver surfaces permanent head loss.
            if self.role == "worker":
                self._reconnect_enabled = False
                self.head_permanently_lost.set()
                with self._wait_cond:
                    self._head_conn_lost = True
                    self._wait_cond.notify_all()
                try:
                    self.conn.close()
                except Exception:  # noqa: BLE001 - counted, never silent
                    self._fence_close_errors = getattr(
                        self, "_fence_close_errors", 0
                    ) + 1
            return
        self._push_handler(msg)

    # -------------------------------------------------- push-based wait state

    def _wait_mark(self, oids, subscribed: bool = False) -> None:
        """A result landed: promote interested ids to the ready set.

        Ids without registered interest are ignored (wait() classifies
        already-done entries itself), keeping the ready set bounded by
        what has actually been waited on."""
        cond = self._wait_cond
        with cond:
            interest = self._wait_interest
            if subscribed:
                hit = [o for o in oids if o in self._wait_subscribed]
            else:
                if not interest:
                    return
                hit = [o for o in oids if o in interest]
            if not hit:
                return
            interest.difference_update(hit)
            self._wait_ready.update(hit)
            cond.notify_all()

    def _wait_on_failure(self, oids) -> None:
        """A direct route died and its entries were rewritten to
        sentinels: re-classify interested ids — terminal sentinels are
        ready, via_gcs resubmissions move to a GCS subscription."""
        to_subscribe = []
        cond = self._wait_cond
        with cond:
            interest = self._wait_interest
            if not interest:
                return
            woke = False
            for oid in oids:
                if oid not in interest:
                    continue
                entry = self._direct_results.get(oid)
                if isinstance(entry, dict) and entry.get("via_gcs"):
                    if oid not in self._wait_subscribed:
                        self._wait_subscribed.add(oid)
                        to_subscribe.append(oid)
                else:
                    # FAILED / exception sentinel (or a racing success):
                    # counts as ready; get() surfaces the outcome.
                    interest.discard(oid)
                    self._wait_ready.add(oid)
                    woke = True
            if woke:
                cond.notify_all()
        if to_subscribe:
            self._wait_subscribe_async(to_subscribe)

    def _wait_subscribe_async(self, oids) -> None:
        fut = self.conn.request_async(
            {"type": "wait_subscribe", "object_ids": oids}
        )

        def _done(f):
            try:
                ready = f.result().get("ready")
            except BaseException:  # noqa: BLE001 - conn loss ends waits
                return
            if ready:
                self._wait_mark(ready, subscribed=True)

        fut.add_done_callback(_done)

    def _wait_prune(self, oids) -> None:
        """Refs died locally: forget their wait bookkeeping. O(changed)
        — set difference over the dead ids only, never a rescan of the
        live wait set."""
        cond = self._wait_cond
        with cond:
            if (
                not self._wait_tracked
                and not self._wait_subscribed
            ):
                return
            self._wait_ready.difference_update(oids)
            self._wait_interest.difference_update(oids)
            self._wait_subscribed.difference_update(oids)
            self._wait_tracked.difference_update(oids)

    # ------------------------------------------------------------------ submit

    def register_function_once(self, function_id: bytes, blob: bytes) -> Optional[bytes]:
        """Returns the blob if this client hasn't shipped it yet, else None."""
        with self._fn_lock:
            if function_id in self._registered_functions:
                return None
            self._registered_functions.add(function_id)
            return blob

    def fetch_function(self, function_id: bytes) -> bytes:
        reply = self.request_reliable(
            {"type": "get_function", "function_id": function_id}
        )
        if not reply.get("ok"):
            raise RayTpuError(f"function {function_id.hex()} not found in GCS")
        return reply["blob"]

    def _record_lineage(self, spec: TaskSpec) -> None:
        if spec.actor_id is None:
            for oid in spec.return_object_ids():
                self._lineage[oid.binary()] = spec

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        self._record_lineage(spec)
        _rec = _events.get_recorder()
        if _rec.enabled:
            _rec.record(
                _events.TASK, spec.task_id.hex(), "SUBMITTED",
                {"route": "gcs", "name": spec.name},
            )
        # Reliable: a submit racing a head restart parks on the
        # failover and lands on the recovered head instead of vanishing.
        self.send_reliable({"type": "submit_task", "spec": spec})
        owner = self.worker_id.binary()
        refs = [ObjectRef(oid, owner) for oid in spec.return_object_ids()]
        self._advertise_returns(refs)
        return refs

    def _advertise_returns(self, refs: Sequence[ObjectRef]) -> None:
        """Owner-side return refs count as advertised from birth: the
        directory frees a sealed result on the owner's remove (the
        had_holder fast-drop path), so the drop must go out even when
        the ref dies inside the first flush window — otherwise every
        short-lived `get(f.remote())` result leaks server-side."""
        for r in refs:
            self._tracker.mark_advertised(r.id().binary())

    # ------------------------------------------------- leased task transport
    # Reference: CoreWorkerDirectTaskSubmitter (direct_task_transport.cc:24)
    # — the caller leases idle workers from the control plane once per
    # burst and pushes tasks to them directly, so the steady-state task
    # path costs one hop (caller -> worker -> caller) instead of four
    # through the GCS. Resource accounting happens at lease grant/return
    # granularity; the worker's async task_done keeps the object
    # directory coherent for wait/free/cross-process refs.

    def _lease_eligible(self, spec: TaskSpec) -> bool:
        return (
            spec.actor_id is None
            and not spec.actor_creation
            and not spec.dependencies
            # Nested arg refs need the GCS route's lifetime pins (the
            # leased path has no head-side pinning at all).
            and not spec.borrowed_refs
            and spec.placement_group_id is None
            and spec.scheduling_strategy is None
            and not spec.retry_exceptions
            and spec.function_blob is None  # first call registers via GCS
            # Single-chip TPU tasks lease from the LOCAL raylet only
            # (its pool assigns each worker a dedicated chip); larger
            # shapes need the GCS's quantity accounting.
            and (
                spec.resources.get("TPU", 0) == 0
                or (
                    # Exactly one whole chip: local slots are chip-
                    # granular; fractional requests need the GCS's
                    # float quantity accounting.
                    spec.resources.get("TPU", 0) == 1
                    and os.environ.get("RAY_TPU_LOCAL_RAYLET")
                )
            )
        )

    def submit_task_leased(self, spec: TaskSpec) -> Optional[List[ObjectRef]]:
        """Push a task to a leased worker; None -> route via the GCS."""
        if not self._lease_eligible(spec):
            return None
        # Flight recorder, compact form: ONE ring append per task
        # carrying the submit/queue/lease boundaries in its attrs (the
        # head expands it off the hot path — events._expand).
        _rec = _events.get_recorder()
        t_submit = time.time() if _rec.enabled else 0.0
        key = spec.scheduling_class()
        now = time.monotonic()
        with self._lease_lock:
            pool = self._leases.setdefault(key, [])
            lease = min(pool, key=lambda c: c["outstanding"], default=None)
            expand = (
                lease is not None
                and lease["outstanding"] >= _LEASE_PIPELINE_MAX
                # Back off after a failed grow: each attempt is a
                # synchronous GCS round-trip, and a saturated pool would
                # otherwise retry on every submit of a burst.
                and not self._lease_grow_held(key, now)
            )
            if lease is not None and not expand:
                # Claim under the lock so the idle reaper can't return
                # the lease between selection and push.
                lease["outstanding"] += 1
        if lease is None and self._lease_grow_held(key, now):
            return None  # recent failed acquire (e.g. remote driver): GCS route
        if lease is None or expand:
            fresh = self._acquire_lease(key, spec.resources)
            if fresh is not None:
                lease = fresh
                with self._lease_lock:
                    # Grow succeeded: the hold-off window resets.
                    bo = self._lease_backoff.get(key)
                    if bo is not None:
                        bo.reset()
                    self._lease_grow_hold_until.pop(key, None)
            else:
                self._note_lease_grow_failed(key)
                if lease is None:
                    return None  # no lease at all: GCS route
            # Pool can't grow: queue on the least-loaded lease anyway —
            # workers drain serially either way, and mixing paths would
            # strand the GCS-routed overflow behind held leases.
            with self._lease_lock:
                if lease["returned"]:
                    # Reaped while the grow round-trip was in flight
                    # (a loaded head can stall lease_worker past the
                    # idle-return window): its conn is closing and its
                    # reader may already be gone — a frame pushed now
                    # would be dropped with a forever-pending future.
                    return None  # GCS route
                lease["outstanding"] += 1
        # t_submit truthy too: recording toggled on mid-submit must not
        # ship a half-captured span (a 0.0 boundary poisons the phase
        # histograms with epoch-sized durations).
        if _rec.enabled and t_submit:
            # Recorded BEFORE the push so the span is in the ring before
            # the task can possibly execute — the head aggregator drains
            # this process's ring ahead of shipped worker batches, which
            # keeps submit→…→seal ordered without cross-process sync.
            # t_queue = t_submit: a directly-pushed task never queued, so
            # the queue phase is zero-width and the submit→lease-claim
            # gap is attributed to the lease phase.
            _rec.record(
                _events.TASK, spec.task_id.hex(), "SUBMIT_SPAN",
                {
                    "t_submit": t_submit,
                    "t_queue": t_submit,
                    "t_lease": time.time(),
                    "route": "lease",
                },
            )
        return self._push_leased(lease, spec)

    def _lease_grow_held(self, key, now: float) -> bool:
        """Inside the post-failure hold-off window for this class?"""
        return now <= self._lease_grow_hold_until.get(key, 0.0)

    def _note_lease_grow_failed(self, key) -> None:
        from .chaos import Backoff

        with self._lease_lock:
            bo = self._lease_backoff.get(key)
            if bo is None:
                bo = self._lease_backoff[key] = Backoff(
                    base_s=0.1, cap_s=2.0
                )
            self._lease_grow_hold_until[key] = (
                time.monotonic() + max(0.05, bo.next_delay())
            )

    def _raylet_conn(self) -> Optional[PeerConn]:
        """Connection to this node's raylet lease service, if any."""
        addr = os.environ.get("RAY_TPU_LOCAL_RAYLET")
        if not addr:
            return None
        with self._lease_lock:
            conn = getattr(self, "_raylet_peer", None)
            if conn is not None and not conn.closed:
                return conn
        from . import transport

        try:
            raw = transport.connect(addr, self._authkey)
        except OSError:
            return None
        conn = PeerConn(raw, push_handler=lambda m: None, name="raylet-lease")
        with self._lease_lock:
            cur = getattr(self, "_raylet_peer", None)
            if cur is not None and not cur.closed:
                # Lost a connect race: keep the winner, drop ours.
                conn.close()
                return cur
            self._raylet_peer = conn
        return conn

    def _acquire_lease(self, key, resources) -> Optional[dict]:
        # Local dispatch first (reference: tasks submitted on a node
        # lease from its raylet, not the head — cluster_task_manager):
        # one node-local hop, the head never sees the dispatch.
        # Local slots are single-unit: multi-CPU/TPU shapes need the
        # GCS's quantity accounting (_fits/_acquire), not a 1-slot
        # grant.
        tpu_shape = bool(resources) and resources.get("TPU", 0) > 0
        simple_shape = not resources or (
            set(resources) <= {"CPU", "TPU"}
            and resources.get("CPU", 0) <= 1
            and resources.get("TPU", 0) in (0, 1)
        )
        rconn = self._raylet_conn() if simple_shape else None
        if rconn is not None:
            try:
                reply = rconn.request(
                    {"type": "lease_worker", "resources": resources},
                    timeout=5,
                )
            except (ConnectionLost, TimeoutError):
                reply = None
            if reply and reply.get("ok") and reply.get("addr"):
                lease = self._connect_lease(key, reply, raylet=True)
                if lease is not None:
                    return lease
        if tpu_shape:
            # The head's lease pool is CPU-only; TPU tasks the local
            # raylet cannot serve take the GCS submit route.
            return None
        try:
            reply = self.conn.request(
                {"type": "lease_worker", "resources": resources}
            )
        except ConnectionLost:
            return None
        if not reply.get("ok") or not reply.get("addr"):
            return None
        return self._connect_lease(key, reply, raylet=False)

    def _connect_lease(self, key, reply, raylet: bool) -> Optional[dict]:
        from . import transport

        try:
            raw = transport.connect(reply["addr"], self._authkey)
        except OSError:
            # Worker on another machine (or gone): give the lease back.
            self._send_lease_return(reply["worker_id"], raylet)
            return None
        lease = {
            "worker_id": reply["worker_id"],
            "key": key,
            "outstanding": 0,
            "returned": False,
            "raylet": raylet,
        }
        lease["conn"] = PeerConn(
            raw, push_handler=lambda m: None, name="lease",
        )
        with self._lease_lock:
            self._leases.setdefault(key, []).append(lease)
        return lease

    def _send_lease_return(self, worker_id: bytes, raylet: bool) -> None:
        if raylet:
            rconn = self._raylet_conn()
            if rconn is not None:
                try:
                    rconn.send(
                        {"type": "return_lease", "worker_id": worker_id}
                    )
                    return
                except ConnectionLost:
                    pass
            return
        try:
            self.conn.send({"type": "return_lease", "worker_id": worker_id})
        except ConnectionLost:
            pass

    def _push_leased(self, lease, spec: TaskSpec) -> List[ObjectRef]:
        """Caller must have already claimed a slot (outstanding += 1).

        Ships a compact OP_CALL frame, buffered (send_lazy): a burst of
        submissions coalesces into one wire message, and the reply
        future doubles as the per-return result slot — get() interprets
        the reply frame lazily, so the steady-state task costs one
        Future and one tuple pickle end to end."""
        conn: PeerConn = lease["conn"]
        tid = spec.task_id._bytes
        nret = spec.num_returns
        oids = _return_oids(tid, nret)
        lineage = self._lineage
        for ob in oids:
            lineage[ob] = spec
        req_id = conn.next_req_id()
        rfut = conn.register_future(req_id)
        for i, ob in enumerate(oids):
            self._direct_results[ob] = (rfut, i)
        frame = (
            OP_CALL, req_id, tid, spec.function_id, None, spec.args_blob,
            nret, None, None,
        )
        owner = self.worker_id.binary()
        refs = [ObjectRef(ObjectID(ob), owner) for ob in oids]
        self._advertise_returns(refs)
        try:
            conn.send_lazy(frame)
        except ConnectionLost:
            conn.drop_future(req_id)
            # Send failed: the task never reached the worker, so a GCS
            # resubmit is always safe.
            self._leased_conn_lost(lease, spec, oids, delivered=False)
            return refs
        self._mark_lazy(conn)
        if conn.closed_after_push(req_id):
            # Closed between claim and push (the 1-in-200k lost-task
            # wedge): resolve through the conn-lost path;
            # delivered=True keeps at-most-once semantics in case the
            # frame flushed before the close landed.
            self._leased_conn_lost(lease, spec, oids, delivered=True)
            return refs
        rfut.add_done_callback(
            lambda f, lease=lease, spec=spec, oids=oids: self._resolve_leased(
                lease, spec, oids, f
            )
        )
        return refs

    def _resolve_leased(self, lease, spec: TaskSpec, oids, rfut):
        if rfut.exception() is not None:
            self._leased_conn_lost(lease, spec, oids, delivered=True)
            return
        self._dec_lease(lease)
        self._wait_mark(oids)

    def _leased_conn_lost(self, lease, spec: TaskSpec, oids, delivered: bool):
        give_back = False
        with self._lease_lock:
            pool = self._leases.get(lease["key"], [])
            if lease in pool:
                pool.remove(lease)
            if not lease["returned"]:
                lease["returned"] = True
                give_back = True
        if give_back:
            # The worker may still be alive with only the lease conn
            # broken: give the lease back so it isn't stranded leased
            # (idempotent if the worker actually died).
            self._send_lease_return(lease["worker_id"], lease.get("raylet", False))
        if delivered and spec.max_retries <= 0:
            # May have executed: at-most-once for non-retriable tasks
            # (reference: only retriable tasks resubmit on worker crash —
            # task_manager.h:468).
            from ..exceptions import WorkerCrashedError

            blob = serialization.pack(
                WorkerCrashedError("leased worker connection lost mid-task")
            )
            for ob in oids:
                self._direct_results[ob] = {"status": "FAILED", "error": blob}
            self._wait_on_failure(oids)
            return
        if delivered:
            spec.max_retries -= 1
        for ob in oids:
            self._direct_results[ob] = {"via_gcs": True}
        try:
            # Fast path: head healthy, the resubmit lands instantly.
            # raylint: disable=raw-send-on-gcs-path -- ConnectionLost hands off to send_reliable on a side thread below; a raw drop here previously lost the resubmit across a head failover (the _report_done bug class)
            self.conn.send({"type": "submit_task", "spec": spec})
        except ConnectionLost:
            # Head mid-failover. send_reliable parks until the
            # reconnect lands — but THIS code often runs on the dying
            # leased conn's reader thread (close-sweep future
            # callbacks), and parking that thread would stall the
            # sweep's remaining futures for the whole outage window.
            # Queue for the single drainer thread (same pattern as
            # PR 4 moving _report_done onto the batcher thread); many
            # leases die together in a failover, so thread-per-spec
            # would balloon exactly when the process is degraded.
            self._queue_resubmit(spec)
        self._wait_on_failure(oids)

    def _queue_resubmit(self, spec):
        with self._resubmit_lock:
            self._resubmit_pending.append(spec)
            t = self._resubmit_thread
            if t is not None and t.is_alive():
                return
            self._resubmit_thread = threading.Thread(
                target=self._drain_resubmits, name="resubmit-reliable",
                daemon=True,
            )
            self._resubmit_thread.start()

    def _drain_resubmits(self):
        while True:
            with self._resubmit_lock:
                if not self._resubmit_pending:
                    self._resubmit_thread = None
                    return
                spec = self._resubmit_pending.pop(0)
            try:
                self.send_reliable({"type": "submit_task", "spec": spec})
            except ConnectionLost:
                # Head permanently lost: the session is over; gets
                # fail through the head-loss path — drop the rest,
                # counted (a post-mortem must be able to tell a mass
                # resubmit discard from tasks never resubmitted).
                with self._resubmit_lock:
                    dropped = len(self._resubmit_pending) + 1
                    del self._resubmit_pending[:]
                    self._resubmit_thread = None
                if _events.enabled():
                    _events.record(
                        _events.HEAD, self.worker_id.hex()[:12],
                        "RESUBMITS_DROPPED", {"count": dropped},
                    )
                return
            except Exception as e:
                # Anything else (say, a spec that fails to serialize
                # on the reconnected conn) must not kill the drainer
                # with specs still queued behind it — count and drop
                # THIS spec, keep draining; its gets fail through the
                # normal result paths.
                if _events.enabled():
                    _events.record(
                        _events.HEAD, self.worker_id.hex()[:12],
                        "RESUBMITS_DROPPED",
                        {"count": 1, "error": type(e).__name__},
                    )

    def _dec_lease(self, lease):
        with self._lease_lock:
            lease["outstanding"] -= 1
            if lease["outstanding"] <= 0:
                # Keep the lease warm: returning on drain would pay a
                # lease round-trip per burst (reference: leased workers
                # are reused across tasks of a scheduling class and
                # returned after an idle timeout).
                lease["idle_since"] = time.monotonic()
                self._ensure_lease_reaper()

    def _ensure_lease_reaper(self):
        if self._lease_reaper is None:
            self._lease_reaper = threading.Thread(
                target=self._lease_reaper_loop, name="lease-reaper", daemon=True
            )
            self._lease_reaper.start()

    def _lease_reaper_loop(self):
        while self._running():
            time.sleep(0.1)
            now = time.monotonic()
            to_return = []
            with self._lease_lock:
                for key, pool in self._leases.items():
                    for lease in list(pool):
                        if (
                            lease["outstanding"] <= 0
                            and not lease["returned"]
                            and now - lease.get("idle_since", now)
                            > _LEASE_IDLE_RETURN_S
                        ):
                            lease["returned"] = True
                            pool.remove(lease)
                            to_return.append(lease)
            for lease in to_return:
                lease["conn"].close()
                if not self._running():
                    return
                self._send_lease_return(
                    lease["worker_id"], lease.get("raylet", False)
                )

    # ----------------------------------------------------- direct actor path

    def call_actor_fast(
        self,
        aid: bytes,
        method_name: str,
        args_blob: bytes,
        num_returns: int,
        deps: Sequence[ObjectID] = (),
        concurrency_group: Optional[str] = None,
    ) -> Optional[List[ObjectRef]]:
        """Steady-state actor call: compact frame straight down an
        established direct connection, no TaskSpec object at all.
        Returns None when the route isn't live yet (first call,
        resolving, or GCS-routed actor) — the caller falls back to the
        TaskSpec path which establishes/buffers correctly."""
        conn = self._direct_conns.get(aid)
        if conn is None or conn == "resolving" or isinstance(conn, str):
            return None
        tid = fast_unique_bytes()
        if _events.enabled():
            _events.record(_events.TASK, tid.hex(), "SUBMITTED", None)
        return self._send_frame(
            conn, aid, tid, method_name, args_blob, num_returns, deps,
            concurrency_group,
        )

    def _send_frame(
        self, conn, aid: bytes, tid: bytes, method_name: str,
        args_blob: bytes, num_returns: int, deps: Sequence[ObjectID] = (),
        concurrency_group: Optional[str] = None,
    ) -> List[ObjectRef]:
        oids = _return_oids(tid, num_returns)
        req_id = conn.next_req_id()
        rfut = conn.register_future(req_id)
        with self._direct_lock:
            pending = self._direct_oids.setdefault(aid, set())
            for i, ob in enumerate(oids):
                self._direct_results[ob] = (rfut, i)
                pending.add(ob)
        # Pin arg refs for the life of the in-flight call. The GCS route
        # pins spec.dependencies server-side (_h_submit_task task_pins);
        # the direct route bypasses the GCS, so without this the caller
        # dropping its own ref (e.g. re-broadcasting weights every step
        # while calls queue behind a deep actor backlog) frees the
        # object before the actor's arg-resolution get — which then
        # parks forever and wedges the serial actor.
        dep_ids = [d.binary() for d in deps]
        for d in dep_ids:
            self._tracker.incr(d)
        frame = (
            OP_CALL, req_id, tid, None, method_name, args_blob, num_returns,
            aid, concurrency_group,
        )
        owner = self.worker_id.binary()
        refs = [ObjectRef(ObjectID(ob), owner) for ob in oids]
        self._advertise_returns(refs)
        try:
            conn.send_lazy(frame)
        except ConnectionLost:
            conn.drop_future(req_id)
            for d in dep_ids:
                self._tracker.decr(d)
            self._on_direct_close(aid)
            return refs
        self._mark_lazy(conn)
        if conn.closed_after_push(req_id):
            # Closed between the route lookup and this push (an actor
            # kill's async death cleanup racing the very next call);
            # found by the lock witness's timing perturbation in
            # test_kill_actor.
            for d in dep_ids:
                self._tracker.decr(d)
            self._on_direct_close(aid)
            return refs

        def _resolved(f, oids=oids, aid=aid, dep_ids=dep_ids):
            for d in dep_ids:
                self._tracker.decr(d)
            self._resolve_direct(aid, oids, f)

        rfut.add_done_callback(_resolved)
        return refs

    def submit_actor_direct(self, spec: TaskSpec) -> Optional[List[ObjectRef]]:
        """Submit an actor method over the direct transport.

        Returns the refs when the call is (or will be) delivered
        directly or is buffered pending route resolution; None tells the
        caller to route via the GCS (restartable actors). The first call
        for an actor kicks off an async get_actor_direct lookup (the GCS
        parks it until the actor is ALIVE); calls buffer until the route
        is known so a single ordered stream flows down exactly one path —
        mixing paths could reorder a caller's calls."""
        aid = spec.actor_id.binary()
        if _events.enabled():
            _events.record(
                _events.TASK, spec.task_id.hex(), "SUBMITTED", None
            )
        with self._direct_lock:
            st = self._direct_conns.get(aid, _MISSING)
            if st is None:
                return None  # definitive: GCS route
            if st is _MISSING:
                self._direct_conns[aid] = "resolving"
                self._direct_buffer[aid] = [spec]
                rfut = self.conn.request_async(
                    {"type": "get_actor_direct", "actor_id": aid}
                )
                rfut.add_done_callback(
                    lambda f, a=aid: self._on_direct_resolved(a, f)
                )
                return self._refs_for(spec)
            if st == "resolving":
                self._direct_buffer[aid].append(spec)
                return self._refs_for(spec)
            return self._send_direct(st, spec)

    def _refs_for(self, spec: TaskSpec) -> List[ObjectRef]:
        owner = self.worker_id.binary()
        refs = [ObjectRef(oid, owner) for oid in spec.return_object_ids()]
        self._advertise_returns(refs)
        return refs

    def _on_direct_resolved(self, aid: bytes, rfut):
        try:
            reply = rfut.result()
        except BaseException:  # noqa: BLE001
            reply = {"fallback": True}
        conn = None
        if reply.get("ok") and not reply.get("fallback") and reply.get("addr"):
            from . import transport

            try:
                raw = transport.connect(reply["addr"], self._authkey)
                conn = PeerConn(
                    raw,
                    push_handler=lambda msg: None,
                    on_close=lambda a=aid: self._on_direct_close(a),
                    name="direct",
                )
            except OSError:
                conn = None
        with self._direct_lock:
            # Flush the buffer down the chosen path, then publish it —
            # all under the lock so late submitters can't jump the queue.
            buffered = self._direct_buffer.pop(aid, [])
            for spec in buffered:
                if conn is not None:
                    self._send_direct(conn, spec)
                else:
                    try:
                        self.submit(spec)
                    except ConnectionLost:
                        pass
            self._direct_conns[aid] = conn

    def _send_direct(self, conn, spec: TaskSpec) -> Optional[List[ObjectRef]]:
        return self._send_frame(
            conn,
            spec.actor_id.binary(),
            spec.task_id._bytes,
            spec.method_name,
            spec.args_blob,
            spec.num_returns,
            spec.dependencies,
            spec.concurrency_group,
        )

    def _resolve_direct(self, aid: bytes, oids, rfut) -> None:
        if rfut.exception() is not None:
            # Conn lost mid-flight: _on_direct_close (triggered by the
            # reader teardown) marks every pending oid as actor-died.
            self._on_direct_close(aid)
            return
        with self._direct_lock:
            pending = self._direct_oids.get(aid)
            if pending is not None:
                pending.difference_update(oids)
        self._wait_mark(oids)

    def _on_direct_close(self, aid: bytes) -> None:
        from ..exceptions import ActorDiedError

        with self._direct_lock:
            self._direct_conns[aid] = None
            pending = self._direct_oids.pop(aid, set())
            for ob in pending:
                if self._direct_results.pop(ob, None) is not None:
                    self._direct_results[ob] = {
                        "exception": ActorDiedError(
                            reason="actor connection lost"
                        )
                    }
        if pending:
            self._wait_on_failure(pending)

    # ------------------------------------------------------------------ objects

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID(fast_unique_bytes())
        # Ref exists (count>=1) BEFORE the directory learns of the
        # object, and the tracker knows the directory holds us as a
        # holder (put_object registers the putter) — so the eventual
        # drop sends its remove even if the add batch never went out.
        ref = ObjectRef(oid, self.worker_id.binary())
        self.put_with_id(oid, value)
        self._tracker.mark_advertised(oid.binary())
        return ref

    def put_with_id(self, oid: ObjectID, value: Any) -> Dict[str, Any]:
        """Seal a value; small values inline through the GCS, large ones go
        to the shm store (reference: max_direct_call_object_size split
        between memory store and plasma)."""
        from ..object_ref import _CaptureRefs

        value = serialization.prepare_value(value)
        with _CaptureRefs() as cap:
            payload, buffers = serialization.dumps(value)
        size = serialization.serialized_size(payload, buffers)
        if size <= RayConfig.max_inline_object_size:
            blob = bytearray(size)
            serialization.write_to(memoryview(blob), payload, buffers)
            blob = bytes(blob)
            fields = {"object_id": oid.binary(), "inline": blob, "size": size}
            # Node-segment copy for small values too (zero extra
            # syscalls — one pool create under the shm mutex): it is
            # what same-host readers hit with zero head round-trips,
            # and the local bearer of truth reconcile re-advertises
            # after a head failover — which is what makes the advert
            # below safe to fire-and-forget.
            try:
                loc = self.store.try_pool_put_packed(oid, blob)
            except Exception:  # noqa: BLE001 - pool mid-close
                self._pool_put_errors = getattr(
                    self, "_pool_put_errors", 0
                ) + 1
                loc = None
            if loc is not None:
                fields["segment"] = loc
        else:
            name = object_segment_put(self.store, oid, payload, buffers, size)
            fields = {"object_id": oid.binary(), "segment": name, "size": size}
        if cap.seen:
            # Refs nested inside the stored value: the directory pins them
            # while this object lives (borrowing — reference_count.h:61).
            fields["children"] = cap.seen
        if fields.get("segment") is not None and not cap.seen:
            # Async advert (the put fast path): the value is sealed in
            # the local store, so the directory learns of it via a
            # buffered fire-and-forget frame — zero blocking round
            # trips per put. Safe because (a) frames on one conn are
            # FIFO, so any later submit/free naming this object lands
            # after the advert; (b) chaos faults never target
            # put_object; (c) on conn loss the reconnect reconcile
            # re-advertises every owned object from store.location_of.
            # Values with captured child refs stay synchronous: the
            # children pins ride the advert and must survive failover.
            self._advert_async({"type": "put_object", **fields})
            return fields
        reply = self.request_reliable({"type": "put_object", **fields})
        if not reply.get("ok"):
            raise RayTpuError(f"put failed: {reply}")
        return fields

    def _advert_async(self, msg: Dict[str, Any]) -> None:
        rec = _events.get_recorder()
        if rec.enabled:
            rec.record(
                _events.OBJECT, ObjectID(msg["object_id"]).hex(),
                "SHM_PUT_ADVERT", {"size": msg.get("size", 0)},
            )
        try:
            self.conn.send_lazy(msg)
        except ConnectionLost:
            return  # reconcile re-advertises from the store on reconnect
        self._mark_lazy(self.conn)

    def _materialize(self, reply: Dict[str, Any], oid: ObjectID,
                     _retried: bool = False, packed: bool = False,
                     timeout: Optional[float] = None) -> Any:
        from ..exceptions import ObjectLostError

        if reply.get("status") == "FAILED":
            err = serialization.unpack(reply["error"])
            if isinstance(err, RayTaskError):
                raise err.as_instanceof_cause()
            raise err
        if reply.get("status") == "LOST":
            raise ObjectLostError(f"object {oid.hex()} lost (node died)")
        if reply.get("inline") is not None:
            if packed:
                return bytes(reply["inline"])
            return serialization.unpack(reply["inline"])
        spilled = reply.get("spilled_path")
        if spilled is not None and not self.store.contains(oid):
            # Restore rung of the memory-pressure ladder: the object was
            # spilled to disk under pool pressure. Same-host: read the
            # file directly (header + checksum validated — truncated
            # bytes are never returned); cross-node: fall through to the
            # transfer plane (the owner's transfer server restores from
            # its spill dir).
            from .object_store import SpillCorruptionError, read_spill_file

            try:
                data = read_spill_file(spilled)
                return data if packed else serialization.unpack(data)
            except SpillCorruptionError:
                # Bad file: tell the head so the entry resolves LOST
                # (reconstruct from lineage) instead of every future get
                # re-reading garbage; fall through to the other copies.
                try:
                    self.send_reliable(
                        {"type": "spill_corrupt", "object_id": oid.binary()}
                    )
                except (ConnectionLost, RayTpuError):
                    pass
            except OSError:
                pass
        # Cross-node: the object's primary copy lives on another node —
        # pull it into the local store first, through the admission-
        # controlled pull manager (reference: raylet PullManager
        # fetching via the object directory).
        owner_node = reply.get("node_id")
        if (
            owner_node is not None
            and owner_node != self.node_id
            and not self.store.contains(oid)
        ):
            addr = reply.get("transfer_addr")

            def _relead(slow_addr: str):
                # Hedged pull: the current holder is below the
                # throughput floor — ask the directory again and move to
                # wherever the primary copy lives now. Same answer is
                # fine too (the fetcher reconnected already); returning
                # None just keeps the current lead.
                try:
                    fresh = self.request_reliable(
                        {"type": "get_object", "object_id": oid.binary()}
                    )
                except (ConnectionLost, RayTpuError):
                    return None
                new_addr = fresh.get("transfer_addr")
                if not new_addr or fresh.get("node_id") == self.node_id:
                    return None
                return new_addr

            # The caller's remaining get budget covers BOTH the
            # admission queue wait and the chunk fetch — a pull parked
            # behind a saturated budget must not fail a patient get.
            if not addr or not self._pull_manager.pull(
                oid, addr, size=reply.get("size") or 0, timeout=timeout,
                resolve=_relead,
            ):
                raise ObjectLostError(
                    f"object {oid.hex()} on node "
                    f"{owner_node.hex()[:8]} could not be fetched"
                )
        try:
            if packed:
                view = self.store.get_raw(oid)
                if view is None:
                    raise FileNotFoundError(oid.hex())
                try:
                    return bytes(view)
                finally:
                    del view
                    self.store.release_raw(oid)
            return self.store.get(oid)
        except FileNotFoundError:
            if not _retried:
                # The copy may have moved while this reply was in
                # flight (spilled to disk between directory lookup and
                # our read): ask the directory again once.
                fresh = self.request_reliable(
                    {"type": "get_object", "object_id": oid.binary()}
                )
                return self._materialize(fresh, oid, _retried=True,
                                         packed=packed, timeout=timeout)
            # Directory says READY but the data is gone (evicted).
            raise ObjectLostError(
                f"object {oid.hex()} missing from the local store (evicted)"
            ) from None

    def _materialize_or_reconstruct(
        self, reply: Dict[str, Any], ref: ObjectRef, remaining: Optional[float],
        packed: bool = False,
    ) -> Any:
        """Materialize; on loss, resubmit the producing task from lineage
        and retry (reference: ObjectRecoveryManager
        object_recovery_manager.h:41 + TaskManager::ResubmitTask
        task_manager.h:269 — the owner reconstructs)."""
        from ..exceptions import ObjectLostError

        oid = ref.id()
        for _ in range(3):
            try:
                return self._materialize(reply, oid, packed=packed,
                                         timeout=remaining)
            except ObjectLostError:
                spec = self._lineage.get(oid.binary())
                if spec is None:
                    raise
                self.send_reliable({"type": "submit_task", "spec": spec})
                reply = self.request_reliable(
                    {"type": "get_object", "object_id": oid.binary()},
                    timeout=remaining,
                )
        return self._materialize(reply, oid, packed=packed,
                                 timeout=remaining)

    def _resolve_direct_entry(
        self, ref: ObjectRef, entry, remaining: Optional[float]
    ) -> Dict[str, Any]:
        """Turn a _direct_results entry — (reply_future, index) or an
        already-resolved fields dict — into result fields, consuming it."""
        idb = ref.id().binary()
        if type(entry) is tuple:
            rfut, idx = entry
            try:
                reply = rfut.result(timeout=remaining)
            except (TimeoutError, concurrent.futures.TimeoutError):
                # Both: only Python 3.11 unified futures.TimeoutError
                # with the builtin.
                raise GetTimeoutError(f"get timed out on {ref}") from None
            except BaseException:
                # Connection lost: the failure callback rewrites the
                # entry with the outcome (resubmitted via GCS, failed,
                # actor died). Callbacks run just after waiters wake —
                # spin briefly for the rewrite.
                stop = time.monotonic() + 2.0
                while True:
                    e2 = self._direct_results.get(idb)
                    if isinstance(e2, dict):
                        entry = e2
                        break
                    if time.monotonic() > stop:
                        raise
                    time.sleep(0.001)
            else:
                # Consumed: later gets resolve through the GCS directory
                # (the worker's batched task_done seals results there).
                self._direct_results.pop(idb, None)
                if reply[2] is not None:
                    return {"status": "FAILED", "error": reply[2]}
                r = reply[3][idx]
                return {
                    "status": "READY",
                    "inline": r[0],
                    "segment": r[1],
                    "size": r[2],
                }
        # Sentinel dicts stay in place: the GCS never saw these tasks,
        # so a repeat get must find the sentinel again (popping it would
        # strand the second get on a directory entry that never seals).
        exc = entry.get("exception")
        if exc is not None:
            raise exc
        return entry

    def _gcs_get_fields(
        self, ref: ObjectRef, fut, deadline: Optional[float]
    ) -> Dict[str, Any]:
        """Resolve one GCS-routed get_object, riding out a head
        failover: a request parked on a connection that dies re-issues
        on the reconnected head (which re-parks it as a waiter; the
        recovery sweep answers LOST for entries nobody reclaims, so the
        get resolves into lineage reconstruction instead of wedging)."""
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(f"get timed out on {ref}")
            if fut is not None:
                try:
                    return fut.result(timeout=remaining)
                except (TimeoutError, concurrent.futures.TimeoutError):
                    # Both: only Python 3.11 unified futures.TimeoutError
                    # with the builtin.
                    raise GetTimeoutError(
                        f"get timed out on {ref}"
                    ) from None
                except ConnectionLost:
                    fut = None  # fall through to the failover retry
            if not self._await_failover():
                raise ConnectionLost("GCS connection lost during get")
            try:
                fut = self.conn.request_async(
                    {"type": "get_object", "object_id": ref.id().binary()}
                )
            except ConnectionLost:
                fut = None  # reconnected conn died again: loop

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None,
            packed: bool = False) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        self.flush_lazy()
        # Pipeline: fire every get_object request up front, then collect —
        # a batch of N costs one round-trip of latency, not N (reference:
        # the core worker batches plasma fetches in Get, core_worker.cc).
        rec = _events.get_recorder()
        futs = []
        for ref in refs:
            entry = self._direct_results.get(ref.id().binary())
            if entry is not None:
                # Direct call result: resolves on the direct socket,
                # no GCS round-trip.
                futs.append((ref, entry, _GET_DIRECT))
                continue
            # Local-first: a sealed same-host copy (node shm segment or
            # fallback segment) serves the get with zero RPCs — objects
            # are immutable once sealed, so no directory consult can
            # change the bytes. A copy that vanishes between this check
            # and the read (spilled/freed mid-flight) falls back to the
            # directory inside _materialize's retry.
            if self.store.contains(ref.id()):
                if rec.enabled:
                    rec.record(
                        _events.OBJECT, ref.id().hex(), "SHM_GET_LOCAL", {}
                    )
                futs.append((ref, {"status": "READY"}, _GET_LOCAL))
                continue
            try:
                fut = self.conn.request_async(
                    {"type": "get_object", "object_id": ref.id().binary()}
                )
            except ConnectionLost:
                # Head mid-restart: the collection loop re-issues
                # this one after the failover lands.
                fut = None
            futs.append((ref, fut, _GET_GCS))
        out = []
        for ref, ent, kind in futs:
            remaining = None
            direct = kind is _GET_DIRECT
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(f"get timed out on {ref}")
            if direct:
                fields = self._resolve_direct_entry(ref, ent, remaining)
            elif kind is _GET_LOCAL:
                fields = ent
            else:
                fields = self._gcs_get_fields(ref, ent, deadline)
            if direct and (
                fields.get("via_gcs")
                or (
                    fields.get("inline") is None
                    and fields.get("status") != "FAILED"
                    and not self.store.contains(ref.id())
                )
            ):
                # Resubmitted via the GCS, or a large result not in the
                # local store: the directory has (or will have) the
                # authoritative location.
                fields = self.request_reliable(
                    {"type": "get_object", "object_id": ref.id().binary()},
                    timeout=remaining,
                )
            out.append(
                self._materialize_or_reconstruct(
                    fields, ref, remaining, packed=packed
                )
            )
        return out

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int = 1,
        timeout: Optional[float] = None,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """Push-based wait: zero head round-trips in steady state.

        Each id is classified ONCE (across all wait calls on it): ids
        with an in-flight direct future get completion callbacks, the
        rest are covered by a single GCS subscription whose readiness
        arrives as ("RDY", ids) pushes. After that, every wait() call is
        a pure in-process partition against the ready set under a
        condvar — the drain-by-wait loop (reference ray_perf
        wait_multiple_refs) costs O(n) set lookups per call and no wire
        traffic (reference: raylet/wait_manager.h)."""
        refs = list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        self.flush_lazy()
        cond = self._wait_cond
        ready_set = self._wait_ready
        interest = self._wait_interest
        tracked = self._wait_tracked
        direct = self._direct_results
        to_subscribe: List[bytes] = []
        with cond:
            for r in refs:
                oid = r._id._bytes
                if oid in tracked:
                    # Already classified by an earlier wait() on this
                    # id: one set probe, no re-registration — the
                    # drain-by-wait loop registers each id exactly once.
                    continue
                self._wait_stats["registered"] += 1
                tracked.add(oid)
                entry = direct.get(oid)
                if entry is None:
                    # GCS-routed (task result, put, foreign ref):
                    # subscribe once; the head replies with the already-
                    # sealed subset and pushes the rest as they seal.
                    interest.add(oid)
                    if oid not in self._wait_subscribed:
                        self._wait_subscribed.add(oid)
                        to_subscribe.append(oid)
                elif type(entry) is tuple:
                    fut = entry[0]
                    if fut.done() and fut.exception() is None:
                        ready_set.add(oid)
                    else:
                        # In flight (or failing): _resolve_leased/
                        # _resolve_direct mark success, the conn-lost
                        # handlers re-classify through _wait_on_failure.
                        interest.add(oid)
                else:
                    # Sentinel dict: resolved locally, unless the task
                    # was resubmitted through the GCS.
                    if entry.get("via_gcs"):
                        interest.add(oid)
                        if oid not in self._wait_subscribed:
                            self._wait_subscribed.add(oid)
                            to_subscribe.append(oid)
                    else:
                        ready_set.add(oid)
        if to_subscribe:
            # Synchronous: the old check_ready always performed one
            # readiness round-trip even with timeout=0 — "check once"
            # callers must see objects already sealed at the GCS.
            reply = self.request_reliable(
                {"type": "wait_subscribe", "object_ids": to_subscribe}
            )
            already = reply.get("ready")
            if already:
                self._wait_mark(already, subscribed=True)
        while True:
            with cond:
                if self._head_conn_lost and not self.conn_failover_pending():
                    # Head gone for good. While a failover reconnect is
                    # still possible the wait parks instead: the replay
                    # re-subscribes every id and the condvar is notified
                    # on both reconnect success and final failure.
                    raise ConnectionLost("GCS connection lost during wait")
                if num_returns == 1:
                    # Drain-loop fast path: results complete roughly in
                    # submission order, so the first ready ref sits near
                    # the front — scan to it (no per-element appends) and
                    # build the rest as two C-level slices.
                    hit = -1
                    i = 0
                    for r in refs:
                        if r._id._bytes in ready_set:
                            hit = i
                            break
                        i += 1
                    if hit >= 0:
                        return [refs[hit]], refs[:hit] + refs[hit + 1:]
                elif _fp is not None:
                    part = _fp.wait_partition(refs, ready_set, num_returns)
                    if part is not None:
                        return part
                else:
                    part = self._wait_split(refs, num_returns)
                    if part is not None:
                        return part
                if deadline is None:
                    cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # Timed out: partial result — whatever is ready
                        # (fewer than num_returns), rest unchanged.
                        ready = [
                            r for r in refs if r._id._bytes in ready_set
                        ][:num_returns]
                        got = {id(r) for r in ready}
                        rest = [r for r in refs if id(r) not in got]
                        return ready, rest
                    cond.wait(remaining)

    def _wait_split(
        self, refs, num_returns: int
    ) -> Optional[Tuple[List[ObjectRef], List[ObjectRef]]]:
        """Partition refs against the ready set; None if not enough
        ready yet (caller holds the wait condvar)."""
        ready_set = self._wait_ready
        ready: List[ObjectRef] = []
        rest: List[ObjectRef] = []
        nready = 0
        for r in refs:
            if nready < num_returns and r._id._bytes in ready_set:
                ready.append(r)
                nready += 1
            else:
                rest.append(r)
        if nready < num_returns:
            return None
        return ready, rest

    def free(self, refs: Sequence[ObjectRef]):
        ids = [r.id().binary() for r in refs]
        with self._direct_lock:
            for oid in ids:
                self._direct_results.pop(oid, None)
        # Queued pulls for a freed object cancel now — their budget
        # share activates the next request instead of fetching data
        # nobody can reference (reference: pull cancellation on
        # ref-drop, pull_manager.h).
        for oid in ids:
            self._pull_manager.cancel(oid)
        self._wait_prune(ids)
        # Explicit free: drop tracker state so the instances still alive
        # can't emit retractions for entries already gone.
        self._tracker.forget(ids)
        self.send_reliable({"type": "free_objects", "object_ids": ids})
        # Drop our local copies (pulled replicas / remote-driver puts);
        # the GCS fan-out only reaches node daemons, not this process.
        for r in refs:
            try:
                self.store.delete(r.id())
            except Exception:  # noqa: BLE001
                pass

    # ---------------------------------------------------------------------- kv

    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True, ns: str = "") -> bool:
        r = self.conn.request(
            {"type": "kv_put", "key": key, "value": value, "overwrite": overwrite, "ns": ns}
        )
        return r.get("added", False)

    def kv_get(self, key: bytes, ns: str = "") -> Optional[bytes]:
        return self.conn.request({"type": "kv_get", "key": key, "ns": ns}).get("value")

    def kv_del(self, key: bytes, ns: str = "") -> bool:
        return self.conn.request({"type": "kv_del", "key": key, "ns": ns}).get("deleted", False)

    def kv_exists(self, key: bytes, ns: str = "") -> bool:
        return self.conn.request({"type": "kv_exists", "key": key, "ns": ns}).get("exists", False)

    def kv_keys(self, prefix: bytes = b"", ns: str = "") -> List[bytes]:
        return self.conn.request({"type": "kv_keys", "prefix": prefix, "ns": ns}).get("keys", [])

    # ------------------------------------------------------------------- misc

    def cluster_info(self) -> Dict[str, Any]:
        # A state read, not a bare request: health signals recorded in
        # this process's ring (a puller's PULL_RELEAD naming a slow
        # provider) must reach the head's scorer no later than the poll
        # that asks about node health — a bare request would leave a
        # driver-observed straggler invisible until some unrelated
        # state read happened to flush the ring.
        return self.state_read({"type": "cluster_info"})

    def request(self, msg: Dict[str, Any], timeout: Optional[float] = None) -> Dict[str, Any]:
        # Failover-transparent: control-plane requests (kv, actor
        # lookups, cluster info, state reads) park across a head
        # restart and re-issue, instead of surfacing ConnectionLost to
        # every API caller mid-failover.
        return self.request_reliable(msg, timeout=timeout)

    def flush_runtime_events(self) -> None:
        """Ship this process's flight-recorder ring to the head.

        Workers normally piggyback on the done-batcher flush and the
        head/driver shares a process with the aggregator; this covers
        the remaining case (remote drivers) and is harmless elsewhere
        (drain is destructive, so nothing double-ships)."""
        rec = _events.get_recorder()
        if not len(rec) and not rec.dropped:
            return
        msg = {"type": "event_batch", "source": rec.source}
        items, dropped = rec.attach(msg)
        try:
            self.conn.send(msg)
        except ConnectionLost:
            rec.count_lost(items, dropped)

    def state_read(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """A request that reads task/object state: flushes this
        process's own coalesced completion records first so the answer
        includes everything this process has already observed finish."""
        if self.pre_state_read_flush is not None:
            self.pre_state_read_flush()
        if self.role != "worker":
            # Workers flush via their done batcher (pre_state_read_flush
            # piggybacks the ring); non-worker clients ship here so a
            # remote driver's submission events reach the aggregator
            # before its own read is answered.
            self.flush_runtime_events()
        return self.request(msg)

    def send(self, msg: Dict[str, Any]) -> None:
        self.conn.send(msg)

    def close(self):
        # Mark the session over BEFORE closing the conn: the close
        # handler must not launch a reconnect against a head we are
        # deliberately leaving, and watchers parked on
        # head_permanently_lost must exit now.
        self._closing = True
        self.head_permanently_lost.set()
        self.conn.close()
        rp = getattr(self, "_raylet_peer", None)
        if rp is not None:
            rp.close()
        self._pull_manager.close()
        self._fetcher.close()
        self.store.close()


def object_segment_put(store: ObjectStore, oid: ObjectID, payload, buffers, size) -> str:
    return store.put_serialized(oid, payload, buffers, size)
